"""Workflow storage: filesystem-backed step results + workflow metadata.

Reference: python/ray/workflow/workflow_storage.py — keyed blobs under a
per-workflow directory; writes are atomic (tmp + rename) so a crash
mid-write never corrupts a completed-step record.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

import cloudpickle

DEFAULT_ROOT = os.environ.get("RAY_TPU_WORKFLOW_ROOT",
                              "/tmp/ray_tpu_workflows")


class WorkflowStorage:
    def __init__(self, workflow_id: str, root: Optional[str] = None):
        self.workflow_id = workflow_id
        self.root = os.path.join(root or DEFAULT_ROOT, workflow_id)
        os.makedirs(os.path.join(self.root, "steps"), exist_ok=True)

    # -- atomic write helpers ----------------------------------------------

    def _write(self, path: str, data: bytes):
        tmp = path + f".tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)

    # -- step results -------------------------------------------------------

    def _step_path(self, step_id: str) -> str:
        return os.path.join(self.root, "steps", f"{step_id}.pkl")

    def has_step(self, step_id: str) -> bool:
        return os.path.exists(self._step_path(step_id))

    def save_step(self, step_id: str, result: Any):
        self._write(self._step_path(step_id), cloudpickle.dumps(result))

    def load_step(self, step_id: str) -> Any:
        with open(self._step_path(step_id), "rb") as f:
            return cloudpickle.loads(f.read())

    # -- DAG + status --------------------------------------------------------

    def save_dag(self, dag_blob: bytes):
        self._write(os.path.join(self.root, "dag.pkl"), dag_blob)

    def load_dag(self) -> bytes:
        with open(os.path.join(self.root, "dag.pkl"), "rb") as f:
            return f.read()

    def save_status(self, status: str, **extra):
        data = {"status": status, "ts": time.time(),
                "workflow_id": self.workflow_id, **extra}
        self._write(os.path.join(self.root, "status.json"),
                    json.dumps(data, default=str).encode())

    def load_status(self) -> Dict[str, Any]:
        try:
            with open(os.path.join(self.root, "status.json")) as f:
                return json.load(f)
        except FileNotFoundError:
            return {"status": "NOT_FOUND", "workflow_id": self.workflow_id}

    def save_output(self, value: Any):
        self._write(os.path.join(self.root, "output.pkl"),
                    cloudpickle.dumps(value))

    def load_output(self) -> Any:
        with open(os.path.join(self.root, "output.pkl"), "rb") as f:
            return cloudpickle.loads(f.read())

    def has_output(self) -> bool:
        return os.path.exists(os.path.join(self.root, "output.pkl"))

    def delete(self):
        import shutil

        shutil.rmtree(self.root, ignore_errors=True)

    @staticmethod
    def list_workflows(root: Optional[str] = None) -> List[str]:
        base = root or DEFAULT_ROOT
        try:
            return sorted(
                d for d in os.listdir(base)
                if os.path.isdir(os.path.join(base, d)))
        except FileNotFoundError:
            return []
