"""ray_tpu.workflow: durable DAG execution.

Reference: python/ray/workflow/ (10.3k LoC — api.py:123 run, :243 resume,
workflow_executor.py, workflow_storage.py).  Each step's result is
persisted to storage before the next step runs; a crashed or cancelled
workflow resumes from its last completed step.  Checkpointing long
TPU-training DAGs composes with Train's orbax checkpoints: workflow steps
persist the *control* state (which stage finished), the model state lives
in the step's own checkpoint artifacts.
"""

from .api import (WorkflowCancellationError, WorkflowStatus, cancel,
                  continuation, delete, get_output, get_status, list_all,
                  options, resume, resume_all, run, run_async)

__all__ = ["WorkflowCancellationError", "WorkflowStatus", "cancel",
           "continuation", "delete", "get_output", "get_status",
           "list_all", "options", "resume", "resume_all", "run",
           "run_async"]
