"""workflow public API + executor.

Reference: python/ray/workflow/api.py (run :123, resume :243) +
workflow_executor.py.  The DAG is the same FunctionNode/ClassMethodNode
graph as ray_tpu.dag; step ids are deterministic over the DAG topology so
a resumed run maps steps onto their persisted results.
"""

from __future__ import annotations

import threading
import uuid
from typing import Any, Dict, List, Optional

import cloudpickle

import ray_tpu
from ray_tpu.dag.dag_node import DAGNode, FunctionNode, InputNode

from .storage import WorkflowStorage


class WorkflowStatus:
    RUNNING = "RUNNING"
    SUCCESSFUL = "SUCCESSFUL"
    FAILED = "FAILED"
    CANCELED = "CANCELED"
    NOT_FOUND = "NOT_FOUND"


# cancellation flags polled between steps (reference: api.py:712 cancel —
# the executor checks for a canceled marker before launching each task)
_canceled: set = set()
_canceled_lock = threading.Lock()


class WorkflowCancellationError(RuntimeError):
    pass


def options(*, max_retries: int = 0, catch_exceptions: bool = False
            ) -> Dict[str, Any]:
    """Per-step durability options, passed through fn.options(**...)
    (reference: workflow/api.py options — max_retries, catch_exceptions).

        result = my_step.options(**workflow.options(max_retries=3)).bind(x)
    """
    return {"_workflow_max_retries": max_retries,
            "_workflow_catch_exceptions": catch_exceptions}


class _Continuation:
    """Marker a step returns to hand control to a sub-DAG (reference:
    workflow.continuation — dynamic workflows)."""

    __slots__ = ("dag",)

    def __init__(self, dag: DAGNode):
        self.dag = dag


def continuation(dag: DAGNode) -> _Continuation:
    """Return from a step to continue the workflow with a new DAG; the
    sub-DAG's steps are checkpointed under the returning step's id."""
    return _Continuation(dag)


def _step_ids(dag: DAGNode) -> Dict[int, str]:
    """Deterministic step id per node: topo index + function name (stable
    across re-loads because topo_sort order is structural)."""
    ids = {}
    for i, node in enumerate(dag.topo_sort()):
        if isinstance(node, FunctionNode):
            name = getattr(node.remote_fn._fn, "__name__", "fn")
        elif isinstance(node, InputNode):
            name = "input"
        else:
            name = type(node).__name__
        ids[node._id] = f"{i:04d}_{name}"
    return ids


def _run_step(node: FunctionNode, resolved_args, resolved_kwargs) -> Any:
    """One step with per-step durability options (retries /
    catch_exceptions, reference: workflow step options)."""
    opts = getattr(node.remote_fn, "_opts", {}) or {}
    retries = int(opts.get("_workflow_max_retries", 0))
    catch = bool(opts.get("_workflow_catch_exceptions", False))
    attempt = 0
    while True:
        try:
            ref = node.remote_fn.remote(*resolved_args, **resolved_kwargs)
            result = ray_tpu.get(ref, timeout=3600.0)
            if isinstance(result, _Continuation):
                # hand the continuation straight to the executor — the
                # catch wrapper applies to step *values*, not control flow
                return result
            return (result, None) if catch else result
        except BaseException as e:
            if attempt < retries:
                attempt += 1
                continue
            if catch:
                return (None, e)
            raise


def _execute_dag(dag: DAGNode, storage: WorkflowStorage, args: tuple,
                 prefix: str = "") -> Any:
    """Topo-walk the DAG; completed steps load from storage, the rest run
    as tasks and persist before proceeding (at-least-once per step).
    Continuations recurse with the parent step id as checkpoint prefix."""
    ids = _step_ids(dag)
    values: Dict[int, Any] = {}
    for node in dag.topo_sort():
        sid = prefix + ids[node._id]
        if isinstance(node, InputNode):
            values[node._id] = args[0] if len(args) == 1 else args
            continue
        if storage.has_step(sid):
            values[node._id] = storage.load_step(sid)
            continue
        if not isinstance(node, FunctionNode):
            raise TypeError(
                f"workflows support function nodes (fn.bind) and InputNode,"
                f" got {node!r}")
        with _canceled_lock:
            was_canceled = storage.workflow_id in _canceled
        if not was_canceled:
            # cancel() from ANOTHER process persists CANCELED (the
            # reference's cancel is cluster-wide); polling only the
            # module-global set would silently lose it and let this run
            # overwrite the status with SUCCESSFUL on completion
            was_canceled = (storage.load_status()["status"]
                            == WorkflowStatus.CANCELED)
        if was_canceled:
            storage.save_status(WorkflowStatus.CANCELED, at_step=sid)
            e = WorkflowCancellationError(
                f"workflow {storage.workflow_id!r} canceled before {sid}")
            e._wf_recorded = True
            raise e
        try:
            resolved_args = [values[a._id] if isinstance(a, DAGNode) else a
                             for a in node.args]
            resolved_kwargs = {
                k: values[v._id] if isinstance(v, DAGNode) else v
                for k, v in node.kwargs.items()}
            result = _run_step(node, resolved_args, resolved_kwargs)
            if isinstance(result, _Continuation):
                # dynamic workflow: run the sub-DAG under this step's id
                # (flat ':' namespacing keeps step files in one directory)
                result = _execute_dag(result.dag, storage, args,
                                      prefix=f"{sid}:")
        except BaseException as e:
            # a failed continuation sub-step already recorded the precise
            # inner step id — don't overwrite it with the parent's
            if not getattr(e, "_wf_recorded", False):
                storage.save_status(WorkflowStatus.FAILED, failed_step=sid,
                                    error=f"{type(e).__name__}: {e}")
                try:
                    e._wf_recorded = True
                except Exception:
                    pass
            raise
        storage.save_step(sid, result)
        values[node._id] = result
    return values[dag._id]


def _execute_workflow(dag: DAGNode, storage: WorkflowStorage,
                      args: tuple) -> Any:
    storage.save_status(WorkflowStatus.RUNNING)
    out = _execute_dag(dag, storage, args)
    storage.save_output(out)
    storage.save_status(WorkflowStatus.SUCCESSFUL)
    return out


def run(dag: DAGNode, *args, workflow_id: Optional[str] = None,
        storage: Optional[str] = None) -> Any:
    """Run a DAG durably; returns its output (reference: api.py:123)."""
    workflow_id = workflow_id or f"workflow-{uuid.uuid4().hex[:10]}"
    st = WorkflowStorage(workflow_id, storage)
    st.save_dag(cloudpickle.dumps((dag, args)))
    return _execute_workflow(dag, st, args)


def run_async(dag: DAGNode, *args, workflow_id: Optional[str] = None,
              storage: Optional[str] = None):
    """Run in a background thread; returns (workflow_id, thread)."""
    workflow_id = workflow_id or f"workflow-{uuid.uuid4().hex[:10]}"
    t = threading.Thread(
        target=lambda: _swallow(run, dag, *args, workflow_id=workflow_id,
                                storage=storage),
        name=f"workflow-{workflow_id}", daemon=True)
    t.start()
    return workflow_id, t


def _swallow(fn, *a, **kw):
    try:
        fn(*a, **kw)
    except BaseException:
        pass  # status already persisted as FAILED


def resume(workflow_id: str, storage: Optional[str] = None) -> Any:
    """Re-run a workflow from its last completed step (reference:
    api.py:243)."""
    st = WorkflowStorage(workflow_id, storage)
    if st.has_output():
        return st.load_output()
    status = st.load_status()
    if status["status"] == WorkflowStatus.NOT_FOUND:
        raise ValueError(f"no workflow {workflow_id!r}")
    # resuming un-cancels: clear both the in-process flag and (via the
    # RUNNING transition in _execute_workflow) the persisted CANCELED
    with _canceled_lock:
        _canceled.discard(workflow_id)
    dag, args = cloudpickle.loads(st.load_dag())
    return _execute_workflow(dag, st, args)


def get_status(workflow_id: str, storage: Optional[str] = None) -> str:
    return WorkflowStorage(workflow_id, storage).load_status()["status"]


def get_output(workflow_id: str, storage: Optional[str] = None) -> Any:
    st = WorkflowStorage(workflow_id, storage)
    if not st.has_output():
        raise ValueError(f"workflow {workflow_id!r} has no output "
                         f"(status={st.load_status()['status']})")
    return st.load_output()


def list_all(storage: Optional[str] = None) -> List[tuple]:
    out = []
    for wid in WorkflowStorage.list_workflows(storage):
        out.append((wid, WorkflowStorage(wid, storage)
                    .load_status()["status"]))
    return out


def delete(workflow_id: str, storage: Optional[str] = None):
    WorkflowStorage(workflow_id, storage).delete()


def cancel(workflow_id: str, storage: Optional[str] = None) -> None:
    """Stop a running workflow between steps (reference: api.py:712).
    The executor checks the flag before each step; completed step
    results stay persisted, so a later resume() continues from them."""
    with _canceled_lock:
        _canceled.add(workflow_id)
    st = WorkflowStorage(workflow_id, storage)
    if st.load_status()["status"] == WorkflowStatus.RUNNING:
        st.save_status(WorkflowStatus.CANCELED)


def resume_all(storage: Optional[str] = None,
               include_failed: bool = True) -> List[tuple]:
    """Resume every resumable workflow (reference: api.py:502).  Returns
    [(workflow_id, output), ...] for those that completed."""
    out = []
    resumable = (WorkflowStatus.RUNNING, WorkflowStatus.CANCELED,
                 WorkflowStatus.FAILED)
    for wid, status in list_all(storage):
        if status == WorkflowStatus.SUCCESSFUL:
            continue
        if status in resumable and (include_failed
                                    or status != WorkflowStatus.FAILED):
            with _canceled_lock:
                _canceled.discard(wid)
            try:
                out.append((wid, resume(wid, storage)))
            except Exception:
                pass  # stays FAILED; caller inspects list_all()
    return out
