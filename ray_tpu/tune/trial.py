"""Trial: one hyperparameter configuration's run state.

Mirrors the reference (reference: python/ray/tune/experiment/trial.py):
status machine PENDING -> RUNNING -> {TERMINATED, ERROR, PAUSED}, last
result, checkpoint, and serialization for experiment resume.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional

PENDING = "PENDING"
RUNNING = "RUNNING"
PAUSED = "PAUSED"
TERMINATED = "TERMINATED"
ERROR = "ERROR"


class Trial:
    def __init__(self, trial_id: str, config: Dict[str, Any],
                 experiment_dir: str, experiment_name: str):
        self.trial_id = trial_id
        self.config = config
        self.experiment_name = experiment_name
        self.status = PENDING
        self.last_result: Optional[Dict[str, Any]] = None
        self.results: List[Dict[str, Any]] = []
        self.checkpoint_path: Optional[str] = None
        self.error_msg: Optional[str] = None
        self.iteration = 0
        self.num_failures = 0
        self.start_time = time.time()
        from ray_tpu.train import storage

        self.trial_dir = storage.join(experiment_dir, trial_id)
        storage.makedirs(self.trial_dir)

    @property
    def is_finished(self) -> bool:
        return self.status in (TERMINATED, ERROR)

    def metric(self, name: str, default: float = float("nan")) -> float:
        if not self.last_result:
            return default
        v = self.last_result.get(name, default)
        try:
            return float(v)
        except (TypeError, ValueError):
            return default

    def to_json(self) -> Dict[str, Any]:
        return {
            "trial_id": self.trial_id,
            "config": self.config,
            "status": self.status,
            "last_result": self.last_result,
            "checkpoint_path": self.checkpoint_path,
            "error_msg": self.error_msg,
            "iteration": self.iteration,
            "num_failures": self.num_failures,
        }

    @classmethod
    def from_json(cls, d: Dict[str, Any], experiment_dir: str,
                  experiment_name: str) -> "Trial":
        t = cls(d["trial_id"], d["config"], experiment_dir, experiment_name)
        t.status = d["status"]
        t.last_result = d.get("last_result")
        t.checkpoint_path = d.get("checkpoint_path")
        t.error_msg = d.get("error_msg")
        t.iteration = d.get("iteration", 0)
        t.num_failures = d.get("num_failures", 0)
        return t

    def __repr__(self):
        return f"Trial({self.trial_id}, {self.status})"
