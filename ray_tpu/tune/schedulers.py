"""Trial schedulers: early stopping and population-based training.

Mirrors the reference (reference: python/ray/tune/schedulers/ —
trial_scheduler.py TrialScheduler, async_hyperband.py ASHA,
median_stopping_rule.py, pbt.py PopulationBasedTraining): the controller
feeds every reported result to the scheduler, which answers
CONTINUE / PAUSE / STOP; PBT additionally mutates paused trials' configs
and restarts them from a donor's checkpoint (exploit + explore).
"""

from __future__ import annotations

import logging
import math
import random
from typing import Any, Callable, Dict, List, Optional

from .trial import Trial

logger = logging.getLogger(__name__)

CONTINUE = "CONTINUE"
PAUSE = "PAUSE"
STOP = "STOP"


class TrialScheduler:
    def __init__(self, metric: Optional[str] = None, mode: str = "max"):
        if mode not in ("max", "min"):
            raise ValueError("mode must be 'max' or 'min'")
        self.metric = metric
        self.mode = mode

    def _score(self, result: Dict[str, Any]) -> float:
        v = float(result[self.metric])
        return v if self.mode == "max" else -v

    def on_trial_add(self, trial: Trial):
        pass

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        return CONTINUE

    def on_trial_complete(self, trial: Trial):
        pass

    def on_trial_error(self, trial: Trial):
        pass

    def choose_trial_to_run(self, trials: List[Trial]) -> Optional[Trial]:
        """Default: any PENDING trial (FIFO)."""
        from .trial import PENDING

        for t in trials:
            if t.status == PENDING:
                return t
        return None

    def trials_to_stop(self):
        """Trial ids the scheduler decided to cull outside their own
        report (drained by the controller each step)."""
        return ()


class FIFOScheduler(TrialScheduler):
    pass


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA (reference: tune/schedulers/async_hyperband.py): rungs at
    grace_period * reduction_factor^k; a trial reaching a rung is stopped
    unless it is in the top 1/reduction_factor of results recorded there."""

    def __init__(self, metric: Optional[str] = None, mode: str = "max",
                 time_attr: str = "training_iteration",
                 grace_period: int = 1, reduction_factor: float = 3,
                 max_t: int = 100):
        super().__init__(metric, mode)
        self.time_attr = time_attr
        self.grace_period = grace_period
        self.rf = reduction_factor
        self.max_t = max_t
        # rung levels: grace, grace*rf, grace*rf^2, ... < max_t
        self.rungs: List[int] = []
        r = grace_period
        while r < max_t:
            self.rungs.append(int(r))
            r *= reduction_factor
        self.rung_results: Dict[int, List[float]] = {r: [] for r in self.rungs}
        self._recorded: Dict[str, set] = {}  # trial_id -> rungs recorded

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        t = result.get(self.time_attr, 0)
        if t >= self.max_t:
            return STOP
        score = self._score(result)
        decision = CONTINUE
        seen = self._recorded.setdefault(trial.trial_id, set())
        for rung in reversed(self.rungs):
            if t < rung or rung in seen:
                continue
            # each trial contributes to a rung exactly once
            seen.add(rung)
            recorded = self.rung_results[rung]
            recorded.append(score)
            if len(recorded) >= self.rf:
                cutoff_idx = max(0, int(len(recorded) / self.rf) - 1)
                cutoff = sorted(recorded, reverse=True)[cutoff_idx]
                if score < cutoff:
                    decision = STOP
            break  # only the highest new rung reached this round
        return decision


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose best result so far is worse than the median of
    other trials' running averages at the same step (reference:
    tune/schedulers/median_stopping_rule.py)."""

    def __init__(self, metric: Optional[str] = None, mode: str = "max",
                 time_attr: str = "training_iteration",
                 grace_period: int = 1, min_samples_required: int = 3):
        super().__init__(metric, mode)
        self.time_attr = time_attr
        self.grace_period = grace_period
        self.min_samples = min_samples_required
        self._trial_scores: Dict[str, List[float]] = {}

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        t = result.get(self.time_attr, 0)
        score = self._score(result)
        self._trial_scores.setdefault(trial.trial_id, []).append(score)
        if t < self.grace_period:
            return CONTINUE
        others = [sum(v) / len(v) for k, v in self._trial_scores.items()
                  if k != trial.trial_id and v]
        if len(others) < self.min_samples:
            return CONTINUE
        others.sort()
        median = others[len(others) // 2]
        best = max(self._trial_scores[trial.trial_id])
        return STOP if best < median else CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT (reference: tune/schedulers/pbt.py): every
    perturbation_interval, bottom-quantile trials PAUSE; the controller
    clones the config of a top-quantile donor, perturbs it via
    hyperparam_mutations, and restarts the trial from the donor's
    checkpoint."""

    def __init__(self, metric: Optional[str] = None, mode: str = "max",
                 time_attr: str = "training_iteration",
                 perturbation_interval: int = 5,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 seed: Optional[int] = None):
        super().__init__(metric, mode)
        self.time_attr = time_attr
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.resample_p = resample_probability
        self.rng = random.Random(seed)
        self._last_perturb: Dict[str, int] = {}
        # controller reads + clears this: trial_id -> (new_config, donor)
        self.pending_exploits: Dict[str, tuple] = {}

    def _explore(self, config: Dict[str, Any]) -> Dict[str, Any]:
        new = dict(config)
        for k, domain in self.mutations.items():
            if isinstance(domain, list):
                if self.rng.random() < self.resample_p or k not in new:
                    new[k] = self.rng.choice(domain)
                else:
                    i = domain.index(new[k]) if new[k] in domain else 0
                    j = min(max(i + self.rng.choice([-1, 1]), 0),
                            len(domain) - 1)
                    new[k] = domain[j]
            elif callable(domain):
                if self.rng.random() < self.resample_p or k not in new:
                    new[k] = domain()
                else:
                    new[k] = new[k] * self.rng.choice([0.8, 1.2])
            else:
                from .search import Domain

                if isinstance(domain, Domain):
                    if self.rng.random() < self.resample_p or k not in new:
                        new[k] = domain.sample(self.rng)
                    else:
                        new[k] = new[k] * self.rng.choice([0.8, 1.2])
        return new

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        t = result.get(self.time_attr, 0)
        last = self._last_perturb.get(trial.trial_id, 0)
        if t - last < self.interval:
            return CONTINUE
        self._last_perturb[trial.trial_id] = t
        # rank current population by last seen score
        peers = [(tid, scores[-1])
                 for tid, scores in self._population().items() if scores]
        self._record(trial.trial_id, self._score(result))
        peers = [(tid, s) for tid, s in peers if tid != trial.trial_id]
        peers.append((trial.trial_id, self._score(result)))
        if len(peers) < 2:
            return CONTINUE
        peers.sort(key=lambda p: p[1], reverse=True)
        n = len(peers)
        k = max(1, int(math.ceil(n * self.quantile)))
        top = [tid for tid, _ in peers[:k]]
        bottom = [tid for tid, _ in peers[-k:]]
        if trial.trial_id in bottom and trial.trial_id not in top:
            donor_id = self.rng.choice(top)
            self.pending_exploits[trial.trial_id] = (donor_id,)
            return PAUSE
        return CONTINUE

    _scores: Dict[str, List[float]] = None

    def _population(self) -> Dict[str, List[float]]:
        if self._scores is None:
            self._scores = {}
        return self._scores

    def _record(self, tid: str, score: float):
        self._population().setdefault(tid, []).append(score)

    def make_exploit_config(self, donor: Trial) -> Dict[str, Any]:
        return self._explore(donor.config)


class HyperBandScheduler(TrialScheduler):
    """Synchronous HyperBand (reference: tune/schedulers/hyperband.py).

    Trials are assigned round-robin to brackets; each bracket runs
    successive halving: at every rung milestone the cohort synchronizes
    (arrivals wait as PENDING but are not chosen to run), then the top
    1/eta advance and the rest are culled via `trials_to_stop`, which the
    controller drains.
    """

    def __init__(self, metric: Optional[str] = None, mode: str = "max",
                 time_attr: str = "training_iteration",
                 max_t: int = 81, eta: int = 3):
        super().__init__(metric, mode)
        self.time_attr = time_attr
        self.max_t = max_t
        self.eta = eta
        # bracket s: initial budget r0 = max_t / eta^s
        s_max = int(math.floor(math.log(max_t, eta)))
        self.brackets: List[Dict[str, Any]] = []
        for s in range(s_max, -1, -1):
            r0 = max(1, int(max_t / (eta ** s)))
            rungs = []
            r = r0
            while r <= max_t:
                rungs.append(r)
                r *= eta
            self.brackets.append({"s": s, "rungs": rungs,
                                  "trials": [],      # trial ids in bracket
                                  # rung idx -> {trial_id: score}
                                  "scores": {},
                                  # trial_id -> next allowed rung idx
                                  "at_rung": {}})
        self._bracket_of: Dict[str, int] = {}
        self._next_bracket = 0
        self._stop: set = set()
        self._done: set = set()

    def on_trial_add(self, trial: Trial):
        if trial.trial_id in self._bracket_of:
            return
        b = self._next_bracket % len(self.brackets)
        self._next_bracket += 1
        self._bracket_of[trial.trial_id] = b
        br = self.brackets[b]
        br["trials"].append(trial.trial_id)
        br["at_rung"][trial.trial_id] = 0

    def _ensure_added(self, trial: Trial):
        """Restored experiments bypass on_trial_add (resumed trials are
        handed straight to the controller) — register lazily."""
        if trial.trial_id not in self._bracket_of:
            self.on_trial_add(trial)

    def trials_to_stop(self):
        out, self._stop = self._stop, set()
        return out

    def _live_cohort(self, br, rung_idx) -> List[str]:
        """Trials expected to report at this rung (not culled/errored)."""
        return [tid for tid in br["trials"]
                if br["at_rung"].get(tid, -1) == rung_idx
                and tid not in self._done]

    def _maybe_promote(self, br, rung_idx):
        """If the rung's live cohort has fully reported, promote the top
        1/eta and cull the rest.  Called on every report AND whenever a
        cohort member leaves (complete/error) — otherwise the waiters
        strand as PENDING forever."""
        reported = br["scores"].get(rung_idx, {})
        cohort = self._live_cohort(br, rung_idx)
        waiting = [tid for tid in cohort if tid not in reported]
        if waiting or not reported:
            return
        ranked = sorted(reported.items(), key=lambda kv: kv[1], reverse=True)
        keep = max(1, len(ranked) // self.eta)
        if rung_idx + 1 >= len(br["rungs"]):
            keep = 0  # last rung: everyone stops
        for i, (mid, _) in enumerate(ranked):
            if mid in self._done:
                continue
            if i < keep:
                br["at_rung"][mid] = rung_idx + 1
            else:
                self._done.add(mid)
                self._stop.add(mid)

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        self._ensure_added(trial)
        tid = trial.trial_id
        br = self.brackets[self._bracket_of[tid]]
        rung_idx = br["at_rung"].get(tid, 0)
        if rung_idx >= len(br["rungs"]):
            return STOP  # finished the last rung
        t = result.get(self.time_attr, 0)
        if t < br["rungs"][rung_idx]:
            return CONTINUE
        # reached the milestone: record + synchronize
        br["scores"].setdefault(rung_idx, {})[tid] = self._score(result)
        self._maybe_promote(br, rung_idx)
        if tid in self._done:
            self._stop.discard(tid)  # we answer this one directly
            return STOP
        if br["at_rung"].get(tid, 0) > rung_idx:
            return CONTINUE  # promoted immediately (cohort was complete)
        return PAUSE  # wait for the cohort (stays PENDING, not chosen)

    def _on_trial_left(self, trial: Trial):
        """Completion or error removes the trial from its cohorts; the
        rungs it was gating may now be promotable."""
        tid = trial.trial_id
        self._done.add(tid)
        b = self._bracket_of.get(tid)
        if b is None:
            return
        br = self.brackets[b]
        for rung_idx in range(len(br["rungs"])):
            self._maybe_promote(br, rung_idx)

    def on_trial_error(self, trial: Trial):
        self._on_trial_left(trial)

    def on_trial_complete(self, trial: Trial):
        self._on_trial_left(trial)

    def choose_trial_to_run(self, trials: List[Trial]) -> Optional[Trial]:
        from .trial import PENDING

        for t in trials:
            if t.status != PENDING:
                continue
            self._ensure_added(t)
            tid = t.trial_id
            if tid in self._done or tid in self._stop:
                continue
            br = self.brackets[self._bracket_of[tid]]
            rung_idx = br["at_rung"].get(tid, 0)
            if rung_idx >= len(br["rungs"]):
                continue
            # runnable iff its rung is not waiting on a full lower cohort
            if br["scores"].get(rung_idx, {}).get(tid) is None:
                return t
        return None


class PB2(PopulationBasedTraining):
    """PBT with a GP-UCB exploration step (reference:
    tune/schedulers/pb2.py) — instead of random perturbation, fit a
    Gaussian process mapping hyperparameters -> score improvement and pick
    the UCB-maximizing candidate inside `hyperparam_bounds`.  Numpy-only
    (the reference depends on GPy; same math, RBF kernel)."""

    def __init__(self, metric: Optional[str] = None, mode: str = "max",
                 time_attr: str = "training_iteration",
                 perturbation_interval: int = 5,
                 hyperparam_bounds: Optional[Dict[str, tuple]] = None,
                 quantile_fraction: float = 0.25,
                 seed: Optional[int] = None):
        super().__init__(metric, mode, time_attr, perturbation_interval,
                         hyperparam_mutations={}, 
                         quantile_fraction=quantile_fraction, seed=seed)
        self.bounds = hyperparam_bounds or {}
        #: (hyperparam vector, score delta) observations
        self._gp_data: List[tuple] = []
        self._last_score: Dict[str, float] = {}

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        t = result.get(self.time_attr, 0)
        last = self._last_perturb.get(trial.trial_id, 0)
        if t - last >= self.interval and self.bounds:
            score = self._score(result)
            prev = self._last_score.get(trial.trial_id)
            if prev is not None:
                x = [float(trial.config.get(k, (lo + hi) / 2))
                     for k, (lo, hi) in sorted(self.bounds.items())]
                self._gp_data.append((x, score - prev))
            self._last_score[trial.trial_id] = score
        return super().on_trial_result(trial, result)

    def _explore(self, config: Dict[str, Any]) -> Dict[str, Any]:
        import numpy as np

        new = dict(config)
        if not self.bounds:
            return new
        keys = sorted(self.bounds.keys())
        lo = np.asarray([self.bounds[k][0] for k in keys], float)
        hi = np.asarray([self.bounds[k][1] for k in keys], float)
        if len(self._gp_data) < 4:
            for k, l, h in zip(keys, lo, hi):
                new[k] = float(self.rng.uniform(l, h))
            return new
        X = np.asarray([x for x, _ in self._gp_data], float)
        y = np.asarray([d for _, d in self._gp_data], float)
        y = (y - y.mean()) / (y.std() + 1e-8)
        # normalize inputs to [0,1]
        Xn = (X - lo) / np.maximum(hi - lo, 1e-12)
        ls, sn = 0.3, 1e-3

        def k(a, b):
            d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
            return np.exp(-0.5 * d2 / (ls * ls))

        K = k(Xn, Xn) + sn * np.eye(len(Xn))
        Kinv_y = np.linalg.solve(K, y)
        cand = np.random.RandomState(
            self.rng.randrange(1 << 31)).rand(64, len(keys))
        Kc = k(cand, Xn)
        mu = Kc @ Kinv_y
        var = 1.0 - np.einsum("ij,jk,ik->i", Kc, np.linalg.inv(K), Kc)
        ucb = mu + 1.0 * np.sqrt(np.maximum(var, 1e-12))
        best = cand[int(np.argmax(ucb))]
        for k_, v in zip(keys, lo + best * (hi - lo)):
            new[k_] = float(v)
        return new
