"""Trial schedulers: early stopping and population-based training.

Mirrors the reference (reference: python/ray/tune/schedulers/ —
trial_scheduler.py TrialScheduler, async_hyperband.py ASHA,
median_stopping_rule.py, pbt.py PopulationBasedTraining): the controller
feeds every reported result to the scheduler, which answers
CONTINUE / PAUSE / STOP; PBT additionally mutates paused trials' configs
and restarts them from a donor's checkpoint (exploit + explore).
"""

from __future__ import annotations

import logging
import math
import random
from typing import Any, Callable, Dict, List, Optional

from .trial import Trial

logger = logging.getLogger(__name__)

CONTINUE = "CONTINUE"
PAUSE = "PAUSE"
STOP = "STOP"


class TrialScheduler:
    def __init__(self, metric: Optional[str] = None, mode: str = "max"):
        if mode not in ("max", "min"):
            raise ValueError("mode must be 'max' or 'min'")
        self.metric = metric
        self.mode = mode

    def _score(self, result: Dict[str, Any]) -> float:
        v = float(result[self.metric])
        return v if self.mode == "max" else -v

    def on_trial_add(self, trial: Trial):
        pass

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        return CONTINUE

    def on_trial_complete(self, trial: Trial):
        pass

    def on_trial_error(self, trial: Trial):
        pass

    def choose_trial_to_run(self, trials: List[Trial]) -> Optional[Trial]:
        """Default: any PENDING trial (FIFO)."""
        from .trial import PENDING

        for t in trials:
            if t.status == PENDING:
                return t
        return None


class FIFOScheduler(TrialScheduler):
    pass


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA (reference: tune/schedulers/async_hyperband.py): rungs at
    grace_period * reduction_factor^k; a trial reaching a rung is stopped
    unless it is in the top 1/reduction_factor of results recorded there."""

    def __init__(self, metric: Optional[str] = None, mode: str = "max",
                 time_attr: str = "training_iteration",
                 grace_period: int = 1, reduction_factor: float = 3,
                 max_t: int = 100):
        super().__init__(metric, mode)
        self.time_attr = time_attr
        self.grace_period = grace_period
        self.rf = reduction_factor
        self.max_t = max_t
        # rung levels: grace, grace*rf, grace*rf^2, ... < max_t
        self.rungs: List[int] = []
        r = grace_period
        while r < max_t:
            self.rungs.append(int(r))
            r *= reduction_factor
        self.rung_results: Dict[int, List[float]] = {r: [] for r in self.rungs}
        self._recorded: Dict[str, set] = {}  # trial_id -> rungs recorded

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        t = result.get(self.time_attr, 0)
        if t >= self.max_t:
            return STOP
        score = self._score(result)
        decision = CONTINUE
        seen = self._recorded.setdefault(trial.trial_id, set())
        for rung in reversed(self.rungs):
            if t < rung or rung in seen:
                continue
            # each trial contributes to a rung exactly once
            seen.add(rung)
            recorded = self.rung_results[rung]
            recorded.append(score)
            if len(recorded) >= self.rf:
                cutoff_idx = max(0, int(len(recorded) / self.rf) - 1)
                cutoff = sorted(recorded, reverse=True)[cutoff_idx]
                if score < cutoff:
                    decision = STOP
            break  # only the highest new rung reached this round
        return decision


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose best result so far is worse than the median of
    other trials' running averages at the same step (reference:
    tune/schedulers/median_stopping_rule.py)."""

    def __init__(self, metric: Optional[str] = None, mode: str = "max",
                 time_attr: str = "training_iteration",
                 grace_period: int = 1, min_samples_required: int = 3):
        super().__init__(metric, mode)
        self.time_attr = time_attr
        self.grace_period = grace_period
        self.min_samples = min_samples_required
        self._trial_scores: Dict[str, List[float]] = {}

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        t = result.get(self.time_attr, 0)
        score = self._score(result)
        self._trial_scores.setdefault(trial.trial_id, []).append(score)
        if t < self.grace_period:
            return CONTINUE
        others = [sum(v) / len(v) for k, v in self._trial_scores.items()
                  if k != trial.trial_id and v]
        if len(others) < self.min_samples:
            return CONTINUE
        others.sort()
        median = others[len(others) // 2]
        best = max(self._trial_scores[trial.trial_id])
        return STOP if best < median else CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT (reference: tune/schedulers/pbt.py): every
    perturbation_interval, bottom-quantile trials PAUSE; the controller
    clones the config of a top-quantile donor, perturbs it via
    hyperparam_mutations, and restarts the trial from the donor's
    checkpoint."""

    def __init__(self, metric: Optional[str] = None, mode: str = "max",
                 time_attr: str = "training_iteration",
                 perturbation_interval: int = 5,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 seed: Optional[int] = None):
        super().__init__(metric, mode)
        self.time_attr = time_attr
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.resample_p = resample_probability
        self.rng = random.Random(seed)
        self._last_perturb: Dict[str, int] = {}
        # controller reads + clears this: trial_id -> (new_config, donor)
        self.pending_exploits: Dict[str, tuple] = {}

    def _explore(self, config: Dict[str, Any]) -> Dict[str, Any]:
        new = dict(config)
        for k, domain in self.mutations.items():
            if isinstance(domain, list):
                if self.rng.random() < self.resample_p or k not in new:
                    new[k] = self.rng.choice(domain)
                else:
                    i = domain.index(new[k]) if new[k] in domain else 0
                    j = min(max(i + self.rng.choice([-1, 1]), 0),
                            len(domain) - 1)
                    new[k] = domain[j]
            elif callable(domain):
                if self.rng.random() < self.resample_p or k not in new:
                    new[k] = domain()
                else:
                    new[k] = new[k] * self.rng.choice([0.8, 1.2])
            else:
                from .search import Domain

                if isinstance(domain, Domain):
                    if self.rng.random() < self.resample_p or k not in new:
                        new[k] = domain.sample(self.rng)
                    else:
                        new[k] = new[k] * self.rng.choice([0.8, 1.2])
        return new

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        t = result.get(self.time_attr, 0)
        last = self._last_perturb.get(trial.trial_id, 0)
        if t - last < self.interval:
            return CONTINUE
        self._last_perturb[trial.trial_id] = t
        # rank current population by last seen score
        peers = [(tid, scores[-1])
                 for tid, scores in self._population().items() if scores]
        self._record(trial.trial_id, self._score(result))
        peers = [(tid, s) for tid, s in peers if tid != trial.trial_id]
        peers.append((trial.trial_id, self._score(result)))
        if len(peers) < 2:
            return CONTINUE
        peers.sort(key=lambda p: p[1], reverse=True)
        n = len(peers)
        k = max(1, int(math.ceil(n * self.quantile)))
        top = [tid for tid, _ in peers[:k]]
        bottom = [tid for tid, _ in peers[-k:]]
        if trial.trial_id in bottom and trial.trial_id not in top:
            donor_id = self.rng.choice(top)
            self.pending_exploits[trial.trial_id] = (donor_id,)
            return PAUSE
        return CONTINUE

    _scores: Dict[str, List[float]] = None

    def _population(self) -> Dict[str, List[float]]:
        if self._scores is None:
            self._scores = {}
        return self._scores

    def _record(self, tid: str, score: float):
        self._population().setdefault(tid, []).append(score)

    def make_exploit_config(self, donor: Trial) -> Dict[str, Any]:
        return self._explore(donor.config)
