"""Tuner: the user-facing experiment API.

Mirrors the reference (reference: python/ray/tune/tuner.py:44 Tuner, fit
:344; result_grid.py ResultGrid): Tuner(trainable, param_space=...,
tune_config=TuneConfig(...), run_config=RunConfig(...)).fit() ->
ResultGrid.  Trainers plug in via JaxTrainer.as_trainable(), matching the
reference where BaseTrainer.fit constructs a single-trial Tuner
(train/base_trainer.py:567-623).
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import RunConfig
from ray_tpu.train.result import Result

from .schedulers import TrialScheduler
from .search import BasicVariantGenerator, Searcher
from .trial import ERROR, TERMINATED, Trial
from .tune_controller import Callback, TuneController


@dataclass
class TuneConfig:
    """(reference: tune/tune_config.py)"""

    metric: Optional[str] = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: int = 0
    search_alg: Optional[Searcher] = None
    scheduler: Optional[TrialScheduler] = None
    trial_resources: Optional[Dict[str, float]] = None
    seed: Optional[int] = None


class ResultGrid:
    """(reference: tune/result_grid.py)"""

    def __init__(self, results: List[Result], trials: List[Trial]):
        self._results = results
        self._trials = trials

    def __len__(self):
        return len(self._results)

    def __getitem__(self, i) -> Result:
        return self._results[i]

    @property
    def errors(self) -> List[BaseException]:
        return [r.error for r in self._results if r.error is not None]

    @property
    def num_errors(self) -> int:
        """reference: tune/result_grid.py ResultGrid.num_errors"""
        return len(self.errors)

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> Result:
        metric = metric or getattr(self, "_default_metric", None)
        mode = mode or getattr(self, "_default_mode", "max")
        if metric is None:
            raise ValueError("metric required (none set in TuneConfig)")
        scored = [r for r in self._results
                  if r.metrics and metric in r.metrics]
        if not scored:
            raise RuntimeError(f"no trial reported metric {metric!r}")
        key = lambda r: float(r.metrics[metric])  # noqa: E731
        return max(scored, key=key) if mode == "max" else min(scored, key=key)

    def get_dataframe(self):
        import pandas as pd

        return pd.DataFrame([r.metrics or {} for r in self._results])


class Tuner:
    def __init__(self, trainable: Callable, *,
                 param_space: Optional[Dict[str, Any]] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 _resumed_trials: Optional[List[Trial]] = None,
                 _experiment_dir: Optional[str] = None):
        # trainer instances are adapted automatically (reference:
        # base_trainer.py wraps itself into a trainable the same way)
        from ray_tpu.train.trainer import JaxTrainer

        if isinstance(trainable, JaxTrainer):
            if run_config is None:
                run_config = trainable.run_config
            trainable = trainable.as_trainable()
        self._trainable = trainable
        self._param_space = param_space or {}
        self._tune_config = tune_config or TuneConfig()
        self._run_config = run_config or RunConfig()
        self._resumed_trials = _resumed_trials
        self._experiment_dir = _experiment_dir

    def fit(self) -> ResultGrid:
        from ray_tpu._private.usage_stats import record_library_usage

        record_library_usage("tune")
        cfg = self._tune_config
        from ray_tpu.train import storage

        name = self._run_config.name or f"tune_{int(time.time())}"
        exp_dir = (self._experiment_dir
                   or storage.join(self._run_config.resolved_storage_path(),
                                   name))
        storage.makedirs(exp_dir)
        if self._resumed_trials is not None:
            # resumed run: continue the ORIGINAL searcher if its pickled
            # state was saved (reference: Searcher.save/restore — an
            # ask/tell optimizer picks up with everything it learned);
            # otherwise rerun the saved trials only
            searcher = TuneController.load_searcher(exp_dir)
            if searcher is None:
                searcher = BasicVariantGenerator(
                    {}, num_samples=0, metric=cfg.metric, mode=cfg.mode)
        else:
            searcher = cfg.search_alg or BasicVariantGenerator(
                self._param_space, num_samples=cfg.num_samples, seed=cfg.seed,
                metric=cfg.metric, mode=cfg.mode)
        # user-supplied search_alg inherits unset metric/mode from the
        # TuneConfig (same backfill the scheduler gets below) — an unset
        # metric silently drops every observation, an unset mode
        # silently optimizes the wrong direction.  Walk .searcher
        # chains (ConcurrencyLimiter/Repeater delegate completion to
        # the INNER searcher); explicit inner settings always win.
        # Independent gates: TuneConfig(mode=...) must apply even when
        # the searcher carries its own metric.
        s = searcher
        while s is not None:
            if cfg.metric and getattr(s, "metric", None) is None:
                s.metric = cfg.metric
            if cfg.mode and getattr(s, "mode", None) is None:
                s.mode = cfg.mode
            s = getattr(s, "searcher", None)
        scheduler = cfg.scheduler
        if scheduler is not None and scheduler.metric is None:
            scheduler.metric = cfg.metric
            scheduler.mode = cfg.mode
        from .tune_controller import JsonLoggerCallback

        controller = TuneController(
            self._trainable, searcher=searcher, scheduler=scheduler,
            experiment_dir=exp_dir, experiment_name=name,
            max_concurrent=cfg.max_concurrent_trials,
            stop=self._run_config.stop,
            max_failures=self._run_config.failure_config.max_failures,
            trial_resources=cfg.trial_resources,
            resumed_trials=self._resumed_trials,
            # user callbacks (RunConfig.callbacks — e.g. TBX/W&B/MLflow
            # from air.integrations) ride alongside the default logger
            callbacks=[JsonLoggerCallback()]
            + list(self._run_config.callbacks or ()),
        )
        controller.run()
        results = []
        for t in controller.trials:
            err = None
            if t.status == ERROR:
                err = RuntimeError(t.error_msg or "trial failed")
            ckpt = Checkpoint(t.checkpoint_path) if t.checkpoint_path else None
            metrics = dict(t.last_result or {})
            metrics.setdefault("config", t.config)
            results.append(Result(metrics=metrics or None, checkpoint=ckpt,
                                  path=t.trial_dir, error=err))
        grid = ResultGrid(results, controller.trials)
        grid._default_metric = cfg.metric
        grid._default_mode = cfg.mode
        return grid

    @classmethod
    def restore(cls, path: str, trainable: Callable,
                tune_config: Optional[TuneConfig] = None,
                run_config: Optional[RunConfig] = None) -> "Tuner":
        """Resume an interrupted experiment from its directory (reference:
        tune/tuner.py Tuner.restore; experiment_state.py)."""
        trials = TuneController.load_trials(path)
        run_config = run_config or RunConfig(name=os.path.basename(path))
        t = cls(trainable, tune_config=tune_config, run_config=run_config,
                _resumed_trials=trials, _experiment_dir=path)
        return t


def run(trainable: Callable, *, config: Optional[Dict[str, Any]] = None,
        num_samples: int = 1, metric: Optional[str] = None,
        mode: str = "max", scheduler: Optional[TrialScheduler] = None,
        storage_path: Optional[str] = None, name: Optional[str] = None,
        stop: Optional[Dict[str, Any]] = None) -> ResultGrid:
    """Functional entry point (reference: tune/tune.py tune.run)."""
    tuner = Tuner(
        trainable, param_space=config,
        tune_config=TuneConfig(metric=metric, mode=mode,
                               num_samples=num_samples, scheduler=scheduler),
        run_config=RunConfig(name=name, storage_path=storage_path, stop=stop),
    )
    return tuner.fit()
