"""ray_tpu.tune: hyperparameter search and experiment orchestration.

Mirrors the reference's Ray Tune surface (reference: python/ray/tune/):
Tuner/TuneConfig/ResultGrid, search domains (uniform/loguniform/randint/
choice/grid_search/sample_from), schedulers (ASHA, median stopping, PBT),
and `tune.report` via the shared train session.
"""

from ray_tpu.train.session import get_checkpoint, get_context, report

from .schedulers import (AsyncHyperBandScheduler, FIFOScheduler,
                         HyperBandScheduler, MedianStoppingRule, PB2,
                         PopulationBasedTraining, TrialScheduler)
from .search import (BasicVariantGenerator, BayesOptSearch, Categorical,
                     ConcurrencyLimiter,
                     Domain, Float, Integer, Repeater, Searcher,
                     SearcherWrapper, TPESearch,
                     choice, generate_variants, grid_search, loguniform,
                     randint, sample_from, uniform)
from .trial import Trial
from .tune_controller import Callback, JsonLoggerCallback, TuneController
from .tuner import ResultGrid, TuneConfig, Tuner, run

ASHAScheduler = AsyncHyperBandScheduler

__all__ = [
    "ASHAScheduler", "AsyncHyperBandScheduler", "BasicVariantGenerator",
    "BayesOptSearch",
    "Callback", "Categorical", "ConcurrencyLimiter", "Domain",
    "FIFOScheduler", "Float", "HyperBandScheduler", "Integer",
    "JsonLoggerCallback", "MedianStoppingRule", "PB2",
    "PopulationBasedTraining", "Repeater", "ResultGrid", "Searcher",
    "SearcherWrapper", "TPESearch", "Trial", "TrialScheduler",
    "TuneConfig", "TuneController",
    "Tuner", "choice", "generate_variants", "get_checkpoint", "get_context",
    "grid_search", "loguniform", "randint", "report", "run", "sample_from",
    "uniform",
]
