"""TuneController: the experiment event loop.

Mirrors the reference (reference: python/ray/tune/execution/
tune_controller.py:68 TuneController, step :666): start trial actors up to
the concurrency/resource budget, consume reported results, apply scheduler
decisions (CONTINUE/PAUSE/STOP), retry failed trials from their last
checkpoint, snapshot experiment state for resume, and run PBT
exploit/explore by restarting paused trials from a donor checkpoint.

Each trial runs in one actor (`_TrialRunnerActor`) which hosts the user
trainable inside a TrainSession — the same report/lockstep machinery Train
uses, which is exactly how the reference unifies the two (Train runs *on*
Tune; tune session == train session).
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu import ActorDiedError, WorkerCrashedError
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.session import TrainContext, TrainSession

from . import schedulers as sched_mod
from .schedulers import CONTINUE, PAUSE, STOP, FIFOScheduler, TrialScheduler
from .search import Searcher
from .trial import (ERROR, PAUSED, PENDING, RUNNING, TERMINATED, Trial)

logger = logging.getLogger(__name__)


class _TuneSessionShim:
    """What a trainer-adapter trainable sees as `tune_session`."""

    def __init__(self, trial_dir: str, experiment_name: str, trial_name: str):
        self.trial_dir = trial_dir
        self.experiment_name = experiment_name
        self.trial_name = trial_name

    def report(self, metrics: Dict[str, Any]):
        from ray_tpu.train.session import report

        report(metrics)

    def get_checkpoint(self):
        from ray_tpu.train.session import get_checkpoint

        return get_checkpoint()


class _TrialRunnerActor:
    """Actor hosting one trial's trainable."""

    def __init__(self):
        self._session: Optional[TrainSession] = None
        self._iteration = 0

    def start(self, trainable: Callable, config: Dict[str, Any],
              trial_dir: str, experiment_name: str, trial_id: str,
              checkpoint_path: Optional[str], start_iteration: int):
        ckpt = Checkpoint(checkpoint_path) if checkpoint_path else None
        ctx = TrainContext(world_size=1, world_rank=0,
                           experiment_name=experiment_name,
                           trial_name=trial_id, trial_id=trial_id,
                           trial_dir=trial_dir)
        self._iteration = start_iteration
        if getattr(trainable, "_is_trainer_adapter", False):
            shim = _TuneSessionShim(trial_dir, experiment_name, trial_id)
            fn = lambda: trainable(config, shim)  # noqa: E731
        else:
            import inspect

            params = list(inspect.signature(trainable).parameters)
            fn = (lambda: trainable(config)) if params else trainable
        self._session = TrainSession(ctx, fn, checkpoint=ckpt,
                                     checkpoint_upload_dir=trial_dir,
                                     start_iteration=start_iteration)
        self._session.start()
        return True

    def next_result(self):
        kind, metrics, ckpt_path = self._session.next_result()
        if kind == "result":
            self._iteration += 1
            metrics = dict(metrics or {})
            metrics.setdefault("training_iteration", self._iteration)
            metrics.setdefault("timestamp", time.time())
        return (kind, metrics, ckpt_path)

    def stop(self):
        """Graceful teardown: unwind the trainable so nested resources
        (trainer-adapter worker groups + placement groups) are released
        before the actor dies."""
        if self._session is not None:
            self._session.abort()
            self._session = None
        return True


class Callback:
    """Experiment callbacks (reference: tune/callback.py)."""

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]):
        pass

    def on_trial_complete(self, trial: Trial):
        pass

    def on_trial_error(self, trial: Trial):
        pass


class JsonLoggerCallback(Callback):
    """Append each result as a JSON line in the trial dir (reference:
    tune/logger/json.py)."""

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]):
        from ray_tpu.train import storage

        try:
            storage.append_text(
                storage.join(trial.trial_dir, "result.json"),
                json.dumps(result, default=str) + "\n")
        except OSError:
            pass


class TuneController:
    def __init__(self, trainable: Callable, *, searcher: Searcher,
                 scheduler: Optional[TrialScheduler] = None,
                 experiment_dir: str, experiment_name: str,
                 max_concurrent: int = 0,
                 stop: Optional[Dict[str, Any]] = None,
                 max_failures: int = 0,
                 callbacks: Optional[List[Callback]] = None,
                 trial_resources: Optional[Dict[str, float]] = None,
                 resumed_trials: Optional[List[Trial]] = None):
        self._trainable = trainable
        self._searcher = searcher
        self._scheduler = scheduler or FIFOScheduler()
        self._experiment_dir = experiment_dir
        self._experiment_name = experiment_name
        self._max_concurrent = max_concurrent
        self._stop_criteria = stop or {}
        self._max_failures = max_failures
        self._callbacks = callbacks if callbacks is not None else [
            JsonLoggerCallback()]
        if getattr(trainable, "_is_trainer_adapter", False):
            self._trial_resources = {"CPU": 0}
        else:
            self._trial_resources = dict(trial_resources or {"CPU": 1})
        self.trials: List[Trial] = list(resumed_trials or [])
        self._actors: Dict[str, Any] = {}          # trial_id -> actor handle
        self._inflight: Dict[Any, Trial] = {}      # next_result ref -> trial
        self._start_refs: set = set()              # refs that are start-acks
        self._searcher_done = False
        self._runner_cls = ray_tpu.remote(_TrialRunnerActor)
        from ray_tpu._private import common as _common

        _common._ensure_picklable_by_value(trainable)

    # -- trial lifecycle ---------------------------------------------------

    def _new_trial(self) -> Optional[Trial]:
        if self._searcher_done:
            return None
        tid = f"{self._experiment_name}_{len(self.trials):05d}"
        cfg = self._searcher.suggest(tid)
        if cfg is Searcher.DEFER:
            # searcher (e.g. ConcurrencyLimiter) will have more later
            return None
        if cfg is None:
            self._searcher_done = True
            return None
        t = Trial(tid, cfg, self._experiment_dir, self._experiment_name)
        self.trials.append(t)
        self._scheduler.on_trial_add(t)
        return t

    def _running_count(self) -> int:
        return sum(1 for t in self.trials if t.status == RUNNING)

    def _start_trial(self, trial: Trial):
        opts = {"num_cpus": self._trial_resources.get("CPU", 1),
                "max_concurrency": 2}
        extra = {k: v for k, v in self._trial_resources.items() if k != "CPU"}
        if extra:
            opts["resources"] = extra
        actor = self._runner_cls.options(**opts).remote()
        ref = actor.start.remote(self._trainable, trial.config,
                                 trial.trial_dir, self._experiment_name,
                                 trial.trial_id, trial.checkpoint_path,
                                 trial.iteration)
        trial.status = RUNNING
        self._actors[trial.trial_id] = actor
        # non-blocking: the start-ack joins the inflight set so the
        # controller keeps consuming results while this actor waits for
        # resources (a blocking get here deadlocks once trials > CPUs:
        # nothing can finish/tear down to free the CPU being waited for)
        self._inflight[ref] = trial
        self._start_refs.add(ref)

    def _poll(self, trial: Trial):
        actor = self._actors[trial.trial_id]
        ref = actor.next_result.remote()
        self._inflight[ref] = trial

    def _teardown_actor(self, trial: Trial):
        actor = self._actors.pop(trial.trial_id, None)
        if actor is not None:
            # graceful first: unwind the trainable (releases nested worker
            # groups / placement groups held by trainer adapters)
            try:
                ray_tpu.get(actor.stop.remote(), timeout=15.0)
            except Exception:
                pass
            try:
                ray_tpu.kill(actor)
            except Exception:
                pass
        dropped = [r for r, t in self._inflight.items()
                   if t.trial_id == trial.trial_id]
        for r in dropped:
            self._inflight.pop(r, None)
            self._start_refs.discard(r)

    # -- result handling ---------------------------------------------------

    def _should_stop_by_criteria(self, result: Dict[str, Any]) -> bool:
        for k, v in self._stop_criteria.items():
            if k in result and result[k] >= v:
                return True
        return False

    def _handle_result(self, trial: Trial, kind: str,
                       metrics: Optional[Dict[str, Any]],
                       ckpt_path: Optional[str]):
        if kind == "finished":
            if metrics:
                trial.last_result = {**(trial.last_result or {}), **metrics}
            trial.status = TERMINATED
            self._teardown_actor(trial)
            self._searcher.on_trial_complete(trial.trial_id,
                                             trial.last_result)
            self._scheduler.on_trial_complete(trial)
            for cb in self._callbacks:
                cb.on_trial_complete(trial)
            return
        trial.iteration = metrics["training_iteration"]
        trial.last_result = metrics
        trial.results.append(metrics)
        if ckpt_path:
            trial.checkpoint_path = ckpt_path
        self._searcher.on_trial_result(trial.trial_id, metrics)
        for cb in self._callbacks:
            cb.on_trial_result(trial, metrics)
        decision = CONTINUE
        if self._should_stop_by_criteria(metrics):
            decision = STOP
        elif self._scheduler.metric and self._scheduler.metric in metrics:
            decision = self._scheduler.on_trial_result(trial, metrics)
        if decision == CONTINUE:
            self._poll(trial)
        elif decision == STOP:
            trial.status = TERMINATED
            self._teardown_actor(trial)
            self._searcher.on_trial_complete(trial.trial_id, metrics)
            self._scheduler.on_trial_complete(trial)
            for cb in self._callbacks:
                cb.on_trial_complete(trial)
        elif decision == PAUSE:
            trial.status = PAUSED
            self._teardown_actor(trial)
            self._maybe_exploit(trial)
            if trial.status == PAUSED:
                # no exploit pending (non-PBT scheduler, or donor not ready):
                # requeue so the trial resumes from its checkpoint rather
                # than stranding in PAUSED (the experiment would exit)
                trial.status = PENDING

    def _maybe_exploit(self, trial: Trial):
        """PBT exploit/explore: clone a donor's config+checkpoint."""
        pbt = self._scheduler
        if not isinstance(pbt, sched_mod.PopulationBasedTraining):
            return
        pending = pbt.pending_exploits.pop(trial.trial_id, None)
        if not pending:
            return
        donor = next((t for t in self.trials if t.trial_id == pending[0]),
                     None)
        if donor is None:
            trial.status = PENDING
            return
        trial.config = pbt.make_exploit_config(donor)
        if donor.checkpoint_path:
            trial.checkpoint_path = donor.checkpoint_path
        trial.status = PENDING
        logger.info("PBT exploit: %s <- %s config=%s", trial.trial_id,
                    donor.trial_id, trial.config)

    def _handle_failure(self, trial: Trial, err: BaseException):
        if isinstance(err, (ActorDiedError, WorkerCrashedError)):
            trial.num_failures += 1
            self._teardown_actor(trial)
            if (self._max_failures == -1
                    or trial.num_failures <= self._max_failures):
                logger.warning("trial %s failed (%d); restarting from %s",
                               trial.trial_id, trial.num_failures,
                               trial.checkpoint_path)
                trial.status = PENDING
                return
        trial.status = ERROR
        trial.error_msg = str(err)
        self._teardown_actor(trial)
        self._searcher.on_trial_complete(trial.trial_id, error=True)
        self._scheduler.on_trial_error(trial)
        for cb in self._callbacks:
            cb.on_trial_error(trial)

    # -- the loop ----------------------------------------------------------

    def _effective_max_concurrent(self) -> int:
        """Trial-start pacing.  max_concurrent=0 ("unlimited") paces to
        cluster CPU capacity instead of literally unlimited: eagerly
        draining the searcher turns every future trial into a pending
        actor record at once (an unbounded ask/tell searcher made this
        an infinite loop), and lazy suggestion also means ask/tell
        searchers observe completed results before later asks."""
        if self._max_concurrent:
            return self._max_concurrent
        now = time.time()
        if now - getattr(self, "_cap_ts", 0.0) > 5.0:
            try:
                total = ray_tpu.cluster_resources().get("CPU", 0)
            except Exception:
                total = 0
            per = float(self._trial_resources.get("CPU", 1))
            if per <= 0:
                # trainer adapters request CPU:0 at the trial layer (the
                # worker group inside holds the real CPUs) — assume one
                # core per trial rather than dividing by ~zero
                per = 1.0
            self._cap = max(2, int(total / per)) if total else 16
            self._cap_ts = now
        return self._cap

    def _fill(self):
        # FINITE bare searchers (grid/random expose total_trials)
        # materialize every remaining suggestion as a PENDING record up
        # front: records are cheap, save_state persists them, so an
        # interrupted run's restore() sees the full budget.  Actor
        # STARTS are paced below either way.  Wrapped finite searchers
        # (ConcurrencyLimiter(grid)) and ask/tell searchers stay lazy —
        # limiting/learning means suggestions must wait on completions,
        # and their internal state was never resumable (same restore
        # semantics those shapes always had).
        if hasattr(self._searcher, "total_trials"):
            while not self._searcher_done and self._new_trial() is not None:
                pass
        while True:
            if self._running_count() >= self._effective_max_concurrent():
                return
            nxt = self._scheduler.choose_trial_to_run(
                [t for t in self.trials if t.status == PENDING])
            if nxt is None:
                nxt = self._new_trial()
            if nxt is None:
                return
            try:
                self._start_trial(nxt)
            except ray_tpu.TaskError as e:
                self._handle_failure(nxt, e)
            except (ActorDiedError, WorkerCrashedError) as e:
                self._handle_failure(nxt, e)

    def _drain_scheduler_stops(self):
        """Cull trials the scheduler condemned outside their own report
        (HyperBand successive-halving losers waiting as PENDING)."""
        for tid in self._scheduler.trials_to_stop():
            t = next((x for x in self.trials if x.trial_id == tid), None)
            if t is None or t.status in (TERMINATED, ERROR):
                continue
            t.status = TERMINATED
            self._teardown_actor(t)
            self._searcher.on_trial_complete(t.trial_id, t.last_result)
            for cb in self._callbacks:
                cb.on_trial_complete(t)

    def step(self) -> bool:
        """One controller iteration; returns False when the experiment is
        done (reference: tune_controller.py:666)."""
        self._drain_scheduler_stops()
        self._fill()
        if not self._inflight:
            live = any(t.status in (PENDING, RUNNING) for t in self.trials)
            if not live and self._searcher_done:
                return False
            if not live and not self._searcher_done:
                # searcher has more but nothing running: loop to fill again
                return True
            return bool(self._inflight)
        refs = list(self._inflight.keys())
        ready, _ = ray_tpu.wait(refs, num_returns=1, timeout=5.0)
        for ref in ready:
            trial = self._inflight.pop(ref)
            if ref in self._start_refs:
                self._start_refs.discard(ref)
                try:
                    ray_tpu.get(ref)
                except (ActorDiedError, WorkerCrashedError,
                        ray_tpu.TaskError) as e:
                    self._handle_failure(trial, e)
                    continue
                if trial.status == RUNNING:
                    self._poll(trial)
                continue
            try:
                kind, metrics, ckpt = ray_tpu.get(ref)
            except (ActorDiedError, WorkerCrashedError,
                    ray_tpu.TaskError) as e:
                self._handle_failure(trial, e)
                continue
            self._handle_result(trial, kind, metrics, ckpt)
        # periodic, not per-step: serializing every trial record each
        # iteration is O(total_trials) — a 50k-sample sweep would spend
        # its steps writing JSON (reference: TUNE_GLOBAL_CHECKPOINT_S
        # periodic experiment snapshots); run() writes a final one
        now = time.time()
        if now - getattr(self, "_last_save", 0.0) > 5.0:
            self._last_save = now
            self.save_state()
        return True

    def run(self):
        while self.step():
            pass
        self.save_state()
        self.cleanup()

    def cleanup(self):
        for t in list(self.trials):
            if t.trial_id in self._actors:
                self._teardown_actor(t)

    # -- experiment state --------------------------------------------------

    def save_state(self):
        from ray_tpu.train import storage

        state = {
            "experiment_name": self._experiment_name,
            "timestamp": time.time(),
            "trials": [t.to_json() for t in self.trials],
        }
        path = storage.join(self._experiment_dir, "experiment_state.json")
        if storage.is_uri(path):
            storage.write_text(path, json.dumps(state, default=str))
        else:
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(state, f, default=str)
            os.replace(tmp, path)
        # searcher AFTER the trial records: a crash between the writes
        # then means a stale searcher that re-suggests (benign
        # duplicates) rather than a fresh cursor whose already-consumed
        # suggestions have no trial records (silent budget loss)
        self._save_searcher()

    def _save_searcher(self):
        """Pickle the searcher next to the experiment state (reference:
        Searcher.save/restore + experiment_state searcher checkpointing)
        so Tuner.restore continues it — cursor position for grid/random,
        learned observations for TPE/GP/ask-tell wrappers.  Best-effort:
        an unpicklable user optimizer just skips (restore then reruns
        saved trials only, the pre-existing semantics)."""
        import cloudpickle

        from ray_tpu._private import fileio
        from ray_tpu.train import storage

        try:
            blob = cloudpickle.dumps(self._searcher)
        except Exception:
            return
        path = storage.join(self._experiment_dir, "searcher_state.pkl")
        try:
            if fileio.is_uri(path):
                with fileio.open_file(path, "wb") as f:
                    f.write(blob)
            else:
                tmp = path + ".tmp"
                with open(tmp, "wb") as f:
                    f.write(blob)
                os.replace(tmp, path)   # atomic locally
        except Exception:
            logger.debug("searcher state save failed", exc_info=True)

    @staticmethod
    def load_searcher(experiment_dir: str):
        """The pickled searcher of an interrupted run, or None."""
        import cloudpickle

        from ray_tpu._private import fileio
        from ray_tpu.train import storage

        path = storage.join(experiment_dir, "searcher_state.pkl")
        try:
            with fileio.open_file(path, "rb") as f:
                return cloudpickle.loads(f.read())
        except FileNotFoundError:
            return None
        except Exception:
            logger.warning("searcher state unreadable; resuming saved "
                           "trials only", exc_info=True)
            return None

    @staticmethod
    def load_trials(experiment_dir: str) -> List[Trial]:
        from ray_tpu.train import storage

        path = storage.join(experiment_dir, "experiment_state.json")
        state = json.loads(storage.read_text(path))
        name = state["experiment_name"]
        trials = []
        for d in state["trials"]:
            t = Trial.from_json(d, experiment_dir, name)
            if t.status in (RUNNING, PAUSED):
                t.status = PENDING  # interrupted: rerun from checkpoint
            trials.append(t)
        return trials
