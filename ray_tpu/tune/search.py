"""Search spaces and search algorithms.

Mirrors the reference (reference: python/ray/tune/search/ — sample.py
domains, basic_variant.py BasicVariantGenerator, searcher.py Searcher ABC):
grid_search + random sampling domains expand into per-trial configs; a
Searcher proposes configs and learns from completed trials.
"""

from __future__ import annotations

import itertools
import math
import random
from typing import Any, Callable, Dict, List, Optional


# ---------------------------------------------------------------------------
# Domains (reference: tune/search/sample.py)
# ---------------------------------------------------------------------------

class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Float(Domain):
    def __init__(self, lower: float, upper: float, log: bool = False):
        if log and lower <= 0:
            raise ValueError("loguniform requires lower > 0")
        self.lower, self.upper, self.log = lower, upper, log

    def sample(self, rng):
        if self.log:
            import math

            return math.exp(rng.uniform(math.log(self.lower),
                                        math.log(self.upper)))
        return rng.uniform(self.lower, self.upper)


class Integer(Domain):
    def __init__(self, lower: int, upper: int):
        self.lower, self.upper = lower, upper

    def sample(self, rng):
        return rng.randrange(self.lower, self.upper)


class Categorical(Domain):
    def __init__(self, categories: List[Any]):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


class Function(Domain):
    def __init__(self, fn: Callable[[], Any]):
        self.fn = fn

    def sample(self, rng):
        return self.fn()


def uniform(lower: float, upper: float) -> Float:
    return Float(lower, upper)


def loguniform(lower: float, upper: float) -> Float:
    return Float(lower, upper, log=True)


def randint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper)


def choice(categories: List[Any]) -> Categorical:
    return Categorical(categories)


def sample_from(fn: Callable[[], Any]) -> Function:
    return Function(fn)


def grid_search(values: List[Any]) -> Dict[str, List[Any]]:
    return {"grid_search": list(values)}


def _is_grid(v) -> bool:
    return isinstance(v, dict) and set(v.keys()) == {"grid_search"}


# ---------------------------------------------------------------------------
# Variant expansion (reference: tune/search/basic_variant.py)
# ---------------------------------------------------------------------------

def _walk(space: Dict[str, Any], path=()):
    """Yield (path, value) leaves of a nested dict."""
    for k, v in space.items():
        if isinstance(v, dict) and not _is_grid(v):
            yield from _walk(v, path + (k,))
        else:
            yield path + (k,), v


def _set_path(d: Dict[str, Any], path, value):
    for k in path[:-1]:
        d = d.setdefault(k, {})
    d[path[-1]] = value


def generate_variants(param_space: Dict[str, Any], num_samples: int = 1,
                      seed: Optional[int] = None):
    """Expand grid axes (cross product) × num_samples random draws."""
    rng = random.Random(seed)
    leaves = list(_walk(param_space))
    grid_axes = [(p, v["grid_search"]) for p, v in leaves if _is_grid(v)]
    grids = itertools.product(*[vals for _, vals in grid_axes]) \
        if grid_axes else [()]
    for combo in grids:
        for _ in range(num_samples):
            cfg: Dict[str, Any] = {}
            for (p, v) in leaves:
                if _is_grid(v):
                    continue
                _set_path(cfg, p, v.sample(rng) if isinstance(v, Domain) else v)
            for (p, _), val in zip(grid_axes, combo):
                _set_path(cfg, p, val)
            yield cfg


# ---------------------------------------------------------------------------
# Searcher interface (reference: tune/search/searcher.py)
# ---------------------------------------------------------------------------

#: sentinel a searcher returns from suggest() to mean "nothing right now,
#: ask again later" (vs None = exhausted) — used by ConcurrencyLimiter
#: (reference: tune/search/concurrency_limiter.py returns None + retries)
DEFER = object()


class Searcher:
    DEFER = DEFER

    def __init__(self, metric: Optional[str] = None,
                 mode: Optional[str] = None):
        # mode=None means "not explicitly set": Tuner backfills it from
        # TuneConfig (an explicit mode on an inner searcher of a
        # wrapper chain always wins); consumers treat None as "max"
        self.metric = metric
        self.mode = mode

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        """Next config, None when exhausted, or DEFER to retry later."""
        raise NotImplementedError

    def on_trial_result(self, trial_id: str, result: Dict[str, Any]):
        pass

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None,
                          error: bool = False):
        pass


class BasicVariantGenerator(Searcher):
    """Grid/random search over a param_space."""

    def __init__(self, param_space: Dict[str, Any], num_samples: int = 1,
                 seed: Optional[int] = None, **kw):
        super().__init__(**kw)
        self._variants = list(generate_variants(param_space, num_samples,
                                                seed))
        self._i = 0

    @property
    def total_trials(self) -> int:
        return len(self._variants)

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._i >= len(self._variants):
            return None
        cfg = self._variants[self._i]
        self._i += 1
        return cfg


class ConcurrencyLimiter(Searcher):
    """Caps in-flight suggestions from a wrapped searcher (reference:
    tune/search/concurrency_limiter.py)."""

    def __init__(self, searcher: Searcher, max_concurrent: int):
        super().__init__(searcher.metric, searcher.mode or "max")
        self.searcher = searcher
        self.max_concurrent = max_concurrent
        self._live: set = set()

    def suggest(self, trial_id: str):
        if len(self._live) >= self.max_concurrent:
            return DEFER
        cfg = self.searcher.suggest(trial_id)
        if cfg is not None and cfg is not DEFER:
            self._live.add(trial_id)
        return cfg

    def on_trial_result(self, trial_id, result):
        self.searcher.on_trial_result(trial_id, result)

    def on_trial_complete(self, trial_id, result=None, error=False):
        self._live.discard(trial_id)
        self.searcher.on_trial_complete(trial_id, result, error=error)


class Repeater(Searcher):
    """Runs each suggested config `repeat` times and reports the mean
    metric to the wrapped searcher (reference: tune/search/repeater.py —
    for noisy objectives)."""

    def __init__(self, searcher: Searcher, repeat: int = 3,
                 metric: Optional[str] = None):
        super().__init__(metric or searcher.metric, searcher.mode or "max")
        self.searcher = searcher
        self.repeat = repeat
        self._groups: Dict[str, List[str]] = {}   # lead trial id -> members
        self._member_of: Dict[str, str] = {}
        self._results: Dict[str, List[Dict[str, Any]]] = {}
        self._finished: Dict[str, set] = {}        # lead -> finished members
        self._queue: List[tuple] = []              # (lead, config) to repeat

    def suggest(self, trial_id: str):
        if self._queue:
            lead, cfg = self._queue.pop(0)
            self._groups[lead].append(trial_id)
            self._member_of[trial_id] = lead
            return dict(cfg)
        cfg = self.searcher.suggest(trial_id)
        if cfg is None or cfg is DEFER:
            return cfg
        self._groups[trial_id] = [trial_id]
        self._member_of[trial_id] = trial_id
        self._results[trial_id] = []
        for _ in range(self.repeat - 1):
            self._queue.append((trial_id, cfg))
        return dict(cfg)

    def on_trial_complete(self, trial_id, result=None, error=False):
        lead = self._member_of.get(trial_id, trial_id)
        if result is not None and not error:
            self._results.setdefault(lead, []).append(result)
        finished = self._finished.setdefault(lead, set())
        finished.add(trial_id)
        # finalize once every member (including errored ones) is done, with
        # whatever results survived — an errored member must not strand the
        # group and starve the wrapped searcher of the observation
        if len(finished) >= self.repeat:
            done = self._results.get(lead, [])
            if not done:
                self.searcher.on_trial_complete(lead, None, error=True)
                return
            metric = self.metric
            vals = [float(r[metric]) for r in done
                    if metric and metric in r]
            agg = dict(done[-1])
            if vals and metric:
                agg[metric] = sum(vals) / len(vals)
            self.searcher.on_trial_complete(lead, agg)


class TPESearch(Searcher):
    """Tree-structured Parzen Estimator over a Domain param_space.

    Native model-based searcher standing in for the reference's
    hyperopt/optuna integrations (reference: tune/search/hyperopt/,
    tune/search/optuna/) without the external dependency: observations
    split into good/bad by quantile `gamma`; candidates are sampled from a
    KDE over the good set and ranked by the good/bad density ratio,
    independently per dimension.
    """

    def __init__(self, param_space: Dict[str, Any], metric: str,
                 mode: Optional[str] = None, n_initial: int = 8, gamma: float = 0.25,
                 n_candidates: int = 24, num_samples: int = 64,
                 seed: Optional[int] = None):
        super().__init__(metric, mode)
        import numpy as np

        # modeled dims: Float/Integer/Categorical.  Everything else passes
        # through: Function domains get re-sampled each suggest, plain
        # constants are copied verbatim.
        self.space: Dict[str, Domain] = {}
        self._passthrough: Dict[str, Any] = {}
        for k, v in param_space.items():
            if isinstance(v, (Float, Integer, Categorical)):
                self.space[k] = v
            else:
                self._passthrough[k] = v
        self.n_initial = n_initial
        self.gamma = gamma
        self.n_candidates = n_candidates
        self.remaining = num_samples
        self.rng = random.Random(seed)
        self.np_rng = np.random.RandomState(seed)
        self._obs: List[tuple] = []   # (config, score)
        self._pending: Dict[str, Dict[str, Any]] = {}

    def suggest(self, trial_id: str):
        if self.remaining <= 0:
            return None
        self.remaining -= 1
        if len(self._obs) < self.n_initial:
            cfg = {k: d.sample(self.rng) for k, d in self.space.items()}
        else:
            cfg = self._tpe_suggest()
        self._pending[trial_id] = cfg
        out = dict(cfg)
        for k, v in self._passthrough.items():
            out[k] = v.sample(self.rng) if isinstance(v, Domain) else v
        return out

    def _tpe_suggest(self) -> Dict[str, Any]:
        import numpy as np

        obs = sorted(self._obs, key=lambda o: o[1], reverse=True)
        n_good = max(2, int(len(obs) * self.gamma))
        good, bad = obs[:n_good], obs[n_good:] or obs[-2:]
        out = {}
        for k, dom in self.space.items():
            if isinstance(dom, Categorical):
                # weighted by category counts in the good set (+1 smooth)
                counts = {c: 1.0 for c in dom.categories}
                for cfg, _ in good:
                    if cfg.get(k) in counts:
                        counts[cfg[k]] += 1.0
                cats, w = zip(*counts.items())
                w = np.asarray(w) / sum(w)
                out[k] = cats[self.np_rng.choice(len(cats), p=w)]
                continue
            log = isinstance(dom, Float) and dom.log
            xform = (lambda v: float(np.log(v))) if log else float
            inv = (lambda v: float(np.exp(v))) if log else float
            gv = np.asarray([xform(cfg[k]) for cfg, _ in good])
            bv = np.asarray([xform(cfg[k]) for cfg, _ in bad])
            lo, hi = xform(dom.lower), xform(dom.upper)
            bw = max((hi - lo) / 10.0, 1e-6)

            def kde(x, pts):
                d = (x[:, None] - pts[None, :]) / bw
                return np.exp(-0.5 * d * d).sum(axis=1) / max(len(pts), 1)

            cand = gv[self.np_rng.randint(0, len(gv), self.n_candidates)] \
                + self.np_rng.randn(self.n_candidates) * bw
            cand = np.clip(cand, lo, hi)
            ratio = (kde(cand, gv) + 1e-12) / (kde(cand, bv) + 1e-12)
            best = inv(cand[int(np.argmax(ratio))])
            if isinstance(dom, Integer):
                best = int(round(best))
                best = min(max(best, dom.lower), dom.upper - 1)
            out[k] = best
        return out

    def on_trial_complete(self, trial_id, result=None, error=False):
        cfg = self._pending.pop(trial_id, None)
        if cfg is None or error or not result or self.metric not in result:
            return
        score = float(result[self.metric])
        if self.mode == "min":
            score = -score
        self._obs.append((cfg, score))


class BayesOptSearch(Searcher):
    """Gaussian-process Bayesian optimization over Float/Integer domains.

    Native stand-in for the reference's bayes_opt integration
    (reference: tune/search/bayesopt/bayesopt_search.py) without the
    external dependency: an RBF-kernel GP posterior over normalized
    [0,1]^d inputs, maximizing Expected Improvement over random
    candidates.  Categorical dims fall back to good-set-weighted
    sampling (a GP has no natural metric there).
    """

    def __init__(self, param_space: Dict[str, Any], metric: str,
                 mode: Optional[str] = None, n_initial: int = 6,
                 n_candidates: int = 256, num_samples: int = 64,
                 length_scale: float = 0.2, noise: float = 1e-4,
                 xi: float = 0.01, seed: Optional[int] = None):
        super().__init__(metric, mode)
        import numpy as np

        self.space: Dict[str, Domain] = {}
        self._cats: Dict[str, Categorical] = {}
        self._passthrough: Dict[str, Any] = {}
        for k, v in param_space.items():
            if isinstance(v, (Float, Integer)):
                self.space[k] = v
            elif isinstance(v, Categorical):
                self._cats[k] = v
            else:
                self._passthrough[k] = v
        if not self.space:
            raise ValueError(
                "BayesOptSearch needs at least one Float/Integer dimension")
        self.n_initial = n_initial
        self.n_candidates = n_candidates
        self.remaining = num_samples
        self.length_scale = length_scale
        self.noise = noise
        self.xi = xi
        self.rng = random.Random(seed)
        self.np_rng = np.random.RandomState(seed)
        self._obs: List[tuple] = []   # (unit-vector, cat-config, score)
        self._pending: Dict[str, tuple] = {}

    # -- unit-cube transform -------------------------------------------------

    def _to_unit(self, dom: Domain, v: float) -> float:
        import numpy as np

        if isinstance(dom, Float) and dom.log:
            lo, hi = np.log(dom.lower), np.log(dom.upper)
            return float((np.log(v) - lo) / (hi - lo))
        lo, hi = float(dom.lower), float(dom.upper)
        return (float(v) - lo) / (hi - lo)

    def _from_unit(self, dom: Domain, u: float):
        import numpy as np

        u = min(max(u, 0.0), 1.0)
        if isinstance(dom, Float) and dom.log:
            lo, hi = np.log(dom.lower), np.log(dom.upper)
            return float(np.exp(lo + u * (hi - lo)))
        lo, hi = float(dom.lower), float(dom.upper)
        v = lo + u * (hi - lo)
        if isinstance(dom, Integer):
            return min(max(int(round(v)), dom.lower), dom.upper - 1)
        return v

    # -- searcher API --------------------------------------------------------

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        import numpy as np

        if self.remaining <= 0:
            return None
        self.remaining -= 1
        keys = list(self.space)
        if len(self._obs) < self.n_initial:
            u = self.np_rng.rand(len(keys))
        else:
            u = self._ei_suggest(keys)
        cats = {k: self._weighted_cat(k, dom)
                for k, dom in self._cats.items()}
        self._pending[trial_id] = (u.copy(), dict(cats))
        out = {k: self._from_unit(self.space[k], u[i])
               for i, k in enumerate(keys)}
        out.update(cats)
        for k, v in self._passthrough.items():
            out[k] = v.sample(self.rng) if isinstance(v, Domain) else v
        return out

    def _weighted_cat(self, k: str, dom: Categorical):
        import numpy as np

        counts = {c: 1.0 for c in dom.categories}
        obs = sorted(self._obs, key=lambda o: o[2], reverse=True)
        for _, cats, _ in obs[:max(2, len(obs) // 4)]:
            if cats.get(k) in counts:
                counts[cats[k]] += 1.0
        cs, w = zip(*counts.items())
        w = np.asarray(w) / sum(w)
        return cs[self.np_rng.choice(len(cs), p=w)]

    def _ei_suggest(self, keys):
        """Maximize Expected Improvement of the GP posterior over random
        candidate points (plus jittered copies of the incumbent)."""
        import numpy as np

        X = np.stack([o[0] for o in self._obs])            # [n, d]
        y = np.asarray([o[2] for o in self._obs])          # [n]
        y_mean, y_std = y.mean(), max(y.std(), 1e-8)
        yn = (y - y_mean) / y_std

        def k_rbf(A, B):
            d2 = ((A[:, None, :] - B[None, :, :]) ** 2).sum(-1)
            return np.exp(-0.5 * d2 / self.length_scale ** 2)

        K = k_rbf(X, X) + self.noise * np.eye(len(X))
        L = np.linalg.cholesky(K)
        alpha = np.linalg.solve(L.T, np.linalg.solve(L, yn))

        n_rand = self.n_candidates
        cand = self.np_rng.rand(n_rand, len(keys))
        best = X[int(np.argmax(yn))]
        jitter = best[None, :] + 0.05 * self.np_rng.randn(16, len(keys))
        cand = np.vstack([cand, np.clip(jitter, 0, 1)])

        Ks = k_rbf(cand, X)                                # [m, n]
        mu = Ks @ alpha
        v = np.linalg.solve(L, Ks.T)                       # [n, m]
        var = np.clip(1.0 - (v ** 2).sum(0), 1e-12, None)
        sigma = np.sqrt(var)
        f_best = yn.max()
        z = (mu - f_best - self.xi) / sigma
        # standard-normal pdf/cdf without scipy
        pdf = np.exp(-0.5 * z * z) / np.sqrt(2 * np.pi)
        cdf = 0.5 * (1.0 + np.vectorize(math.erf)(z / np.sqrt(2)))
        ei = (mu - f_best - self.xi) * cdf + sigma * pdf
        return cand[int(np.argmax(ei))]

    def on_trial_complete(self, trial_id, result=None, error=False):
        pend = self._pending.pop(trial_id, None)
        if pend is None or error or not result or self.metric not in result:
            return
        u, cats = pend
        score = float(result[self.metric])
        if self.mode == "min":
            score = -score
        self._obs.append((u, cats, score))


class SearcherWrapper(Searcher):
    """Adapt any ask/tell optimizer object into a Tune Searcher
    (reference: python/ray/tune/search/ ships nine per-library
    integrations — OptunaSearch, HyperOptSearch, AxSearch, BOHB, HEBO,
    Nevergrad, ZOOpt... — all of which reduce to an ask/tell loop; this
    one duck-typed shim covers that surface without bundling any of
    the libraries).

    The wrapped object needs:
      * ``ask()`` returning either a config dict, or a trial-like
        object whose config is found under ``.params`` / ``.config``
        / ``.args`` (optuna's ``study.ask()`` returns a Trial with
        ``.params``... populated on access; for such lazy objects pass
        ``to_config=`` to extract the dict yourself), and
      * ``tell(token, value)`` where ``token`` is exactly what ask()
        returned (skopt/nevergrad style) — the wrapper remembers it
        per trial.

    ``mode="max"`` negates values before tell() for minimizers (every
    ask/tell library minimizes by default; pass ``minimize=False`` if
    yours maximizes).

    The run ends when ``ask()`` returns None — the wrapped optimizer
    owns the trial budget (wrap in ConcurrencyLimiter/your own counter
    for unbounded optimizers).
    """

    def __init__(self, opt, metric: Optional[str] = None,
                 mode: Optional[str] = None, *, to_config=None,
                 minimize: bool = True):
        super().__init__(metric=metric, mode=mode)
        for attr in ("ask", "tell"):
            if not callable(getattr(opt, attr, None)):
                raise TypeError(
                    f"SearcherWrapper needs an object with ask()/tell(); "
                    f"{type(opt).__name__} has no {attr}()")
        self._opt = opt
        self._to_config = to_config
        self._minimize = minimize
        self._tokens: Dict[str, Any] = {}

    def _extract(self, token) -> Dict[str, Any]:
        if self._to_config is not None:
            return dict(self._to_config(token))
        if isinstance(token, dict):
            return dict(token)
        for attr in ("params", "config", "args"):
            cfg = getattr(token, attr, None)
            if isinstance(cfg, dict):
                return dict(cfg)
        raise TypeError(
            f"cannot extract a config dict from {type(token).__name__}; "
            "pass to_config= to SearcherWrapper")

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        token = self._opt.ask()
        if token is None:
            return None            # optimizer exhausted
        self._tokens[trial_id] = token
        return self._extract(token)

    def on_trial_complete(self, trial_id, result=None, error=False):
        token = self._tokens.pop(trial_id, None)
        if token is None:
            return
        if error or not result or self.metric not in result:
            # most ask/tell libraries accept a failure signal as a very
            # bad value; losing one observation is safer than feeding a
            # fake number — skip the tell
            return
        value = float(result[self.metric])
        mode = self.mode or "max"
        if self._minimize and mode == "max":
            value = -value
        elif not self._minimize and mode == "min":
            value = -value
        self._opt.tell(token, value)
