"""Search spaces and search algorithms.

Mirrors the reference (reference: python/ray/tune/search/ — sample.py
domains, basic_variant.py BasicVariantGenerator, searcher.py Searcher ABC):
grid_search + random sampling domains expand into per-trial configs; a
Searcher proposes configs and learns from completed trials.
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Callable, Dict, List, Optional


# ---------------------------------------------------------------------------
# Domains (reference: tune/search/sample.py)
# ---------------------------------------------------------------------------

class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Float(Domain):
    def __init__(self, lower: float, upper: float, log: bool = False):
        if log and lower <= 0:
            raise ValueError("loguniform requires lower > 0")
        self.lower, self.upper, self.log = lower, upper, log

    def sample(self, rng):
        if self.log:
            import math

            return math.exp(rng.uniform(math.log(self.lower),
                                        math.log(self.upper)))
        return rng.uniform(self.lower, self.upper)


class Integer(Domain):
    def __init__(self, lower: int, upper: int):
        self.lower, self.upper = lower, upper

    def sample(self, rng):
        return rng.randrange(self.lower, self.upper)


class Categorical(Domain):
    def __init__(self, categories: List[Any]):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


class Function(Domain):
    def __init__(self, fn: Callable[[], Any]):
        self.fn = fn

    def sample(self, rng):
        return self.fn()


def uniform(lower: float, upper: float) -> Float:
    return Float(lower, upper)


def loguniform(lower: float, upper: float) -> Float:
    return Float(lower, upper, log=True)


def randint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper)


def choice(categories: List[Any]) -> Categorical:
    return Categorical(categories)


def sample_from(fn: Callable[[], Any]) -> Function:
    return Function(fn)


def grid_search(values: List[Any]) -> Dict[str, List[Any]]:
    return {"grid_search": list(values)}


def _is_grid(v) -> bool:
    return isinstance(v, dict) and set(v.keys()) == {"grid_search"}


# ---------------------------------------------------------------------------
# Variant expansion (reference: tune/search/basic_variant.py)
# ---------------------------------------------------------------------------

def _walk(space: Dict[str, Any], path=()):
    """Yield (path, value) leaves of a nested dict."""
    for k, v in space.items():
        if isinstance(v, dict) and not _is_grid(v):
            yield from _walk(v, path + (k,))
        else:
            yield path + (k,), v


def _set_path(d: Dict[str, Any], path, value):
    for k in path[:-1]:
        d = d.setdefault(k, {})
    d[path[-1]] = value


def generate_variants(param_space: Dict[str, Any], num_samples: int = 1,
                      seed: Optional[int] = None):
    """Expand grid axes (cross product) × num_samples random draws."""
    rng = random.Random(seed)
    leaves = list(_walk(param_space))
    grid_axes = [(p, v["grid_search"]) for p, v in leaves if _is_grid(v)]
    grids = itertools.product(*[vals for _, vals in grid_axes]) \
        if grid_axes else [()]
    for combo in grids:
        for _ in range(num_samples):
            cfg: Dict[str, Any] = {}
            for (p, v) in leaves:
                if _is_grid(v):
                    continue
                _set_path(cfg, p, v.sample(rng) if isinstance(v, Domain) else v)
            for (p, _), val in zip(grid_axes, combo):
                _set_path(cfg, p, val)
            yield cfg


# ---------------------------------------------------------------------------
# Searcher interface (reference: tune/search/searcher.py)
# ---------------------------------------------------------------------------

class Searcher:
    def __init__(self, metric: Optional[str] = None, mode: str = "max"):
        self.metric = metric
        self.mode = mode

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        """Next config, or None when exhausted."""
        raise NotImplementedError

    def on_trial_result(self, trial_id: str, result: Dict[str, Any]):
        pass

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None,
                          error: bool = False):
        pass


class BasicVariantGenerator(Searcher):
    """Grid/random search over a param_space."""

    def __init__(self, param_space: Dict[str, Any], num_samples: int = 1,
                 seed: Optional[int] = None, **kw):
        super().__init__(**kw)
        self._variants = list(generate_variants(param_space, num_samples,
                                                seed))
        self._i = 0

    @property
    def total_trials(self) -> int:
        return len(self._variants)

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._i >= len(self._variants):
            return None
        cfg = self._variants[self._i]
        self._i += 1
        return cfg
