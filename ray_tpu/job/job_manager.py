"""Job submission: per-job supervisor actor driving a subprocess.

Analog of the reference's job subsystem (reference:
python/ray/dashboard/modules/job/job_manager.py,
job_supervisor.py): ``submit_job`` creates a detached ``JobSupervisor``
actor that Popens the entrypoint with the cluster address injected, streams
its output into a bounded in-actor log buffer, and records status
transitions (PENDING -> RUNNING -> SUCCEEDED | FAILED | STOPPED) in the
control-plane KV store under the ``_jobs`` namespace so any client can read
them without touching the supervisor.
"""

from __future__ import annotations

import json
import os
import subprocess
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

import ray_tpu

JOB_NS = "_jobs"
MAX_LOG_LINES = 20_000


class JobStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"

    TERMINAL = (SUCCEEDED, FAILED, STOPPED)


def _kv_put_job(core, submission_id: str, info: Dict[str, Any]):
    core.control.call("kv_put", {
        "ns": JOB_NS, "key": submission_id,
        "val": json.dumps(info).encode(),
    })


def _kv_get_job(core, submission_id: str) -> Optional[Dict[str, Any]]:
    raw = core.control.call("kv_get", {"ns": JOB_NS, "key": submission_id})
    return json.loads(raw) if raw else None


@ray_tpu.remote
class JobSupervisor:
    """Owns one job subprocess (reference: job_supervisor.py).

    Detached so it outlives the submitting client; 0 CPU so it never
    competes with the job's own tasks for slots.
    """

    def __init__(self, submission_id: str, entrypoint: str,
                 control_address: str,
                 runtime_env: Optional[Dict[str, Any]] = None,
                 metadata: Optional[Dict[str, str]] = None):
        self.submission_id = submission_id
        self.entrypoint = entrypoint
        self.control_address = control_address
        self.runtime_env = runtime_env or {}
        self.metadata = metadata or {}
        self.proc: Optional[subprocess.Popen] = None
        self.logs: List[str] = []
        self.stopped = False
        self._lock = threading.Lock()

    def _core(self):
        from ray_tpu._private.api import current_core

        return current_core()

    def _set_status(self, status: str, message: str = ""):
        info = _kv_get_job(self._core(), self.submission_id) or {}
        info.update(status=status, message=message)
        if status == JobStatus.RUNNING:
            info["start_time"] = time.time()
        if status in JobStatus.TERMINAL:
            info["end_time"] = time.time()
        _kv_put_job(self._core(), self.submission_id, info)
        try:
            # structured cluster event per transition (reference: the
            # job manager's event emission, dashboard event module)
            self._core().control.notify("report_event", {
                "severity": ("ERROR" if status == JobStatus.FAILED
                             else "INFO"),
                "source": "job", "event_type": status.lower(),
                "entity_id": self.submission_id,
                "message": (f"job {self.submission_id} {status}"
                            + (f": {message[:200]}" if message else "")),
            })
        except Exception:
            pass

    def run(self) -> str:
        """Run the entrypoint to completion; returns the terminal status."""
        env = dict(os.environ)
        env["RAY_TPU_ADDRESS"] = self.control_address
        env["RAY_TPU_SUBMISSION_ID"] = self.submission_id
        env.update(self.runtime_env.get("env_vars") or {})
        cwd = self.runtime_env.get("working_dir") or None
        self._set_status(JobStatus.RUNNING)
        try:
            with self._lock:
                if self.stopped:
                    self._set_status(JobStatus.STOPPED)
                    return JobStatus.STOPPED
                self.proc = subprocess.Popen(
                    self.entrypoint, shell=True, cwd=cwd, env=env,
                    stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                    text=True, start_new_session=True)
            for line in self.proc.stdout:
                self.logs.append(line)
                if len(self.logs) > MAX_LOG_LINES:
                    del self.logs[: MAX_LOG_LINES // 10]
            rc = self.proc.wait()
        except Exception as e:
            self._set_status(JobStatus.FAILED, f"supervisor error: {e}")
            return JobStatus.FAILED
        if self.stopped:
            status = JobStatus.STOPPED
        elif rc == 0:
            status = JobStatus.SUCCEEDED
        else:
            status = JobStatus.FAILED
        self._set_status(status, f"exit code {rc}")
        return status

    def stop(self) -> bool:
        with self._lock:
            self.stopped = True
            if self.proc is not None and self.proc.poll() is None:
                try:
                    os.killpg(os.getpgid(self.proc.pid), 15)
                except ProcessLookupError:
                    pass
                return True
        return False

    def get_logs(self) -> str:
        return "".join(self.logs)

    def ping(self) -> bool:
        return True


class JobSubmissionClient:
    """Submit and manage jobs (reference: python/ray/dashboard/modules/job/
    sdk.py JobSubmissionClient).  ``address`` is the control-plane address;
    with None, uses the already-initialized driver connection."""

    def __init__(self, address: Optional[str] = None):
        self._http: Optional[str] = None
        if address and address.startswith("http"):
            # REST mode against the dashboard job API (reference:
            # JobSubmissionClient("http://...") -> job_head.py routes)
            self._http = address.rstrip("/")
            return
        if not ray_tpu.is_initialized():
            # tolerate a concurrent initializer (dashboard handler
            # threads race on first job request)
            ray_tpu.init(address=address, ignore_reinit_error=True)
        from ray_tpu._private.api import current_core

        self._core = current_core()
        info = ray_tpu.connection_info()
        self._control_address = info["control_address"]

    def _rest(self, method: str, path: str, body=None):
        import json as _json
        from urllib.request import Request, urlopen

        data = _json.dumps(body).encode() if body is not None else None
        req = Request(self._http + path, data=data, method=method,
                      headers={"Content-Type": "application/json"})
        with urlopen(req, timeout=60) as resp:
            return _json.loads(resp.read().decode())

    # -- API ---------------------------------------------------------------

    def submit_job(self, *, entrypoint: str,
                   runtime_env: Optional[Dict[str, Any]] = None,
                   submission_id: Optional[str] = None,
                   metadata: Optional[Dict[str, str]] = None) -> str:
        if self._http:
            return self._rest("POST", "/api/jobs", {
                "entrypoint": entrypoint, "runtime_env": runtime_env,
                "submission_id": submission_id, "metadata": metadata,
            })["submission_id"]
        submission_id = submission_id or f"raysubmit_{uuid.uuid4().hex[:16]}"
        _kv_put_job(self._core, submission_id, {
            "submission_id": submission_id,
            "entrypoint": entrypoint,
            "status": JobStatus.PENDING,
            "submit_time": time.time(),
            "metadata": metadata or {},
        })
        # max_concurrency: run() blocks for the job's lifetime; stop()/
        # get_logs() must interleave (reference: async JobSupervisor)
        sup = JobSupervisor.options(
            name=f"_job_supervisor_{submission_id}", lifetime="detached",
            num_cpus=0, max_concurrency=4,
        ).remote(submission_id, entrypoint, self._control_address,
                 runtime_env, metadata)
        # fire-and-forget; the ref is owned by the supervisor's run itself
        sup.run.remote()
        self._supervisor_cache = getattr(self, "_supervisor_cache", {})
        self._supervisor_cache[submission_id] = sup
        return submission_id

    def _supervisor(self, submission_id: str):
        cache = getattr(self, "_supervisor_cache", {})
        if submission_id in cache:
            return cache[submission_id]
        return ray_tpu.get_actor(f"_job_supervisor_{submission_id}")

    def get_job_status(self, submission_id: str) -> str:
        info = self.get_job_info(submission_id)
        return info["status"] if info else None

    def get_job_info(self, submission_id: str) -> Optional[Dict[str, Any]]:
        if self._http:
            from urllib.error import HTTPError

            try:
                return self._rest("GET", f"/api/jobs/{submission_id}")
            except HTTPError as e:
                if e.code == 404:
                    return None
                raise
        return _kv_get_job(self._core, submission_id)

    def get_job_logs(self, submission_id: str) -> str:
        if self._http:
            return self._rest("GET",
                              f"/api/jobs/{submission_id}/logs")["logs"]
        try:
            return ray_tpu.get(
                self._supervisor(submission_id).get_logs.remote(),
                timeout=30.0)
        except Exception:
            return ""

    def stop_job(self, submission_id: str) -> bool:
        if self._http:
            return self._rest("POST",
                              f"/api/jobs/{submission_id}/stop")["stopped"]
        try:
            return ray_tpu.get(
                self._supervisor(submission_id).stop.remote(), timeout=30.0)
        except Exception:
            return False

    def list_jobs(self) -> List[Dict[str, Any]]:
        if self._http:
            return self._rest("GET", "/api/jobs")
        keys = self._core.control.call("kv_keys", {"ns": JOB_NS, "prefix": ""})
        out = []
        for k in keys:
            info = _kv_get_job(self._core, k)
            if info:
                out.append(info)
        return sorted(out, key=lambda j: j.get("submit_time", 0))

    def wait_until_finish(self, submission_id: str,
                          timeout: float = 300.0) -> str:
        deadline = time.time() + timeout
        while time.time() < deadline:
            st = self.get_job_status(submission_id)
            if st in JobStatus.TERMINAL:
                return st
            time.sleep(0.25)
        raise TimeoutError(
            f"job {submission_id} not finished after {timeout}s")
