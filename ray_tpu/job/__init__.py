"""Job submission (reference: python/ray/dashboard/modules/job/)."""

from .job_manager import JobStatus, JobSubmissionClient, JobSupervisor

__all__ = ["JobStatus", "JobSubmissionClient", "JobSupervisor"]
