"""Shared AST model for the ray_tpu static analyzer.

Builds a repo-wide index over the parsed sources:

- every module / class / function (including nested defs), keyed by
  ``(module, qualname)``;
- every ``threading.Lock/RLock/Condition`` the repo creates, identified as
  ``Class.attr`` (instance locks) or ``modbase.name`` (module-level locks),
  with ``Condition(self.x)`` aliased onto its underlying lock;
- lightweight type facts: module-level singletons (``VAR = Class()``),
  instance attributes (``self.x = Class()``), ``Dict[...]``-annotation value
  types, and parameter annotations — enough to resolve ``st.cv`` or
  ``self.core._pump()`` without importing anything.

All passes consume this index; nothing here imports the analyzed code.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

LOCK_CTORS = {"Lock": "Lock", "RLock": "RLock", "Condition": "Condition"}


def repo_root() -> str:
    """Directory that contains the ``ray_tpu`` package."""
    import ray_tpu
    return os.path.dirname(os.path.dirname(os.path.abspath(
        ray_tpu.__file__)))


@dataclasses.dataclass
class Finding:
    pass_id: str
    rule: str
    file: str          # rel path, forward slashes
    func: str          # module-level qualname ('' for module scope)
    detail: str        # stable discriminator (no line numbers)
    message: str
    line: int
    ordinal: int = 0   # >0 when the same key occurs repeatedly

    @property
    def key(self) -> str:
        k = f"{self.pass_id}:{self.rule}:{self.file}:{self.func}:{self.detail}"
        if self.ordinal:
            k += f"#{self.ordinal}"
        return k

    def render(self) -> str:
        return (f"{self.file}:{self.line}: [{self.pass_id}/{self.rule}] "
                f"{self.message}")


class ModuleInfo:
    def __init__(self, path: str, rel: str, source: str):
        self.path = path
        self.rel = rel                      # e.g. ray_tpu/_private/core.py
        self.name = rel[:-3].replace("/", ".")  # dotted, for display
        self.base = self.name.rsplit(".", 1)[-1]
        self.source_lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        # local name -> dotted module/thing it refers to
        self.imports: Dict[str, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.imports[a.asname or a.name.split(".")[0]] = a.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    self.imports[a.asname or a.name] = (
                        f"{node.module}.{a.name}")

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.source_lines):
            return self.source_lines[lineno - 1]
        return ""


@dataclasses.dataclass
class FunctionInfo:
    module: ModuleInfo
    qualname: str                       # Class.meth / func / func.inner
    node: ast.AST                       # FunctionDef | AsyncFunctionDef
    class_name: Optional[str]           # innermost enclosing class

    @property
    def key(self) -> Tuple[str, str]:
        return (self.module.rel, self.qualname)

    @property
    def is_async(self) -> bool:
        return isinstance(self.node, ast.AsyncFunctionDef)


@dataclasses.dataclass
class LockInfo:
    lock_id: str        # "Class.attr" or "modbase.name"
    kind: str           # Lock | RLock | Condition
    module: ModuleInfo
    line: int
    alias_of: Optional[str] = None   # Condition(self.x) -> underlying lock

    @property
    def reentrant(self) -> bool:
        return self.kind == "RLock"

    @property
    def attr(self) -> str:
        return self.lock_id.rsplit(".", 1)[-1]


def dotted(node: ast.AST) -> Optional[List[str]]:
    """['self','streams','get'] for self.streams.get; None if not a pure
    Name/Attribute chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        parts.reverse()
        return parts
    return None


def _is_lock_ctor(call: ast.AST) -> Optional[str]:
    """'Lock'/'RLock'/'Condition' if ``call`` constructs a threading
    primitive (threading.Lock() or bare Lock() via from-import)."""
    if not isinstance(call, ast.Call):
        return None
    chain = dotted(call.func)
    if not chain:
        return None
    if chain[-1] in LOCK_CTORS and (len(chain) == 1
                                    or chain[0] == "threading"):
        return chain[-1]
    return None


def collect_modules(paths: Sequence[str], root: str) -> List[ModuleInfo]:
    """Parse every .py under ``paths``; fixture modules are excluded from
    directory walks (tests pass them explicitly)."""
    files: List[str] = []
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isfile(p):
            files.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", ".git")]
            if (os.path.basename(dirpath) == "fixtures"
                    and os.path.basename(os.path.dirname(dirpath))
                    == "analysis"):
                continue
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    files.append(os.path.join(dirpath, fn))
    out = []
    for f in sorted(set(files)):
        rel = os.path.relpath(f, root).replace(os.sep, "/")
        try:
            with open(f, "r", encoding="utf-8") as fh:
                src = fh.read()
            out.append(ModuleInfo(f, rel, src))
        except (OSError, SyntaxError):
            continue
    return out


class Index:
    """Cross-module symbol, lock and type index."""

    def __init__(self, modules: List[ModuleInfo]):
        self.modules = modules
        self.functions: Dict[Tuple[str, str], FunctionInfo] = {}
        self.locks: Dict[str, LockInfo] = {}
        self.lock_attr_index: Dict[str, Set[str]] = {}
        # (rel, var) -> class qualname for module-level VAR = Class()
        self.instance_types: Dict[Tuple[str, str], str] = {}
        # (rel, Class, attr) -> class name for self.attr = Class()
        self.attr_types: Dict[Tuple[str, str, str], str] = {}
        # (rel, Class, attr) -> value-class for self.attr: Dict[K, V]
        self.dict_value_types: Dict[Tuple[str, str, str], str] = {}
        self.classes: Dict[str, List[str]] = {}   # name -> [rel, ...]
        self.mod_by_rel = {m.rel: m for m in modules}
        # dotted module name suffix -> rel (for import resolution)
        self.mod_by_name = {m.name: m.rel for m in modules}
        for m in modules:
            self._index_module(m)
        self._resolve_lock_aliases()

    # ---------------- construction ----------------

    def _index_module(self, m: ModuleInfo) -> None:
        def visit(node, qual: str, cls: Optional[str]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    q = f"{qual}.{child.name}" if qual else child.name
                    self.functions[(m.rel, q)] = FunctionInfo(
                        m, q, child, cls)
                    self._scan_self_assigns(m, cls, child)
                    visit(child, q, cls)
                elif isinstance(child, ast.ClassDef):
                    q = f"{qual}.{child.name}" if qual else child.name
                    self.classes.setdefault(child.name, []).append(m.rel)
                    visit(child, q, child.name)
                else:
                    if cls is None and qual == "":
                        self._scan_module_stmt(m, child)
        visit(m.tree, "", None)

    def _scan_module_stmt(self, m: ModuleInfo, stmt: ast.AST) -> None:
        if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
            return
        targets = (stmt.targets if isinstance(stmt, ast.Assign)
                   else [stmt.target])
        value = stmt.value
        if value is None:
            return
        kind = _is_lock_ctor(value)
        for t in targets:
            if not isinstance(t, ast.Name):
                continue
            if kind:
                lid = f"{m.base}.{t.id}"
                self._add_lock(LockInfo(lid, kind, m, stmt.lineno))
            elif isinstance(value, ast.Call):
                chain = dotted(value.func)
                if chain and chain[-1][:1].isupper():
                    self.instance_types[(m.rel, t.id)] = chain[-1]

    def _scan_self_assigns(self, m: ModuleInfo, cls: Optional[str],
                           fn: ast.AST) -> None:
        if cls is None:
            return
        for stmt in ast.walk(fn):
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and stmt is not fn:
                continue
            if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                continue
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            value = stmt.value
            for t in targets:
                if not (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    continue
                kind = _is_lock_ctor(value) if value is not None else None
                if kind:
                    li = LockInfo(f"{cls}.{t.attr}", kind, m, stmt.lineno)
                    if (kind == "Condition" and value.args
                            and dotted(value.args[0])
                            and dotted(value.args[0])[0] == "self"):
                        li.alias_of = f"{cls}.{dotted(value.args[0])[-1]}"
                    self._add_lock(li)
                elif value is not None and isinstance(value, ast.Call):
                    chain = dotted(value.func)
                    if chain and chain[-1][:1].isupper():
                        self.attr_types[(m.rel, cls, t.attr)] = chain[-1]
                if isinstance(stmt, ast.AnnAssign):
                    vt = _dict_value_class(stmt.annotation)
                    if vt:
                        self.dict_value_types[(m.rel, cls, t.attr)] = vt

    def _add_lock(self, li: LockInfo) -> None:
        if li.lock_id in self.locks:
            # keep the first definition; re-assignments are common
            return
        self.locks[li.lock_id] = li
        self.lock_attr_index.setdefault(li.attr, set()).add(li.lock_id)

    def _resolve_lock_aliases(self) -> None:
        for li in self.locks.values():
            if li.alias_of and li.alias_of not in self.locks:
                li.alias_of = None

    # ---------------- queries ----------------

    def canon_lock(self, lock_id: str) -> str:
        li = self.locks.get(lock_id)
        if li is not None and li.alias_of:
            return li.alias_of
        return lock_id

    def resolve_lock(self, expr: ast.AST, fn: FunctionInfo,
                     local_types: Dict[str, str]) -> Optional[str]:
        """Lock id for an expression like ``self._lock`` / ``st.cv`` /
        ``_metric_lock``; None if it isn't (or can't be proven) a lock."""
        chain = dotted(expr)
        if not chain:
            return None
        m = fn.module
        if chain[0] == "self" and fn.class_name and len(chain) == 2:
            lid = f"{fn.class_name}.{chain[1]}"
            if lid in self.locks:
                return self.canon_lock(lid)
        if len(chain) == 1:
            lid = f"{m.base}.{chain[0]}"
            if lid in self.locks:
                return self.canon_lock(lid)
            return None
        if len(chain) == 2 and chain[0] != "self":
            # typed local / param: st.cv with st: StreamState
            t = local_types.get(chain[0])
            if t:
                lid = f"{t}.{chain[1]}"
                if lid in self.locks:
                    return self.canon_lock(lid)
            # module-level singleton: _registry.lock
            cls = self.instance_types.get((m.rel, chain[0]))
            if cls:
                lid = f"{cls}.{chain[1]}"
                if lid in self.locks:
                    return self.canon_lock(lid)
            # imported module's lock: othermod._lock
            tgt = m.imports.get(chain[0])
            if tgt:
                lid = f"{tgt.rsplit('.', 1)[-1]}.{chain[1]}"
                if lid in self.locks:
                    return self.canon_lock(lid)
        # last resort: attr name unique across every known lock
        cands = self.lock_attr_index.get(chain[-1], set())
        if len(cands) == 1:
            return self.canon_lock(next(iter(cands)))
        return None

    def resolve_call(self, func_expr: ast.AST, fn: FunctionInfo,
                     local_types: Dict[str, str]
                     ) -> Optional[Tuple[str, str]]:
        """(rel, qualname) of the called function, if statically known."""
        chain = dotted(func_expr)
        if not chain:
            return None
        m = fn.module
        if len(chain) == 1:
            name = chain[0]
            k = (m.rel, f"{fn.qualname}.{name}")      # nested sibling
            if k in self.functions:
                return k
            if fn.class_name:
                k = (m.rel, f"{fn.class_name}.{name}")
                if k in self.functions:
                    return k
            k = (m.rel, name)
            if k in self.functions:
                return k
            return None
        recv, meth = chain[:-1], chain[-1]
        cls = None
        mod_rel = m.rel
        if recv == ["self"] and fn.class_name:
            cls = fn.class_name
        elif len(recv) == 2 and recv[0] == "self" and fn.class_name:
            cls = self.attr_types.get((m.rel, fn.class_name, recv[1]))
            if cls:
                mod_rel = self._class_module(cls, m) or m.rel
        elif len(recv) == 1:
            cls = local_types.get(recv[0]) \
                or self.instance_types.get((m.rel, recv[0]))
            if cls:
                mod_rel = self._class_module(cls, m) or m.rel
            else:
                tgt = m.imports.get(recv[0])
                if tgt:
                    rel = self._module_rel(tgt)
                    if rel and (rel, meth) in self.functions:
                        return (rel, meth)
                return None
        if cls:
            k = (mod_rel, f"{cls}.{meth}")
            if k in self.functions:
                return k
        return None

    def _class_module(self, cls: str, prefer: ModuleInfo) -> Optional[str]:
        rels = self.classes.get(cls) or []
        if prefer.rel in rels:
            return prefer.rel
        return rels[0] if len(rels) == 1 else None

    def _module_rel(self, dotted_name: str) -> Optional[str]:
        if dotted_name in self.mod_by_name:
            return self.mod_by_name[dotted_name]
        for name, rel in self.mod_by_name.items():
            if name.endswith("." + dotted_name) \
                    or dotted_name.endswith("." + name.rsplit(".", 1)[-1]):
                if name.rsplit(".", 1)[-1] == dotted_name.rsplit(".", 1)[-1]:
                    return rel
        return None

    def local_types_for(self, fn: FunctionInfo) -> Dict[str, str]:
        """Best-effort local-variable -> class-name map for ``fn``:
        parameter annotations, ``v = Class()``, and
        ``v = self.<dict-attr>.get/[...]`` via Dict[...] annotations."""
        out: Dict[str, str] = {}
        args = fn.node.args
        for a in (list(args.posonlyargs) + list(args.args)
                  + list(args.kwonlyargs)):
            if a.annotation is not None:
                ch = dotted(a.annotation)
                if ch and ch[-1] in self.classes:
                    out[a.arg] = ch[-1]
        for stmt in ast.walk(fn.node):
            if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1:
                continue
            t = stmt.targets[0]
            if not isinstance(t, ast.Name):
                continue
            v = stmt.value
            ch = dotted(v.func) if isinstance(v, ast.Call) else None
            if ch and ch[-1] in self.classes and len(ch) <= 2:
                out[t.id] = ch[-1]
            elif (ch and len(ch) == 3 and ch[0] == "self"
                  and ch[-1] == "get" and fn.class_name):
                vt = self.dict_value_types.get(
                    (fn.module.rel, fn.class_name, ch[1]))
                if vt:
                    out[t.id] = vt
            elif (isinstance(v, ast.Subscript)
                  and isinstance(v.value, ast.Attribute)
                  and isinstance(v.value.value, ast.Name)
                  and v.value.value.id == "self" and fn.class_name):
                vt = self.dict_value_types.get(
                    (fn.module.rel, fn.class_name, v.value.attr))
                if vt:
                    out[t.id] = vt
        return out


def _dict_value_class(ann: ast.AST) -> Optional[str]:
    """'StreamState' from an annotation like Dict[str, StreamState]."""
    if not isinstance(ann, ast.Subscript):
        return None
    base = dotted(ann.value)
    if not base or base[-1] not in ("Dict", "dict"):
        return None
    sl = ann.slice
    if isinstance(sl, ast.Tuple) and len(sl.elts) == 2:
        ch = dotted(sl.elts[1])
        if ch:
            return ch[-1]
    return None


# ---------------- blocking-call classification ----------------

# attribute names that denote a (potentially) blocking operation in this
# codebase: raw sockets, concurrent futures, thread joins, framed-RPC sends
# (Deferred.resolve/reject and ServerConn.push/reply do sock.sendall) and
# the blocking client RPC (.call / kv polls go through it).
BLOCKING_ATTRS = {
    "recv", "recv_into", "recvfrom", "accept", "connect", "sendall",
    "sendmsg", "join", "result", "call", "wait",
    "resolve", "reject", "push", "reply", "reply_error",
}
_JOIN_SAFE_ROOTS = {"os", "posixpath", "ntpath", "shlex", "string"}


def blocking_symbol(call: ast.Call, module: ModuleInfo,
                    held_attrs: Set[str]) -> Optional[str]:
    """Symbol like 'time.sleep' or '.recv' if ``call`` looks blocking;
    ``held_attrs`` are the attr-parts of currently-held locks (so
    ``cv.wait`` on the held condition is not flagged)."""
    func = call.func
    chain = dotted(func)
    if chain:
        if chain[-1] == "sleep" and (len(chain) == 1
                                     or chain[0] == "time"):
            # bare sleep only if imported from time
            if len(chain) > 1 or \
                    module.imports.get("sleep", "") == "time.sleep":
                return "time.sleep"
        if chain[0] in ("ray_tpu",) and chain[-1] in ("get", "wait") \
                and len(chain) == 2:
            return f"ray_tpu.{chain[-1]}"
    if not isinstance(func, ast.Attribute):
        return None
    attr = func.attr
    if attr not in BLOCKING_ATTRS:
        return None
    recv = func.value
    if attr == "join":
        if isinstance(recv, (ast.Constant, ast.JoinedStr)):
            return None                       # ",".join(...)
        if chain and (chain[0] in _JOIN_SAFE_ROOTS or "path" in chain[:-1]):
            return None                       # os.path.join
    if attr in ("wait", "acquire", "notify", "notify_all"):
        # condition-variable idiom: waiting on the lock you hold releases
        # it — not a held-across-blocking hazard
        if chain and (chain[-2] in held_attrs if len(chain) >= 2
                      else chain[0] in held_attrs):
            return None
    if chain and chain[0] == "asyncio":
        return None
    return f".{attr}"


def walk_calls(node: ast.AST):
    """Yield every Call lexically inside ``node``, NOT descending into
    nested function/class definitions or lambdas."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef, ast.Lambda)):
            continue
        if isinstance(n, ast.Call):
            yield n
        stack.extend(ast.iter_child_nodes(n))
