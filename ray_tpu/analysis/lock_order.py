"""Lock-order pass: acquisition-order cycles and locks held across
blocking calls.

Per function we track the lexically-held lock set through ``with``
statements, recording (held -> acquired) edges both for direct nested
acquisitions and — via a transitive-acquisition fixpoint over the resolved
call graph — for calls made while holding a lock.  Cycles in the resulting
digraph (Tarjan SCCs) are deadlock candidates; a self-edge on a
non-reentrant Lock/Condition is a guaranteed self-deadlock.

Blocking calls (socket recv/sendall, framed-RPC resolve/push/reply,
Future.result, thread join, blocking client .call, time.sleep) made while
any lock is held are flagged directly, and one call level deep (a call to
a function whose own body blocks).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ._model import (Finding, FunctionInfo, Index, blocking_symbol, dotted)

PASS = "lock_order"


class _FuncFacts:
    def __init__(self) -> None:
        # (held tuple, lock, line) for every acquisition site
        self.acquisitions: List[Tuple[Tuple[str, ...], str, int]] = []
        # (held tuple, callee key, display name, line)
        self.calls: List[Tuple[Tuple[str, ...],
                               Optional[Tuple[str, str]], str, int]] = []
        # (held tuple, symbol, line) for blocking calls in this body
        self.blocking: List[Tuple[Tuple[str, ...], str, int]] = []
        self.acq_direct: Set[str] = set()
        self.blocks_direct: bool = False
        self.acq_trans: Set[str] = set()


def _suppressed(fn: FunctionInfo, line: int) -> bool:
    return "# lock-ok" in fn.module.line_text(line)


def _scan_function(index: Index, fn: FunctionInfo) -> _FuncFacts:
    facts = _FuncFacts()
    local_types = index.local_types_for(fn)

    def held_attrs(held: Tuple[str, ...]) -> Set[str]:
        return {h.rsplit(".", 1)[-1] for h in held}

    def scan_expr(node: ast.AST, held: Tuple[str, ...]) -> None:
        for call in walk_calls_incl(node):
            chain = dotted(call.func)
            # explicit lock.acquire() counts as an acquisition event
            if (isinstance(call.func, ast.Attribute)
                    and call.func.attr == "acquire"):
                lock = index.resolve_lock(call.func.value, fn, local_types)
                if lock:
                    facts.acquisitions.append((held, lock, call.lineno))
                    continue
            if (isinstance(call.func, ast.Attribute)
                    and call.func.attr == "wait"):
                # cv.wait releases the condition's underlying lock: not a
                # held-across-blocking hazard when that lock is the one
                # held (directly or via a Condition alias)
                lk = index.resolve_lock(call.func.value, fn, local_types)
                if lk and lk in held:
                    continue
            sym = blocking_symbol(call, fn.module, held_attrs(held))
            if sym:
                facts.blocking.append((held, sym, call.lineno))
            callee = index.resolve_call(call.func, fn, local_types)
            name = ".".join(chain) if chain else "?"
            facts.calls.append((held, callee, name, call.lineno))

    def walk_calls_incl(node: ast.AST):
        # expression-level call walk that does not descend into lambdas
        stack = [node]
        while stack:
            n = stack.pop()
            if isinstance(n, ast.Lambda):
                continue
            if isinstance(n, ast.Call):
                yield n
            stack.extend(ast.iter_child_nodes(n))

    def scan_body(stmts, held: Tuple[str, ...]) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue    # nested defs run later, analyzed separately
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                inner = held
                for item in stmt.items:
                    scan_expr(item.context_expr, inner)
                    lock = index.resolve_lock(item.context_expr, fn,
                                              local_types)
                    if lock:
                        facts.acquisitions.append(
                            (inner, lock, stmt.lineno))
                        inner = inner + (lock,)
                scan_body(stmt.body, inner)
                continue
            # every direct expression child, then nested statement blocks
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    scan_expr(child, held)
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, field, None)
                if isinstance(sub, list) and sub and \
                        isinstance(sub[0], ast.stmt):
                    scan_body(sub, held)
            for h in getattr(stmt, "handlers", []) or []:
                if h.type is not None:
                    scan_expr(h.type, held)
                scan_body(h.body, held)

    scan_body(fn.node.body, ())
    facts.acq_direct = {a[1] for a in facts.acquisitions}
    facts.blocks_direct = bool(facts.blocking)
    return facts


def _budgeted_handlers(index: Index) -> Dict[Tuple[str, str], str]:
    """(module rel, handler function name) -> budgeted RPC method, from
    ``.handle("method", self.h_x)`` registration calls against the
    runtime budget table (rpc_stats.HANDLER_BUDGETS_MS).  A budgeted
    handler runs on a server event loop with a latency ceiling: holding
    a lock across a blocking call there is not a style warning, it is a
    stall of every connection — the lock-held-blocking pass promotes it
    to a distinct, never-baselined rule."""
    try:
        from ray_tpu._private.rpc_stats import HANDLER_BUDGETS_MS
    except Exception:   # analyzer must stand alone if the runtime moved
        return {}
    out: Dict[Tuple[str, str], str] = {}
    for m in index.modules:
        for node in ast.walk(m.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "handle"
                    and len(node.args) >= 2):
                continue
            meth = node.args[0]
            target = node.args[1]
            if not (isinstance(meth, ast.Constant)
                    and isinstance(meth.value, str)
                    and meth.value in HANDLER_BUDGETS_MS):
                continue
            if isinstance(target, ast.Attribute):
                out[(m.rel, target.attr)] = meth.value
            elif isinstance(target, ast.Name):
                out[(m.rel, target.id)] = meth.value
    return out


def run(index: Index) -> List[Finding]:
    facts: Dict[Tuple[str, str], _FuncFacts] = {}
    for key, fn in index.functions.items():
        facts[key] = _scan_function(index, fn)
    budgeted = _budgeted_handlers(index)

    def budget_method(fn: FunctionInfo) -> Optional[str]:
        # nested defs inside a handler (waiter closures etc.) run on the
        # same dispatch, so any qualname segment naming a budgeted
        # handler taints the whole function
        for seg in fn.qualname.split("."):
            meth = budgeted.get((fn.module.rel, seg))
            if meth is not None:
                return meth
        return None

    # transitive acquired-locks fixpoint over the resolved call graph
    for f in facts.values():
        f.acq_trans = set(f.acq_direct)
    changed = True
    rounds = 0
    while changed and rounds < 50:
        changed = False
        rounds += 1
        for f in facts.values():
            for _, callee, _, _ in f.calls:
                if callee and callee in facts:
                    extra = facts[callee].acq_trans - f.acq_trans
                    if extra:
                        f.acq_trans |= extra
                        changed = True

    findings: List[Finding] = []
    # edges: (src lock, dst lock) -> (file, func, line, via)
    edges: Dict[Tuple[str, str], Tuple[str, str, int, str]] = {}

    def add_edge(src: str, dst: str, fn: FunctionInfo, line: int,
                 via: str) -> None:
        if (src, dst) not in edges:
            edges[(src, dst)] = (fn.module.rel, fn.qualname, line, via)

    for key, f in facts.items():
        fn = index.functions[key]
        for held, lock, line in f.acquisitions:
            for h in held:
                if h != lock:
                    add_edge(h, lock, fn, line, "direct")
            if lock in held and not index.locks[lock].reentrant \
                    and not _suppressed(fn, line):
                findings.append(Finding(
                    PASS, "lock-self-reacquire", fn.module.rel,
                    fn.qualname, lock,
                    f"non-reentrant {lock} re-acquired while already "
                    f"held in {fn.qualname}", line))
        for held, callee, name, line in f.calls:
            if not held or callee not in facts:
                continue
            for lock in facts[callee].acq_trans:
                for h in held:
                    if h != lock:
                        add_edge(h, lock, fn, line, name)

    # acquisition-order cycles: Tarjan SCCs of the lock digraph
    for scc in _sccs({s for s, _ in edges} | {d for _, d in edges},
                     edges):
        if len(scc) < 2:
            continue
        members = sorted(scc)
        sites = []
        for (s, d), (rel, qn, line, via) in sorted(edges.items()):
            if s in scc and d in scc:
                sites.append(f"{s}->{d} at {rel}:{line} ({qn})")
        # anchor the finding to the first lock's definition site so the
        # key stays stable as call sites move around
        li = index.locks.get(members[0])
        rel = li.module.rel if li else "?"
        line = li.line if li else 0
        findings.append(Finding(
            PASS, "lock-order-cycle", rel, "", "<->".join(members),
            "lock acquisition-order cycle: " + "; ".join(sites), line))

    # locks held across blocking calls (direct + one call level deep)
    for key, f in facts.items():
        fn = index.functions[key]
        direct_lines = set()
        meth = budget_method(fn)
        for held, sym, line in f.blocking:
            if held and not _suppressed(fn, line):
                direct_lines.add(line)
                findings.append(Finding(
                    PASS, "lock-held-blocking", fn.module.rel,
                    fn.qualname, f"{held[-1]}:{sym}",
                    f"blocking call {sym} while holding "
                    f"{', '.join(held)} in {fn.qualname}", line))
                if meth is not None:
                    findings.append(Finding(
                        PASS, "budget-held-blocking", fn.module.rel,
                        fn.qualname, f"{meth}:{held[-1]}:{sym}",
                        f"blocking call {sym} while holding "
                        f"{', '.join(held)} in {fn.qualname} — handler "
                        f"of budgeted RPC {meth!r} "
                        f"(rpc_stats.HANDLER_BUDGETS_MS); it stalls the "
                        f"server event loop past its latency budget",
                        line))
        for held, callee, name, line in f.calls:
            if not held or callee not in facts:
                continue
            if line in direct_lines:
                continue    # already reported as a direct blocking call
            cf = facts[callee]
            if any(not ch for ch, _, _ in cf.blocking) \
                    and not _suppressed(fn, line):
                findings.append(Finding(
                    PASS, "lock-held-blocking", fn.module.rel,
                    fn.qualname, f"{held[-1]}:call:{name}",
                    f"call to {name} (which blocks) while holding "
                    f"{', '.join(held)} in {fn.qualname}", line))
                if meth is not None:
                    findings.append(Finding(
                        PASS, "budget-held-blocking", fn.module.rel,
                        fn.qualname, f"{meth}:{held[-1]}:call:{name}",
                        f"call to {name} (which blocks) while holding "
                        f"{', '.join(held)} in {fn.qualname} — handler "
                        f"of budgeted RPC {meth!r} "
                        f"(rpc_stats.HANDLER_BUDGETS_MS); it stalls the "
                        f"server event loop past its latency budget",
                        line))
    return findings


def _sccs(nodes: Set[str], edges: Dict[Tuple[str, str], object]):
    """Iterative Tarjan strongly-connected components."""
    adj: Dict[str, List[str]] = {n: [] for n in nodes}
    for (s, d) in edges:
        adj[s].append(d)
    idx: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on: Set[str] = set()
    stack: List[str] = []
    out: List[Set[str]] = []
    counter = [0]

    for root in sorted(nodes):
        if root in idx:
            continue
        work = [(root, 0)]
        while work:
            v, pi = work[-1]
            if pi == 0:
                idx[v] = low[v] = counter[0]
                counter[0] += 1
                stack.append(v)
                on.add(v)
            advanced = False
            for i in range(pi, len(adj[v])):
                w = adj[v][i]
                if w not in idx:
                    work[-1] = (v, i + 1)
                    work.append((w, 0))
                    advanced = True
                    break
                if w in on:
                    low[v] = min(low[v], idx[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == idx[v]:
                comp = set()
                while True:
                    w = stack.pop()
                    on.discard(w)
                    comp.add(w)
                    if w == v:
                        break
                out.append(comp)
    return out
