"""Blocking-in-async pass: blocking calls inside ``async def`` bodies.

An event loop that executes ``time.sleep``, a raw ``socket.recv``, a
blocking RPC ``.call`` (every KV poll goes through it), ``Future.result``
or ``ray_tpu.get`` stalls every coroutine on that loop.  Anything under an
``await`` is fine by construction; nested *sync* defs are excluded (they
run wherever they're called); ``# async-ok`` suppresses a site.
"""

from __future__ import annotations

import ast
from typing import List

from ._model import Finding, Index, blocking_symbol, walk_calls

PASS = "blocking_async"


def run(index: Index) -> List[Finding]:
    findings: List[Finding] = []
    for (rel, qual), fn in index.functions.items():
        if not fn.is_async:
            continue
        awaited = {id(c) for c in _awaited_calls(fn.node)}
        for call in walk_calls(fn.node):
            if id(call) in awaited:
                continue
            sym = blocking_symbol(call, fn.module, set())
            if sym is None:
                continue
            if "# async-ok" in fn.module.line_text(call.lineno):
                continue
            findings.append(Finding(
                PASS, "blocking-in-async", rel, qual, sym,
                f"blocking call {sym} inside async def {qual} "
                f"(stalls the event loop)", call.lineno))
    return findings


def _awaited_calls(root: ast.AST):
    for node in ast.walk(root):
        if isinstance(node, ast.Await) and \
                isinstance(node.value, ast.Call):
            yield node.value
