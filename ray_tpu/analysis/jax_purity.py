"""JAX-purity pass: impurity inside traced (jit/pjit/Pallas) functions.

Traced-function discovery (all static, nothing is imported):

- decorators: ``@jax.jit``, ``@jit``, ``@pjit``,
  ``@functools.partial(jax.jit, ...)`` (and the pjit forms);
- wrap-by-name: ``jax.jit(step)`` / ``jax.jit(functools.partial(step,
  ...))`` anywhere in the module marks the def named ``step`` in that
  module (including nested defs);
- Pallas kernels: the first argument of ``pl.pallas_call(kernel, ...)``.

Rules inside a traced body (nested defs included — they trace too):

- ``side-effect``      ``print`` / ``open`` / ``global`` (``jax.debug.print``
                       is allowed);
- ``host-call``        ``np.*`` calls (dtype/iinfo-style constants are
                       whitelisted) and ``.item()`` / ``.tolist()`` — these
                       force a device->host sync per trace;
- ``nondeterminism``   unseeded stdlib ``random.*``, ``np.random.*``,
                       ``time.time/monotonic/perf_counter`` — baked in at
                       trace time, silently frozen thereafter;
- ``unhashable-static`` a ``static_argnames`` parameter with a mutable
                       default, or a call site passing a list/dict/set
                       literal for one — every such call recompiles.

``# jax-ok`` on the offending line suppresses a site.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ._model import Finding, FunctionInfo, Index, dotted

PASS = "jax_purity"

_NP_WHITELIST = {
    "dtype", "float16", "float32", "float64", "int8", "int16", "int32",
    "int64", "uint8", "uint16", "uint32", "uint64", "bool_", "bfloat16",
    "iinfo", "finfo", "ndim", "shape", "issubdtype", "promote_types",
    "result_type", "can_cast",
}
_TIME_FNS = {"time", "monotonic", "perf_counter", "time_ns",
             "monotonic_ns", "perf_counter_ns"}


def _jit_chain(chain: Optional[List[str]]) -> bool:
    return bool(chain) and chain[-1] in ("jit", "pjit")


def _decorated_static_names(dec: ast.expr) -> Set[str]:
    """static_argnames from @functools.partial(jax.jit, static_argnames=..)"""
    out: Set[str] = set()
    if not isinstance(dec, ast.Call):
        return out
    for kw in dec.keywords:
        if kw.arg == "static_argnames":
            v = kw.value
            elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else \
                ([v] if isinstance(v, ast.Constant) else [])
            for e in elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    out.add(e.value)
    return out


def _traced_functions(index: Index) -> Dict[Tuple[str, str], Set[str]]:
    """(rel, qualname) -> static_argnames for every traced function."""
    traced: Dict[Tuple[str, str], Set[str]] = {}
    # pass 1: decorator-marked
    for key, fn in index.functions.items():
        node = fn.node
        for dec in getattr(node, "decorator_list", []):
            chain = dotted(dec)
            if _jit_chain(chain):
                traced.setdefault(key, set())
                continue
            if isinstance(dec, ast.Call):
                fchain = dotted(dec.func)
                if _jit_chain(fchain):
                    traced.setdefault(key, set()).update(
                        _decorated_static_names(dec))
                elif fchain and fchain[-1] == "partial" and dec.args:
                    if _jit_chain(dotted(dec.args[0])):
                        traced.setdefault(key, set()).update(
                            _decorated_static_names(dec))
    # pass 2: wrap-by-name (jax.jit(step)) and pallas_call(kernel)
    marked: Dict[str, Set[str]] = {}    # rel -> {bare names}
    for m in index.modules:
        names: Set[str] = set()
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = dotted(node.func)
            target: Optional[ast.expr] = None
            if _jit_chain(chain) and node.args:
                target = node.args[0]
            elif chain and chain[-1] == "pallas_call" and node.args:
                target = node.args[0]
            if target is None:
                continue
            if isinstance(target, ast.Call):     # partial(fn, ...)
                tch = dotted(target.func)
                if tch and tch[-1] == "partial" and target.args:
                    target = target.args[0]
            ch = dotted(target)
            if ch and len(ch) == 1:
                names.add(ch[0])
        if names:
            marked[m.rel] = names
    for key, fn in index.functions.items():
        rel, qual = key
        bare = qual.rsplit(".", 1)[-1]
        if bare in marked.get(rel, ()):
            traced.setdefault(key, set())
    return traced


def run(index: Index) -> List[Finding]:
    traced = _traced_functions(index)
    findings: List[Finding] = []
    for key, statics in sorted(traced.items()):
        fn = index.functions[key]
        findings.extend(_check_body(index, fn, statics))
        findings.extend(_check_static_defaults(fn, statics))
    # call-site check for unhashable static literals, module-local by name
    by_name: Dict[Tuple[str, str], Set[str]] = {}
    for (rel, qual), statics in traced.items():
        if statics:
            by_name[(rel, qual.rsplit(".", 1)[-1])] = statics
    if by_name:
        findings.extend(_check_call_sites(index, by_name))
    return findings


def _ok(fn: FunctionInfo, line: int) -> bool:
    return "# jax-ok" in fn.module.line_text(line)


def _check_body(index: Index, fn: FunctionInfo,
                statics: Set[str]) -> List[Finding]:
    out: List[Finding] = []
    mod = fn.module
    np_names = {k for k, v in mod.imports.items() if v == "numpy"}
    has_np = bool(np_names)
    has_random = mod.imports.get("random", "") == "random"
    has_time = mod.imports.get("time", "") == "time"

    def add(rule: str, detail: str, msg: str, line: int) -> None:
        if not _ok(fn, line):
            out.append(Finding(PASS, rule, mod.rel, fn.qualname,
                               detail, msg, line))

    for node in ast.walk(fn.node):
        if isinstance(node, ast.Global):
            add("side-effect", "global",
                f"`global` statement inside traced {fn.qualname}",
                node.lineno)
            continue
        if not isinstance(node, ast.Call):
            continue
        chain = dotted(node.func)
        if chain == ["print"] or chain == ["open"]:
            add("side-effect", chain[0],
                f"{chain[0]}() inside traced {fn.qualname} runs at "
                f"trace time only (use jax.debug.{chain[0]})",
                node.lineno)
        elif chain and chain[0] in np_names and len(chain) >= 2 \
                and has_np:
            if chain[1] == "random":
                add("nondeterminism", ".".join(chain),
                    f"unseeded {'.'.join(chain)} inside traced "
                    f"{fn.qualname} is frozen at trace time",
                    node.lineno)
            elif chain[-1] not in _NP_WHITELIST:
                add("host-call", ".".join(chain),
                    f"host numpy call {'.'.join(chain)} inside traced "
                    f"{fn.qualname} forces device->host sync",
                    node.lineno)
        elif chain and chain[0] == "random" and has_random \
                and len(chain) == 2:
            add("nondeterminism", ".".join(chain),
                f"unseeded stdlib {'.'.join(chain)} inside traced "
                f"{fn.qualname} is frozen at trace time", node.lineno)
        elif chain and chain[0] == "time" and has_time \
                and len(chain) == 2 and chain[1] in _TIME_FNS:
            add("nondeterminism", ".".join(chain),
                f"{'.'.join(chain)} inside traced {fn.qualname} is "
                f"frozen at trace time", node.lineno)
        elif isinstance(node.func, ast.Attribute) \
                and node.func.attr in ("item", "tolist") \
                and not node.args:
            add("host-call", f".{node.func.attr}",
                f".{node.func.attr}() inside traced {fn.qualname} "
                f"forces device->host sync", node.lineno)
    return out


def _check_static_defaults(fn: FunctionInfo,
                           statics: Set[str]) -> List[Finding]:
    out: List[Finding] = []
    args = fn.node.args
    defaults = list(args.defaults)
    # align trailing defaults with trailing positional args
    pos = list(args.posonlyargs) + list(args.args)
    pos_with_default = pos[len(pos) - len(defaults):] if defaults else []
    pairs = list(zip(pos_with_default, defaults)) + [
        (a, d) for a, d in zip(args.kwonlyargs, args.kw_defaults)
        if d is not None]
    for a, d in pairs:
        if a.arg in statics and isinstance(
                d, (ast.List, ast.Dict, ast.Set)):
            if not _ok(fn, d.lineno):
                out.append(Finding(
                    PASS, "unhashable-static", fn.module.rel,
                    fn.qualname, f"default:{a.arg}",
                    f"static arg {a.arg!r} of traced {fn.qualname} has "
                    f"an unhashable {type(d).__name__.lower()} default "
                    f"(jit will raise / recompile)", d.lineno))
    return out


def _check_call_sites(index: Index,
                      by_name: Dict[Tuple[str, str], Set[str]]
                      ) -> List[Finding]:
    out: List[Finding] = []
    for (rel, qual), fn in index.functions.items():
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            chain = dotted(node.func)
            if not chain or len(chain) != 1:
                continue
            statics = by_name.get((rel, chain[0]))
            if not statics:
                continue
            for kw in node.keywords:
                if kw.arg in statics and isinstance(
                        kw.value, (ast.List, ast.Dict, ast.Set)):
                    if not _ok(fn, node.lineno):
                        out.append(Finding(
                            PASS, "unhashable-static", rel, qual,
                            f"call:{chain[0]}:{kw.arg}",
                            f"unhashable literal passed for static arg "
                            f"{kw.arg!r} of {chain[0]} in {qual} "
                            f"(recompiles on every call)",
                            node.lineno))
    return out
