"""JAX-purity pass: impurity inside traced (jit/pjit/Pallas) functions.

Traced-function discovery (all static, nothing is imported):

- decorators: ``@jax.jit``, ``@jit``, ``@pjit``,
  ``@functools.partial(jax.jit, ...)`` (and the pjit forms);
- wrap-by-name: ``jax.jit(step)`` / ``jax.jit(functools.partial(step,
  ...))`` anywhere in the module marks the def named ``step`` in that
  module (including nested defs);
- Pallas kernels: the first argument of ``pl.pallas_call(kernel, ...)``.

Rules inside a traced body (nested defs included — they trace too):

- ``side-effect``      ``print`` / ``open`` / ``global`` (``jax.debug.print``
                       is allowed);
- ``host-call``        ``np.*`` calls (dtype/iinfo-style constants are
                       whitelisted) and ``.item()`` / ``.tolist()`` — these
                       force a device->host sync per trace;
- ``nondeterminism``   unseeded stdlib ``random.*``, ``np.random.*``,
                       ``time.time/monotonic/perf_counter`` — baked in at
                       trace time, silently frozen thereafter;
- ``unhashable-static`` a ``static_argnames`` parameter with a mutable
                       default, or a call site passing a list/dict/set
                       literal for one — every such call recompiles.

Plus one rule about where the jit wrap itself happens:

- ``jit-per-call``     ``jax.jit``/``pjit`` applied inside a ``for``/
                       ``while`` loop, or applied to a callable built
                       per call (local def, lambda, inline ``partial``)
                       and then invoked in the same function scope —
                       each outer call makes a fresh wrapper whose
                       trace cache is thrown away, so every call
                       recompiles.  Factories that *return* the jitted
                       callable are fine (the wrapper outlives the
                       scope).

``# jax-ok`` on the offending line suppresses a site.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ._model import Finding, FunctionInfo, Index, dotted

PASS = "jax_purity"

_NP_WHITELIST = {
    "dtype", "float16", "float32", "float64", "int8", "int16", "int32",
    "int64", "uint8", "uint16", "uint32", "uint64", "bool_", "bfloat16",
    "iinfo", "finfo", "ndim", "shape", "issubdtype", "promote_types",
    "result_type", "can_cast",
}
_TIME_FNS = {"time", "monotonic", "perf_counter", "time_ns",
             "monotonic_ns", "perf_counter_ns"}


def _jit_chain(chain: Optional[List[str]]) -> bool:
    return bool(chain) and chain[-1] in ("jit", "pjit")


def _decorated_static_names(dec: ast.expr) -> Set[str]:
    """static_argnames from @functools.partial(jax.jit, static_argnames=..)"""
    out: Set[str] = set()
    if not isinstance(dec, ast.Call):
        return out
    for kw in dec.keywords:
        if kw.arg == "static_argnames":
            v = kw.value
            elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else \
                ([v] if isinstance(v, ast.Constant) else [])
            for e in elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    out.add(e.value)
    return out


def _traced_functions(index: Index) -> Dict[Tuple[str, str], Set[str]]:
    """(rel, qualname) -> static_argnames for every traced function."""
    traced: Dict[Tuple[str, str], Set[str]] = {}
    # pass 1: decorator-marked
    for key, fn in index.functions.items():
        node = fn.node
        for dec in getattr(node, "decorator_list", []):
            chain = dotted(dec)
            if _jit_chain(chain):
                traced.setdefault(key, set())
                continue
            if isinstance(dec, ast.Call):
                fchain = dotted(dec.func)
                if _jit_chain(fchain):
                    traced.setdefault(key, set()).update(
                        _decorated_static_names(dec))
                elif fchain and fchain[-1] == "partial" and dec.args:
                    if _jit_chain(dotted(dec.args[0])):
                        traced.setdefault(key, set()).update(
                            _decorated_static_names(dec))
    # pass 2: wrap-by-name (jax.jit(step)) and pallas_call(kernel)
    marked: Dict[str, Set[str]] = {}    # rel -> {bare names}
    for m in index.modules:
        names: Set[str] = set()
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = dotted(node.func)
            target: Optional[ast.expr] = None
            if _jit_chain(chain) and node.args:
                target = node.args[0]
            elif chain and chain[-1] == "pallas_call" and node.args:
                target = node.args[0]
            if target is None:
                continue
            if isinstance(target, ast.Call):     # partial(fn, ...)
                tch = dotted(target.func)
                if tch and tch[-1] == "partial" and target.args:
                    target = target.args[0]
            ch = dotted(target)
            if ch and len(ch) == 1:
                names.add(ch[0])
        if names:
            marked[m.rel] = names
    for key, fn in index.functions.items():
        rel, qual = key
        bare = qual.rsplit(".", 1)[-1]
        if bare in marked.get(rel, ()):
            traced.setdefault(key, set())
    return traced


def run(index: Index) -> List[Finding]:
    traced = _traced_functions(index)
    findings: List[Finding] = []
    for key, statics in sorted(traced.items()):
        fn = index.functions[key]
        findings.extend(_check_body(index, fn, statics))
        findings.extend(_check_static_defaults(fn, statics))
    # call-site check for unhashable static literals, module-local by name
    by_name: Dict[Tuple[str, str], Set[str]] = {}
    for (rel, qual), statics in traced.items():
        if statics:
            by_name[(rel, qual.rsplit(".", 1)[-1])] = statics
    if by_name:
        findings.extend(_check_call_sites(index, by_name))
    findings.extend(_check_jit_per_call(index))
    return findings


def _ok(fn: FunctionInfo, line: int) -> bool:
    return "# jax-ok" in fn.module.line_text(line)


def _check_body(index: Index, fn: FunctionInfo,
                statics: Set[str]) -> List[Finding]:
    out: List[Finding] = []
    mod = fn.module
    np_names = {k for k, v in mod.imports.items() if v == "numpy"}
    has_np = bool(np_names)
    has_random = mod.imports.get("random", "") == "random"
    has_time = mod.imports.get("time", "") == "time"

    def add(rule: str, detail: str, msg: str, line: int) -> None:
        if not _ok(fn, line):
            out.append(Finding(PASS, rule, mod.rel, fn.qualname,
                               detail, msg, line))

    for node in ast.walk(fn.node):
        if isinstance(node, ast.Global):
            add("side-effect", "global",
                f"`global` statement inside traced {fn.qualname}",
                node.lineno)
            continue
        if not isinstance(node, ast.Call):
            continue
        chain = dotted(node.func)
        if chain == ["print"] or chain == ["open"]:
            add("side-effect", chain[0],
                f"{chain[0]}() inside traced {fn.qualname} runs at "
                f"trace time only (use jax.debug.{chain[0]})",
                node.lineno)
        elif chain and chain[0] in np_names and len(chain) >= 2 \
                and has_np:
            if chain[1] == "random":
                add("nondeterminism", ".".join(chain),
                    f"unseeded {'.'.join(chain)} inside traced "
                    f"{fn.qualname} is frozen at trace time",
                    node.lineno)
            elif chain[-1] not in _NP_WHITELIST:
                add("host-call", ".".join(chain),
                    f"host numpy call {'.'.join(chain)} inside traced "
                    f"{fn.qualname} forces device->host sync",
                    node.lineno)
        elif chain and chain[0] == "random" and has_random \
                and len(chain) == 2:
            add("nondeterminism", ".".join(chain),
                f"unseeded stdlib {'.'.join(chain)} inside traced "
                f"{fn.qualname} is frozen at trace time", node.lineno)
        elif chain and chain[0] == "time" and has_time \
                and len(chain) == 2 and chain[1] in _TIME_FNS:
            add("nondeterminism", ".".join(chain),
                f"{'.'.join(chain)} inside traced {fn.qualname} is "
                f"frozen at trace time", node.lineno)
        elif isinstance(node.func, ast.Attribute) \
                and node.func.attr in ("item", "tolist") \
                and not node.args:
            add("host-call", f".{node.func.attr}",
                f".{node.func.attr}() inside traced {fn.qualname} "
                f"forces device->host sync", node.lineno)
    return out


def _check_static_defaults(fn: FunctionInfo,
                           statics: Set[str]) -> List[Finding]:
    out: List[Finding] = []
    args = fn.node.args
    defaults = list(args.defaults)
    # align trailing defaults with trailing positional args
    pos = list(args.posonlyargs) + list(args.args)
    pos_with_default = pos[len(pos) - len(defaults):] if defaults else []
    pairs = list(zip(pos_with_default, defaults)) + [
        (a, d) for a, d in zip(args.kwonlyargs, args.kw_defaults)
        if d is not None]
    for a, d in pairs:
        if a.arg in statics and isinstance(
                d, (ast.List, ast.Dict, ast.Set)):
            if not _ok(fn, d.lineno):
                out.append(Finding(
                    PASS, "unhashable-static", fn.module.rel,
                    fn.qualname, f"default:{a.arg}",
                    f"static arg {a.arg!r} of traced {fn.qualname} has "
                    f"an unhashable {type(d).__name__.lower()} default "
                    f"(jit will raise / recompile)", d.lineno))
    return out


def _is_jit_dec(dec: ast.expr) -> bool:
    """Decorator forms: @jax.jit / @devtel.jit(name=..) /
    @functools.partial(jax.jit, ...)."""
    if _jit_chain(dotted(dec)):
        return True
    if isinstance(dec, ast.Call):
        fchain = dotted(dec.func)
        if _jit_chain(fchain):
            return True
        if fchain and fchain[-1] == "partial" and dec.args:
            return _jit_chain(dotted(dec.args[0]))
    return False


def _wrap_target(call: ast.Call) -> Tuple[str, bool]:
    """(display name, built-per-call?) for the callable a jit wrap
    receives.  Lambdas, inline ``partial(...)`` and other call results
    are fresh objects on every evaluation, so the jit cache they carry
    dies with the enclosing scope."""
    t = call.args[0]
    if isinstance(t, ast.Lambda):
        return "<lambda>", True
    if isinstance(t, ast.Call):
        tch = dotted(t.func)
        if tch and tch[-1] == "partial" and t.args:
            inner = dotted(t.args[0])
            return (inner[-1] if inner else "<partial>"), True
        return (tch[-1] if tch else "<call>") + "()", True
    ch = dotted(t)
    return (".".join(ch) if ch else "<expr>"), False


def _check_jit_per_call(index: Index) -> List[Finding]:
    """jit/pjit wraps whose cache cannot outlive the call: wraps inside
    a loop body, and wraps of per-call callables that are then invoked
    in the same function scope (the xla_group closure-jit bug class)."""
    out: List[Finding] = []
    for key, fn in sorted(index.functions.items()):
        rel, qual = key
        # immediate-scope walk with loop depth; nested def/class/lambda
        # bodies belong to their own FunctionInfo scope
        nodes: List[Tuple[ast.AST, int]] = []
        stack = [(c, 0) for c in ast.iter_child_nodes(fn.node)]
        while stack:
            n, depth = stack.pop()
            nodes.append((n, depth))
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef, ast.Lambda)):
                continue
            d = depth + 1 if isinstance(
                n, (ast.For, ast.AsyncFor, ast.While)) else depth
            stack.extend((c, d) for c in ast.iter_child_nodes(n))

        wraps: List[Tuple[ast.Call, int]] = []
        called_names: Set[str] = set()
        invoked_wraps: Set[int] = set()          # id() of jit(f)(x) wraps
        assigns: List[ast.Assign] = []
        nested_defs: List[Tuple[ast.AST, int]] = []
        for n, depth in nodes:
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                nested_defs.append((n, depth))
                continue
            if isinstance(n, ast.Assign):
                assigns.append(n)
            if not isinstance(n, ast.Call):
                continue
            if isinstance(n.func, ast.Name):
                called_names.add(n.func.id)
            if isinstance(n.func, ast.Call):
                invoked_wraps.add(id(n.func))
            if _jit_chain(dotted(n.func)) and n.args:
                wraps.append((n, depth))

        def add(detail: str, msg: str, line: int) -> None:
            if not _ok(fn, line):
                out.append(Finding(PASS, "jit-per-call", rel, qual,
                                   detail, msg, line))

        for call, depth in wraps:
            name, per_call = _wrap_target(call)
            if depth > 0:
                add(f"loop:{name}",
                    f"jit({name}) inside a loop in {qual} builds a "
                    f"fresh wrapper (and trace cache) per iteration — "
                    f"hoist the jit out of the loop", call.lineno)
                continue
            local_def = (not per_call and "." not in name
                         and (rel, f"{qual}.{name}") in index.functions)
            if not (per_call or local_def):
                continue
            # invoked in this scope?  directly (jit(f)(x)) or via a
            # name it was assigned to
            invoked = id(call) in invoked_wraps
            if not invoked:
                for a in assigns:
                    if any(n is call for n in ast.walk(a.value)):
                        invoked = any(
                            isinstance(t, ast.Name)
                            and t.id in called_names for t in a.targets)
                        if invoked:
                            break
            if invoked:
                add(f"closure:{name}",
                    f"jit({name}) wraps a per-call callable and is "
                    f"invoked in the same scope ({qual}) — every call "
                    f"of {qual} recompiles; hoist the jit to module "
                    f"scope or return the wrapper", call.lineno)

        for nd, depth in nested_defs:
            decs = [d for d in getattr(nd, "decorator_list", [])
                    if _is_jit_dec(d)]
            if not decs:
                continue
            line = decs[0].lineno
            if depth > 0:
                add(f"loop:{nd.name}",
                    f"@jit def {nd.name} inside a loop in {qual} "
                    f"recompiles every iteration — hoist it out",
                    line)
            elif nd.name in called_names:
                add(f"closure:{nd.name}",
                    f"@jit def {nd.name} is local to {qual} and called "
                    f"there — every call of {qual} recompiles; hoist "
                    f"the jitted def or return it", line)
    return out


def _check_call_sites(index: Index,
                      by_name: Dict[Tuple[str, str], Set[str]]
                      ) -> List[Finding]:
    out: List[Finding] = []
    for (rel, qual), fn in index.functions.items():
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            chain = dotted(node.func)
            if not chain or len(chain) != 1:
                continue
            statics = by_name.get((rel, chain[0]))
            if not statics:
                continue
            for kw in node.keywords:
                if kw.arg in statics and isinstance(
                        kw.value, (ast.List, ast.Dict, ast.Set)):
                    if not _ok(fn, node.lineno):
                        out.append(Finding(
                            PASS, "unhashable-static", rel, qual,
                            f"call:{chain[0]}:{kw.arg}",
                            f"unhashable literal passed for static arg "
                            f"{kw.arg!r} of {chain[0]} in {qual} "
                            f"(recompiles on every call)",
                            node.lineno))
    return out
