"""Baseline load/save/diff for the analyzer.

``analysis_baseline.json`` (checked in at the repo root) records every
known finding by its stable key.  ``diff()`` splits a fresh scan into
(new, known, stale): new findings fail CI, stale baseline entries are
reported informationally so the baseline can be re-shrunk with
``ray-tpu analyze --update-baseline``.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Tuple

from ._model import Finding, repo_root

VERSION = 1
DEFAULT_NAME = "analysis_baseline.json"


def default_path() -> str:
    return os.path.join(repo_root(), DEFAULT_NAME)


def load(path: str) -> Dict[str, dict]:
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    if data.get("version") != VERSION:
        raise ValueError(
            f"unsupported baseline version {data.get('version')!r} "
            f"in {path}")
    return dict(data.get("findings", {}))


def save(path: str, findings: List[Finding]) -> None:
    payload = {
        "version": VERSION,
        "findings": {
            f.key: {"line": f.line, "message": f.message}
            for f in sorted(findings, key=lambda f: f.key)
        },
    }
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=1, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)


def diff(findings: List[Finding], known: Dict[str, dict]
         ) -> Tuple[List[Finding], List[Finding], List[str]]:
    """-> (new, suppressed, stale_keys)."""
    new, suppressed = [], []
    seen = set()
    for f in findings:
        seen.add(f.key)
        (suppressed if f.key in known else new).append(f)
    stale = sorted(k for k in known if k not in seen)
    return new, suppressed, stale
