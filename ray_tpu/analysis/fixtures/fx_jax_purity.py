"""Seeded violations for the jax_purity pass (parsed, never imported).

Expected findings:
- side-effect        print in impure_print() and in kernel()
- host-call          np.asarray and .item() in host_pull()
- nondeterminism     random.random and time.time in nondet()
- unhashable-static  list default of bad_static(); list literal at the
                     caller() call site
- jit-per-call       jit built inside the loop of loop_jit(); jit of a
                     local def invoked in the same scope in
                     per_call_closure() and per_call_decorated()
                     (jit_factory(), which RETURNS the wrapper, is the
                     negative control)
"""

import functools
import random
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl


@jax.jit
def impure_print(x):
    print("tracing", x)
    return x + 1


@functools.partial(jax.jit, static_argnames=("block",))
def host_pull(x, block=(8, 8)):
    y = np.asarray(x)
    return jnp.sum(jnp.asarray(y)) + x.sum().item()


@jax.jit
def nondet(x):
    return x * random.random() + time.time()


@functools.partial(jax.jit, static_argnames=("cfg",))
def bad_static(x, cfg=[1, 2]):
    return x


def caller(x):
    return bad_static(x, cfg=[3, 4])


def kernel(x_ref, o_ref):
    print("side effect")
    o_ref[...] = x_ref[...]


def run_kernel(x):
    return pl.pallas_call(kernel, out_shape=x)(x)


def clean(x):
    # untraced: nothing here should be flagged
    print("host side is fine")
    return np.asarray(x)


def loop_jit(xs):
    out = []
    for x in xs:
        g = jax.jit(lambda v: v + 1)
        out.append(g(x))
    return out


def per_call_closure(x):
    def inner(v):
        return v * 2

    f = jax.jit(inner)
    return f(x)


def per_call_decorated(x):
    @jax.jit
    def inner2(v):
        return v - 1

    return inner2(x)


def jit_factory(scale):
    # negative control: the wrapper is returned, so its trace cache
    # outlives this scope — a legitimate factory
    def inner(v):
        return v * scale

    return jax.jit(inner)
