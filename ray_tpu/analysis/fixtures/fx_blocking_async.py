"""Seeded violations for the blocking_async pass (parsed, never imported).

Expected findings:
- blocking-in-async  time.sleep in bad_sleep()
- blocking-in-async  sock.recv in bad_recv()

Non-findings: awaited asyncio.sleep, the nested sync def, # async-ok.
"""

import asyncio
import time


async def bad_sleep():
    time.sleep(0.1)


async def bad_recv(sock):
    return sock.recv(10)


async def good():
    await asyncio.sleep(0)

    def inner():
        time.sleep(0.1)     # sync nested def: runs off-loop

    time.sleep(0)           # async-ok
    return inner
