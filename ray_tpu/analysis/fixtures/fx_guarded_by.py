"""Seeded violations for the guarded_by pass (parsed, never imported).

Expected findings:
- unguarded-access  Counter.n read in bad() without self._lock

Non-findings: good() holds the lock, helper() declares `# holds: _lock`,
peek() is suppressed with `# unguarded-ok`, __init__ is exempt.
"""

import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0          # guarded-by: _lock

    def good(self):
        with self._lock:
            self.n += 1
            return self.n

    def bad(self):
        return self.n

    def helper(self):       # holds: _lock
        self.n -= 1

    def peek(self):
        return self.n       # unguarded-ok
