"""Seeded violations for the lock_order pass (parsed, never imported).

Expected findings:
- lock-order-cycle   Widget.a <-> Widget.b  (one() nests a->b, two()
                     reaches b->a through helper())
- lock-held-blocking time.sleep and sock.recv under Widget.a in blocky()
- lock-held-blocking call to slow_io (which blocks) under Widget.a
- lock-self-reacquire Widget.a in reenter()
"""

import socket
import threading
import time


def slow_io(sock):
    return sock.recv(4096)


class Widget:
    def __init__(self):
        self.a = threading.Lock()
        self.b = threading.Lock()

    def one(self):
        with self.a:
            with self.b:
                return 1

    def two(self):
        with self.b:
            self.helper()

    def helper(self):
        with self.a:
            return 2

    def blocky(self, sock: socket.socket):
        with self.a:
            time.sleep(0.1)
            sock.recv(1024)

    def via_callee(self, sock):
        with self.a:
            slow_io(sock)

    def reenter(self):
        with self.a:
            with self.a:
                return 3

    def clean(self):
        with self.a:
            x = 1
        time.sleep(0)       # not held: no finding
        return x
