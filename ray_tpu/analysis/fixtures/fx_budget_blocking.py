"""Seeded violations for the budget-promotion rule (parsed, never
imported).

``kv_put`` is in rpc_stats.HANDLER_BUDGETS_MS, ``wait_thing`` is not.
Expected findings:
- lock-held-blocking    in h_kv_put AND h_wait_thing (time.sleep under
                        MiniServer.lock)
- budget-held-blocking  ONLY in h_kv_put — the handler of a budgeted
                        RPC; the unbudgeted handler stays a plain
                        (baselinable) lock-held-blocking warning
"""

import threading
import time


class MiniServer:
    def __init__(self, server):
        self.lock = threading.Lock()
        server.handle("kv_put", self.h_kv_put)          # budgeted
        server.handle("wait_thing", self.h_wait_thing)  # not budgeted

    def h_kv_put(self, conn, p):
        with self.lock:
            time.sleep(0.1)
        return True

    def h_wait_thing(self, conn, p):
        with self.lock:
            time.sleep(0.1)
        return True

    def h_clean(self, conn, p):
        with self.lock:
            x = 1
        time.sleep(0)       # not held: no finding
        return x
