"""Repo-aware static analysis for ray_tpu (``ray-tpu analyze``).

Four AST-based passes over the runtime sources:

- ``lock_order``      acquisition-order cycles + locks held across blocking
                      calls, interprocedural across the concurrency-heavy
                      modules (core, control, worker_proc, recorder, engine,
                      metrics).
- ``guarded_by``      ``# guarded-by: <lock>`` annotations on shared mutable
                      attributes, checked at every access site.
- ``blocking_async``  blocking calls (time.sleep / socket / RPC) inside
                      ``async def`` bodies in serve/, dag/, util/client/.
- ``jax_purity``      Python side effects, host np./.item() pulls, unseeded
                      random/time nondeterminism and unhashable static args
                      inside jit/pjit/Pallas-traced functions.

Findings carry stable keys (no line numbers) and are diffed against a
checked-in ``analysis_baseline.json``: pre-existing findings are suppressed,
any *new* finding fails CI.
"""

from __future__ import annotations

import os
from typing import Iterable, List, Optional, Sequence

from ._model import Finding, Index, collect_modules, repo_root
from . import baseline
from .lock_order import run as _run_lock_order
from .guarded_by import run as _run_guarded_by
from .blocking_async import run as _run_blocking_async
from .jax_purity import run as _run_jax_purity

__all__ = [
    "Finding",
    "Index",
    "PASSES",
    "baseline",
    "collect_modules",
    "repo_root",
    "run_analysis",
]

# pass name -> (runner, default report scope: rel-path prefixes, or None=all)
PASSES = {
    "lock_order": (_run_lock_order, (
        "ray_tpu/_private/core.py",
        "ray_tpu/_private/control.py",
        "ray_tpu/_private/worker_proc.py",
        "ray_tpu/telemetry/recorder.py",
        "ray_tpu/serve/_engine.py",
        "ray_tpu/serve/_router.py",
        "ray_tpu/util/metrics.py",
    )),
    "guarded_by": (_run_guarded_by, None),
    "blocking_async": (_run_blocking_async, (
        "ray_tpu/serve/",
        "ray_tpu/dag/",
        "ray_tpu/util/client/",
    )),
    "jax_purity": (_run_jax_purity, (
        "ray_tpu/ops/",
        "ray_tpu/models/",
        "ray_tpu/collective/",
        "ray_tpu/parallel/",
    )),
}


def run_analysis(paths: Optional[Sequence[str]] = None,
                 root: Optional[str] = None,
                 passes: Optional[Iterable[str]] = None) -> List[Finding]:
    """Analyze ``paths`` (files or directories; default: the ray_tpu pkg).

    Directory scans report each pass only within its default scope;
    explicitly listed *files* are reported by every pass (this is how the
    fixture modules are driven from tests).  Returns findings with unique
    keys (duplicate sites get ``#n`` ordinals).
    """
    root = os.path.abspath(root or repo_root())
    if not paths:
        paths = [os.path.join(root, "ray_tpu")]
    explicit: set = set()
    for p in paths:
        if os.path.isfile(p):
            explicit.add(os.path.relpath(os.path.abspath(p), root)
                         .replace(os.sep, "/"))
    modules = collect_modules(paths, root)
    index = Index(modules)
    findings: List[Finding] = []
    for name, (runner, scope) in PASSES.items():
        if passes is not None and name not in passes:
            continue
        got = runner(index)
        if scope is not None:
            got = [f for f in got
                   if f.file in explicit
                   or any(f.file == s or (s.endswith("/")
                                          and f.file.startswith(s))
                          for s in scope)]
        findings.extend(got)
    return _assign_keys(findings)


def _assign_keys(findings: List[Finding]) -> List[Finding]:
    """Dedupe identical sites and give repeats stable ``#n`` ordinals."""
    seen = {}
    out = []
    for f in sorted(findings, key=lambda f: (f.file, f.line, f.rule,
                                             f.detail)):
        base = f.key
        n = seen.get(base, 0)
        seen[base] = n + 1
        if n:
            f.ordinal = n
        out.append(f)
    return out
