"""Guarded-by pass: ``# guarded-by: <lock>`` annotation checking.

Convention (opt-in, per attribute):

    self._pending: List[int] = []   # guarded-by: _lock

declares that every read or write of ``self._pending`` anywhere in the
class must happen lexically inside ``with self._lock`` (or the Condition
aliased onto it).  Escape hatches:

- ``def flush(self):  # holds: _lock`` — the whole function runs with the
  lock held (callers acquire it);
- a ``# unguarded-ok`` trailing comment on an access line suppresses that
  single site (e.g. intentional lock-free fast paths).

``__init__`` is exempt (no concurrent access before construction
completes).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Tuple

from ._model import Finding, FunctionInfo, Index

PASS = "guarded_by"
_GUARD_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_]\w*)")
_HOLDS_RE = re.compile(r"#\s*holds:\s*([A-Za-z_]\w*)")
_OK_RE = re.compile(r"#\s*unguarded-ok\b")


def _annotations(index: Index) -> Dict[Tuple[str, str, str], str]:
    """(rel, Class, attr) -> guarding lock attr, from init-time
    assignments with a trailing guarded-by comment."""
    out: Dict[Tuple[str, str, str], str] = {}
    for (rel, qual), fn in index.functions.items():
        if fn.class_name is None:
            continue
        for stmt in ast.walk(fn.node):
            if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                continue
            m = _GUARD_RE.search(fn.module.line_text(stmt.lineno))
            if not m:
                continue
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            for t in targets:
                if (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    out[(rel, fn.class_name, t.attr)] = m.group(1)
    return out


def run(index: Index) -> List[Finding]:
    guards = _annotations(index)
    if not guards:
        return []
    by_class: Dict[Tuple[str, str], Dict[str, str]] = {}
    for (rel, cls, attr), lock in guards.items():
        by_class.setdefault((rel, cls), {})[attr] = lock

    findings: List[Finding] = []
    for (rel, qual), fn in index.functions.items():
        cls = fn.class_name
        if cls is None or (rel, cls) not in by_class:
            continue
        if fn.node.name == "__init__":
            continue
        attrs = by_class[(rel, cls)]
        held_default = frozenset()
        mh = _HOLDS_RE.search(fn.module.line_text(fn.node.lineno))
        if mh:
            held_default = frozenset([mh.group(1)])
        findings.extend(_check_function(index, fn, attrs, held_default))
    return findings


def _check_function(index: Index, fn: FunctionInfo,
                    attrs: Dict[str, str],
                    held_default: frozenset) -> List[Finding]:
    out: List[Finding] = []
    seen: set = set()
    cls = fn.class_name

    def lock_names(lock_attr: str) -> frozenset:
        """The annotated lock plus any Condition aliased onto it."""
        names = {lock_attr}
        for lid, li in index.locks.items():
            if li.alias_of == f"{cls}.{lock_attr}" \
                    and lid.startswith(f"{cls}."):
                names.add(li.attr)
            if lid == f"{cls}.{lock_attr}" and li.alias_of:
                names.add(li.alias_of.rsplit(".", 1)[-1])
        return frozenset(names)

    def scan(stmts, held: frozenset) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue   # nested defs may run without the lock: their
                # accesses are checked only if they are functions in the
                # index with their own holds: annotation
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                inner = held
                for item in stmt.items:
                    ce = item.context_expr
                    if (isinstance(ce, ast.Attribute)
                            and isinstance(ce.value, ast.Name)
                            and ce.value.id == "self"):
                        inner = inner | {ce.attr}
                    check_expr(item.context_expr, held)
                scan(stmt.body, inner)
                continue
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    check_expr(child, held)
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, field, None)
                if isinstance(sub, list) and sub and \
                        isinstance(sub[0], ast.stmt):
                    scan(sub, held)
            for h in getattr(stmt, "handlers", []) or []:
                scan(h.body, held)

    def check_expr(node: ast.AST, held: frozenset) -> None:
        stack = [node]
        while stack:
            n = stack.pop()
            if isinstance(n, ast.Lambda):
                continue
            if (isinstance(n, ast.Attribute)
                    and isinstance(n.value, ast.Name)
                    and n.value.id == "self" and n.attr in attrs):
                lock = attrs[n.attr]
                if not (lock_names(lock) & held):
                    if not _OK_RE.search(
                            fn.module.line_text(n.lineno)):
                        site = (n.attr, n.lineno)
                        if site not in seen:
                            seen.add(site)
                            out.append(Finding(
                                PASS, "unguarded-access",
                                fn.module.rel, fn.qualname, n.attr,
                                f"self.{n.attr} (guarded-by "
                                f"{lock}) accessed without holding "
                                f"self.{lock} in {fn.qualname}",
                                n.lineno))
            stack.extend(ast.iter_child_nodes(n))

    scan(fn.node.body, held_default)
    return out
