from .attention import (attention, blockwise_attention, flash_attention,
                        flash_attention_with_lse, mha_reference)
from .layers import (apply_rope, fused_softmax_cross_entropy, gelu_mlp,
                     layer_norm, rms_norm, rope_table,
                     softmax_cross_entropy, swiglu)
from .quantize import (dequantize_blockwise, quantization_error,
                       quantize_blockwise)
from .ring_attention import ring_attention, ring_attention_sharded
from .ulysses import ulysses_attention, ulysses_attention_sharded

__all__ = [
    "quantize_blockwise", "dequantize_blockwise", "quantization_error",
    "attention", "flash_attention", "flash_attention_with_lse",
    "blockwise_attention", "mha_reference",
    "ring_attention", "ring_attention_sharded",
    "ulysses_attention", "ulysses_attention_sharded",
    "rms_norm", "layer_norm", "rope_table", "apply_rope", "swiglu",
    "gelu_mlp", "softmax_cross_entropy", "fused_softmax_cross_entropy",
]
