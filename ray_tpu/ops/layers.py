"""Core layer math: rmsnorm, rope, activations — XLA-fusable building blocks.

XLA fuses these elementwise chains into surrounding matmuls (the HBM-
bandwidth recipe); they are written shape-polymorphic so the same code runs
under any sharding.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def rms_norm(x, weight, eps: float = 1e-6):
    """RMSNorm in f32 accumulation, cast back to input dtype."""
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    y = x32 * jax.lax.rsqrt(var + eps)
    return (y * weight.astype(jnp.float32)).astype(x.dtype)


def layer_norm(x, weight, bias, eps: float = 1e-5):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    y = (x32 - mu) * jax.lax.rsqrt(var + eps)
    return (y * weight.astype(jnp.float32)
            + bias.astype(jnp.float32)).astype(x.dtype)


def rope_table(seq_len: int, head_dim: int, base: float = 10000.0,
               dtype=jnp.float32) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Precomputed cos/sin tables [seq, head_dim/2]."""
    half = head_dim // 2
    freqs = base ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    pos = jnp.arange(seq_len, dtype=jnp.float32)
    angles = jnp.outer(pos, freqs)
    return jnp.cos(angles).astype(dtype), jnp.sin(angles).astype(dtype)


def apply_rope(x, cos, sin, positions: Optional[jnp.ndarray] = None):
    """Rotary embedding for [B, H, S, D] with tables [S_max, D/2].

    positions: optional [S] global positions (sequence-parallel chunks pass
    their offsets); defaults to arange(S).
    """
    b, h, s, d = x.shape
    if positions is None:
        c = cos[:s][None, None]
        sn = sin[:s][None, None]
    else:
        c = cos[positions][None, None]
        sn = sin[positions][None, None]
    x1, x2 = jnp.split(x, 2, axis=-1)
    y1 = x1 * c - x2 * sn
    y2 = x2 * c + x1 * sn
    return jnp.concatenate([y1, y2], axis=-1).astype(x.dtype)


def swiglu(x, w_gate, w_up, w_down):
    """SwiGLU MLP: silu(x@Wg) * (x@Wu) @ Wd, bf16-friendly."""
    g = jnp.einsum("...d,df->...f", x, w_gate)
    u = jnp.einsum("...d,df->...f", x, w_up)
    return jnp.einsum("...f,fd->...d", jax.nn.silu(g) * u, w_down)


def gelu_mlp(x, w_in, b_in, w_out, b_out):
    h = jnp.einsum("...d,df->...f", x, w_in) + b_in
    return jnp.einsum("...f,fd->...d", jax.nn.gelu(h), w_out) + b_out


def fused_softmax_cross_entropy(x, unembed, labels, z_loss: float = 0.0,
                                chunk: int = 128):
    """Vocab-projected CE WITHOUT materializing [B, S, V] logits: scan
    over sequence chunks; each chunk's logits exist only inside its
    (checkpointed) scan step, so peak memory is [B, chunk, V] and the
    bwd pass recomputes chunk logits instead of reading a stored f32
    logits tensor — on HBM-bandwidth-bound steps the recompute is
    cheaper than the traffic.  Numerically identical to the dense path:
    both einsum in x.dtype and upcast to f32 for the logsumexp.

    x [B, S, D] (compute dtype), unembed [D, V], labels [B, S] int.
    Returns per-token loss [B, S] (f32).
    """
    B, S, D = x.shape
    assert S % chunk == 0, (S, chunk)
    n = S // chunk
    xs = jnp.moveaxis(x.reshape(B, n, chunk, D), 1, 0)     # [n, B, c, D]
    ls = jnp.moveaxis(labels.reshape(B, n, chunk), 1, 0)   # [n, B, c]

    @jax.checkpoint
    def chunk_loss(xc, lc):
        logits = jnp.einsum("bcd,dv->bcv", xc, unembed).astype(jnp.float32)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        out = lse - jnp.take_along_axis(logits, lc[..., None],
                                        axis=-1)[..., 0]
        if z_loss:
            out = out + z_loss * jnp.square(lse)
        return out

    _, losses = jax.lax.scan(lambda _, t: (None, chunk_loss(*t)),
                             None, (xs, ls))               # [n, B, c]
    return jnp.moveaxis(losses, 0, 1).reshape(B, S)


def softmax_cross_entropy(logits, labels, z_loss: float = 0.0):
    """Token-level CE in f32 with optional z-loss (stabilizes large-vocab
    training); logits [..., V], labels [...] int."""
    logits = logits.astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    ll = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    loss = lse - ll
    if z_loss:
        loss = loss + z_loss * jnp.square(lse)
    return loss
