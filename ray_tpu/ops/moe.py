"""Mixture-of-experts with expert parallelism (EP).

Absent from the reference (SURVEY.md §2.3: "EP (expert parallel): absent
in-tree — expert-sharded mesh axis + lax.all_to_all token dispatch"); built
natively here, TPU-first:

  * Routing uses the dense one-hot dispatch/combine formulation
    (GShard/Switch): static shapes, pure einsums — everything tiles onto
    the MXU and nothing falls off the compiled path.  Capacity is a static
    bound; overflow tokens are dropped (their combine weight is zero), the
    standard TPU MoE trade.
  * Expert parallelism is one `lax.all_to_all` each way over the `ep` mesh
    axis inside shard_map: dispatch [E, C, D] -> [E/n, n*C, D] so each
    device runs only its local experts, then the inverse on the way back.
  * Aux losses (load-balance, router z-loss) are returned to the caller —
    the trainer adds them to the objective.
"""

from __future__ import annotations

import math
from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp


class RouterOut(NamedTuple):
    dispatch: jax.Array   # [T, E, C] 0/1 dispatch tensor
    combine: jax.Array    # [T, E, C] gate-weighted combine tensor
    aux_loss: jax.Array   # scalar load-balance loss
    z_loss: jax.Array     # scalar router z-loss


def expert_capacity(num_tokens: int, num_experts: int, k: int,
                    capacity_factor: float) -> int:
    """Static per-expert token budget (multiple of 8 for TPU tiling)."""
    c = int(math.ceil(k * num_tokens / num_experts * capacity_factor))
    return max(8, ((c + 7) // 8) * 8)


def route_topk(logits: jax.Array, k: int, capacity: int) -> RouterOut:
    """Top-k routing with slot-priority positioning (GShard).

    logits: [T, E] router scores.  Returns dense dispatch/combine tensors
    [T, E, C]; tokens beyond an expert's capacity get zero weight.
    """
    T, E = logits.shape
    compute_dtype = jnp.promote_types(logits.dtype, jnp.float32)
    probs = jax.nn.softmax(logits.astype(compute_dtype), axis=-1)
    gate_vals, expert_idx = jax.lax.top_k(probs, k)          # [T, k]
    gate_vals = gate_vals / (gate_vals.sum(-1, keepdims=True) + 1e-9)

    dispatch = jnp.zeros((T, E, capacity), compute_dtype)
    combine = jnp.zeros((T, E, capacity), compute_dtype)
    counts = jnp.zeros((E,), compute_dtype)
    for j in range(k):  # k is tiny (1-2): unrolled at trace time
        oh = jax.nn.one_hot(expert_idx[:, j], E, dtype=compute_dtype)  # [T, E]
        pos = jnp.cumsum(oh, axis=0) - 1.0 + counts[None, :]  # queue position
        counts = counts + oh.sum(axis=0)
        within = (pos < capacity) * oh                        # [T, E]
        pos_oh = jax.nn.one_hot(jnp.clip(pos, 0, capacity - 1).astype(jnp.int32),
                                capacity, dtype=compute_dtype)
        slot = pos_oh * within[..., None]                     # [T, E, C]
        dispatch = dispatch + slot
        combine = combine + slot * gate_vals[:, j][:, None, None]

    # load-balance: E * sum_e fraction_dispatched_e * mean_router_prob_e
    # (Switch Transformer eq. 4, over the top-1 assignment)
    top1 = jax.nn.one_hot(expert_idx[:, 0], E, dtype=compute_dtype)
    frac = top1.mean(axis=0)
    mean_prob = probs.mean(axis=0)
    aux = E * jnp.sum(frac * mean_prob)
    z = jnp.mean(jax.scipy.special.logsumexp(
        logits.astype(compute_dtype), axis=-1) ** 2)
    return RouterOut(dispatch, combine, aux, z)


def moe_ffn(x: jax.Array, router_w: jax.Array, w_in: jax.Array,
            w_out: jax.Array, *, k: int = 2, capacity_factor: float = 1.25,
            act: Callable = jax.nn.gelu,
            capacity: Optional[int] = None) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Dense (single-device / GSPMD-auto) MoE feed-forward.

    x: [T, D] tokens; router_w: [D, E]; w_in: [E, D, F]; w_out: [E, F, D].
    Returns (out [T, D], aux_loss, z_loss).
    """
    T, D = x.shape
    E = router_w.shape[1]
    C = capacity if capacity is not None else expert_capacity(
        T, E, k, capacity_factor)
    logits = x @ router_w                               # [T, E]
    r = route_topk(logits, k, C)
    xe = jnp.einsum("td,tec->ecd", x, r.dispatch.astype(x.dtype))
    h = act(jnp.einsum("ecd,edf->ecf", xe, w_in))
    y = jnp.einsum("ecf,efd->ecd", h, w_out)
    out = jnp.einsum("ecd,tec->td", y, r.combine.astype(y.dtype))
    return out, r.aux_loss, r.z_loss


def moe_ffn_sharded(x: jax.Array, router_w: jax.Array, w_in_local: jax.Array,
                    w_out_local: jax.Array, *, axis_name: str = "ep",
                    k: int = 2, capacity_factor: float = 1.25,
                    act: Callable = jax.nn.gelu,
                    capacity: Optional[int] = None) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Per-device MoE body for use inside an existing shard_map program.

    Token activations are sharded over `axis_name` ([T_local, D] here);
    expert weights are expert-sharded ([E/n, D, F] locally).  The router is
    replicated.  One all_to_all moves each device's dispatched tokens to
    the devices owning their experts; the inverse brings results home —
    the `lax.all_to_all` token dispatch SURVEY.md §2.3 calls for.
    """
    n = jax.lax.psum(1, axis_name)
    El = w_in_local.shape[0]
    E = El * n
    Tl, D = x.shape
    C = capacity if capacity is not None else expert_capacity(
        Tl, E, k, capacity_factor)
    logits = x @ router_w                               # [Tl, E]
    r = route_topk(logits, k, C)
    xe = jnp.einsum("td,tec->ecd", x, r.dispatch.astype(x.dtype))  # [E, C, D]
    # to expert owners: [E, C, D] -> [E/n, n*C, D]
    xe = jax.lax.all_to_all(xe, axis_name, split_axis=0, concat_axis=1,
                            tiled=True)
    h = act(jnp.einsum("ecd,edf->ecf", xe, w_in_local))
    y = jnp.einsum("ecf,efd->ecd", h, w_out_local)      # [E/n, n*C, D]
    # back to token owners: [E/n, n*C, D] -> [E, C, D]
    y = jax.lax.all_to_all(y, axis_name, split_axis=1, concat_axis=0,
                           tiled=True)
    out = jnp.einsum("ecd,tec->td", y, r.combine.astype(y.dtype))
    # aux losses are per-shard means over the same token count: average
    aux = jax.lax.pmean(r.aux_loss, axis_name)
    z = jax.lax.pmean(r.z_loss, axis_name)
    return out, aux, z
