"""Ulysses-style sequence parallelism: all-to-all head<->sequence reshard.

Absent from the reference (SURVEY.md §2.3); built natively: with activations
sharded on sequence over `sp`, attention wants full sequence per head — so
all-to-all swaps the sharded axis from seq to heads before attention and back
after (DeepSpeed-Ulysses; maps to one `lax.all_to_all` each way over ICI).
Requires heads % sp == 0.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from ray_tpu._private.jax_compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def _seq_to_heads(x, axis_name: str):
    # local [B, H, S/n, D] -> exchange -> local [B, H/n, S, D]
    return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)


def _heads_to_seq(x, axis_name: str):
    # local [B, H/n, S, D] -> local [B, H, S/n, D]
    return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)


def ulysses_attention(q, k, v, mesh: Mesh, axis_name: str = "sp",
                      causal: bool = False, scale: Optional[float] = None,
                      attn_fn: Optional[Callable] = None):
    """Attention with Ulysses resharding.

    Inputs [B, H, S, D] sequence-sharded over `axis_name`; internally
    resharded to head-parallel (full sequence per device), attention runs
    with any inner implementation (defaults to the blockwise XLA path /
    Pallas kernel on TPU via ops.attention), then reshard back.
    """
    from .attention import attention as default_attn

    if axis_name not in mesh.shape:
        raise ValueError(f"ulysses_attention: axis {axis_name!r} is not in "
                         f"the mesh (axes: {tuple(mesh.axis_names)})")
    heads, sp = q.shape[1], mesh.shape[axis_name]
    if heads % sp:
        raise ValueError(
            f"ulysses_attention: the all_to_all reshard splits the head dim "
            f"across the {axis_name!r} axis, so heads ({heads}) must be "
            f"divisible by the axis size ({sp}); pad/regroup heads or "
            f"shrink {axis_name!r}")

    inner = attn_fn or (lambda a, b, c: default_attn(a, b, c, causal=causal,
                                                     scale=scale))
    spec = P(None, None, axis_name, None)

    def local(q_, k_, v_):
        qh = _seq_to_heads(q_, axis_name)
        kh = _seq_to_heads(k_, axis_name)
        vh = _seq_to_heads(v_, axis_name)
        oh = inner(qh, kh, vh)
        return _heads_to_seq(oh, axis_name)

    return shard_map(local, check_vma=False, mesh=mesh,
                     in_specs=(spec, spec, spec), out_specs=spec)(q, k, v)


def ulysses_attention_sharded(q, k, v, axis_name: str = "sp",
                              causal: bool = False,
                              scale: Optional[float] = None,
                              attn_fn: Optional[Callable] = None):
    """Per-device body for use inside an existing shard_map program.
    The inner attention goes through ops.attention's dispatch, so TPU
    runs the Pallas flash kernels (same as the outer wrapper)."""
    from .attention import attention as default_attn

    inner = attn_fn or (lambda a, b, c: default_attn(
        a, b, c, causal=causal, scale=scale))
    qh = _seq_to_heads(q, axis_name)
    kh = _seq_to_heads(k, axis_name)
    vh = _seq_to_heads(v, axis_name)
    return _heads_to_seq(inner(qh, kh, vh), axis_name)
