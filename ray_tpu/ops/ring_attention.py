"""Ring attention: sequence/context parallelism over the `sp` mesh axis.

Absent from the reference (SURVEY.md §2.3/§5: "no ring_attention/ulysses/
context_parallel anywhere in-tree") — the TPU build implements it natively:
Q stays resident per device; K/V blocks rotate around the `sp` ring via
`lax.ppermute` while each device accumulates flash-style online-softmax
partial results.  ICI neighbor links make the rotation bandwidth-optimal,
and XLA overlaps the ppermute with the local attention compute (the
latency-hiding recipe of Liu et al., Ring Attention, and the scaling-book
collective chapter).

Causal masking works on *global* positions: device r owns query rows
[r*S_local, (r+1)*S_local); at rotation step t it sees KV chunk from device
(r - t) mod n, i.e. kv_offset = ((r - t) mod n) * S_local.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from ray_tpu._private.jax_compat import axis_size, shard_map
from jax.sharding import Mesh, PartitionSpec as P

from .attention import (DEFAULT_MASK_VALUE, _block_stats_update,
                        blockwise_attention, flash_attention_with_lse)


def _ring_attention_local_pallas(q, k, v, axis_name: str, causal: bool,
                                 scale: Optional[float],
                                 block_k: int = 512,
                                 interpret: bool = False):
    """Pallas-kernel ring body.  Because KV rotates in whole-device
    chunks, every step is one of three STATIC shapes — full attention
    (KV strictly before Q), diagonal causal (own chunk), or fully
    masked (KV strictly after Q) — so the offset-free flash kernels
    compose: each chunk call returns a per-chunk-normalized (o, lse)
    and steps combine in log space.  No offset-aware kernel needed."""
    n = axis_size(axis_name)
    r = jax.lax.axis_index(axis_name)
    b, h, s_loc, d = q.shape
    scale_ = (d ** -0.5) if scale is None else scale
    perm = [(i, (i + 1) % n) for i in range(n)]

    def chunk(k_cur, v_cur, diag: bool):
        o, lse = flash_attention_with_lse(
            q, k_cur, v_cur, diag, scale_, 512, block_k, interpret)
        return o.astype(jnp.float32), lse

    def masked(k_cur, v_cur):
        return (jnp.zeros((b, h, s_loc, d), jnp.float32),
                jnp.full((b, h, s_loc), DEFAULT_MASK_VALUE, jnp.float32))

    def step(t, carry):
        o_acc, lse_acc, k_cur, v_cur = carry
        src = (r - t) % n                  # whose KV chunk we hold
        if causal:
            o_c, lse_c = jax.lax.cond(
                src == r,
                lambda kc, vc: chunk(kc, vc, True),
                lambda kc, vc: jax.lax.cond(
                    src < r,
                    lambda kc_, vc_: chunk(kc_, vc_, False),
                    masked, kc, vc),
                k_cur, v_cur)
        else:
            o_c, lse_c = chunk(k_cur, v_cur, False)
        m = jnp.maximum(lse_acc, lse_c)
        w1 = jnp.exp(lse_acc - m)
        w2 = jnp.exp(lse_c - m)
        o_acc = (o_acc * w1[..., None] + o_c * w2[..., None]) \
            / jnp.maximum(w1 + w2, 1e-30)[..., None]
        lse_acc = m + jnp.log(jnp.maximum(w1 + w2, 1e-30))
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return o_acc, lse_acc, k_nxt, v_nxt

    o0 = jnp.zeros((b, h, s_loc, d), jnp.float32)
    lse0 = jnp.full((b, h, s_loc), DEFAULT_MASK_VALUE, jnp.float32)
    o, _, _, _ = jax.lax.fori_loop(0, n, step, (o0, lse0, k, v))
    return o.astype(q.dtype)


def _ring_attention_local(q, k, v, axis_name: str, causal: bool,
                          scale: Optional[float], block_k: int):
    """Runs inside shard_map: q,k,v are the local [B,H,S_loc,D] chunks."""
    n = axis_size(axis_name)
    r = jax.lax.axis_index(axis_name)
    b, h, s_loc, d = q.shape
    scale_ = (d ** -0.5) if scale is None else scale
    q_offset = r * s_loc

    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(t, carry):
        acc, m, l, k_cur, v_cur = carry
        src = (r - t) % n                  # whose KV chunk we hold at step t
        kv_offset = src * s_loc
        s_blk_fn = functools.partial(
            _partial_scores, q=q, scale=scale_, causal=causal,
            q_offset=q_offset, kv_offset=kv_offset, block_k=block_k)
        acc, m, l = _accumulate_chunk(acc, m, l, s_blk_fn, k_cur, v_cur)
        # rotate KV to the next device; XLA overlaps this with compute
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return acc, m, l, k_nxt, v_nxt

    acc0 = jnp.zeros((b, h, s_loc, d), jnp.float32)
    m0 = jnp.full((b, h, s_loc, 1), DEFAULT_MASK_VALUE, jnp.float32)
    l0 = jnp.zeros((b, h, s_loc, 1), jnp.float32)
    acc, m, l, _, _ = jax.lax.fori_loop(
        0, n, step, (acc0, m0, l0, k, v))
    return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)


def _partial_scores(k_blk, col_start, *, q, scale, causal, q_offset,
                    kv_offset, block_k):
    q32 = q.astype(jnp.float32)
    s = jnp.einsum("bhqd,bhkd->bhqk", q32, k_blk.astype(jnp.float32)) * scale
    sq = q.shape[-2]
    bk = k_blk.shape[-2]
    rows = q_offset + jax.lax.broadcasted_iota(jnp.int32, (sq, 1), 0)
    cols = kv_offset + col_start + jax.lax.broadcasted_iota(
        jnp.int32, (1, bk), 1)
    if causal:
        mask = rows >= cols
        s = jnp.where(mask[None, None], s, DEFAULT_MASK_VALUE)
    return s


def _accumulate_chunk(acc, m, l, s_blk_fn, k_chunk, v_chunk):
    """Fold one KV chunk into the running flash stats, blockwise."""
    s_loc = k_chunk.shape[-2]
    s = s_blk_fn(k_chunk, 0)
    return _block_stats_update((acc, m, l), s, v_chunk)


def _ring_local_dispatch(q, k, v, axis_name: str, causal: bool,
                         scale: Optional[float], block_k: int, impl: str):
    if impl == "auto":
        # same rule as attention(): the flash kernels win on TPU for any
        # kernel-shaped chunk; the XLA scan is the portable path
        s_loc, sk_loc = q.shape[-2], k.shape[-2]
        impl = ("pallas" if (jax.default_backend() == "tpu"
                             and s_loc % 128 == 0 and sk_loc % 128 == 0)
                else "xla")
    if impl == "pallas":
        return _ring_attention_local_pallas(q, k, v, axis_name, causal,
                                            scale, block_k)
    if impl == "pallas_interpret":
        return _ring_attention_local_pallas(q, k, v, axis_name, causal,
                                            scale, block_k, interpret=True)
    if impl == "xla":
        return _ring_attention_local(q, k, v, axis_name, causal, scale,
                                     block_k)
    raise ValueError(f"unknown ring attention impl {impl!r}")


def ring_attention(q, k, v, mesh: Mesh, axis_name: str = "sp",
                   causal: bool = False, scale: Optional[float] = None,
                   block_k: int = 512, in_specs: Optional[P] = None,
                   impl: str = "auto"):
    """Sequence-parallel attention over `axis_name`.

    q,k,v are global arrays [B, H, S, D] sharded on S over the mesh axis
    (other axes may carry dp/tp sharding; this op only touches `sp`).
    Returns the globally-correct attention output with the same sharding.
    """
    spec = in_specs if in_specs is not None else P(None, None, axis_name, None)
    local = functools.partial(_ring_local_dispatch, axis_name=axis_name,
                              causal=causal, scale=scale, block_k=block_k,
                              impl=impl)
    return shard_map(local, check_vma=False, mesh=mesh,
                     in_specs=(spec, spec, spec), out_specs=spec)(q, k, v)


def ring_attention_sharded(q, k, v, axis_name: str = "sp",
                           causal: bool = False,
                           scale: Optional[float] = None,
                           block_k: int = 512, impl: str = "auto"):
    """For use *inside* an existing shard_map/pjit program: the per-device
    body alone (q,k,v already local chunks)."""
    return _ring_local_dispatch(q, k, v, axis_name, causal, scale,
                                block_k, impl)
