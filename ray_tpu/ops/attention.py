"""Attention ops: Pallas TPU flash attention + blockwise XLA fallback.

The reference has no attention kernels at all (it delegates compute to
torch); for a TPU-native framework the attention kernel IS the hot op, so it
lives here as a first-class component (SURVEY.md §2.3: ring attention must be
built natively).

Layouts: all functions take [batch, heads, seq, head_dim] (BHSD).

Three tiers:
  * mha_reference     — O(S^2) naive, the correctness oracle.
  * blockwise_attention — flash-style streaming softmax as a lax.scan; runs
    anywhere XLA runs, differentiable, memory O(S·block).
  * flash_attention   — Pallas TPU kernels, forward AND backward (MXU-tiled,
    VMEM-resident blocks, causal block skipping; FlashAttention-2-style
    dq/dk/dv backward, so no XLA recompute anywhere).
  * flash_attention_with_lse — (out, logsumexp) variant whose partial
    results compose across KV chunks (the ring-attention building block).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

DEFAULT_MASK_VALUE = -0.7 * float(np.finfo(np.float32).max)


def mha_reference(q, k, v, causal: bool = False, scale: Optional[float] = None):
    """Naive O(S^2) attention; the oracle for kernel tests."""
    *_, sq, d = q.shape
    sk = k.shape[-2]
    scale = (d ** -0.5) if scale is None else scale
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        mask = np.tril(np.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(mask, s, DEFAULT_MASK_VALUE)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(q.dtype), v)


# ---------------------------------------------------------------------------
# Blockwise (flash-style) attention in pure XLA — runs on CPU/TPU, grads OK
# ---------------------------------------------------------------------------


def _block_stats_update(carry, s_blk, v_blk):
    """One online-softmax accumulation step (the flash recurrence)."""
    acc, m_prev, l_prev = carry
    m_cur = jnp.max(s_blk, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(s_blk - m_new)
    l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
    acc_new = acc * alpha + jnp.einsum("bhqk,bhkd->bhqd", p.astype(v_blk.dtype),
                                       v_blk).astype(acc.dtype)
    return acc_new, m_new, l_new


def blockwise_attention(q, k, v, causal: bool = False,
                        scale: Optional[float] = None, block_k: int = 512,
                        kv_offset: int = 0, q_offset: int = 0):
    """Streaming-softmax attention scanning KV blocks.

    kv_offset/q_offset give the *global* positions of the local q/k chunks —
    that's what lets ring attention reuse this with rotated KV blocks.

    Deliberately a FLAT scan over KV blocks with all queries in each
    matmul: a q-chunked variant that skips upper-triangle blocks via
    lax.cond was measured 2.5x SLOWER end-to-end on v5e (GPT-2 @4096:
    7.8k vs 19.8k tokens/s) — the skip trades one wide MXU-saturating
    matmul per KV block for a serialized chain of narrow ones.  On TPU,
    keep matmuls big; masked FLOPs are cheaper than small grids.
    """
    b, h, sq, d = q.shape
    sk = k.shape[-2]
    scale = (d ** -0.5) if scale is None else scale
    block_k = min(block_k, sk)
    nblocks = (sk + block_k - 1) // block_k
    pad = nblocks * block_k - sk
    if pad:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    kb = k.reshape(b, h, nblocks, block_k, d).transpose(2, 0, 1, 3, 4)
    vb = v.reshape(b, h, nblocks, block_k, d).transpose(2, 0, 1, 3, 4)

    q32 = q.astype(jnp.float32)
    row_ids = q_offset + jax.lax.broadcasted_iota(jnp.int32, (sq, 1), 0)

    def step(carry, inputs):
        idx, k_blk, v_blk = inputs
        s = jnp.einsum("bhqd,bhkd->bhqk", q32,
                       k_blk.astype(jnp.float32)) * scale
        col_start = kv_offset + idx * block_k
        col_ids = col_start + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1)
        mask = col_ids < (kv_offset + sk)  # padding mask
        if causal:
            mask = mask & (row_ids >= col_ids)
        s = jnp.where(mask[None, None], s, DEFAULT_MASK_VALUE)
        return _block_stats_update(carry, s, v_blk), None

    acc0 = jnp.zeros((b, h, sq, d), jnp.float32)
    m0 = jnp.full((b, h, sq, 1), DEFAULT_MASK_VALUE, jnp.float32)
    l0 = jnp.zeros((b, h, sq, 1), jnp.float32)
    (acc, m, l), _ = jax.lax.scan(
        step, (acc0, m0, l0),
        (jnp.arange(nblocks), kb, vb))
    return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas TPU flash attention
# ---------------------------------------------------------------------------


def _fit_block(block, seq):
    # shrink to a divisor so seq lengths like 768 (divisible by 256
    # but not the 512/1024 defaults) keep working — but never below
    # 128 lanes: a seq like 520 would "fit" at block 8, turning the
    # grid into thousands of tiny sequential programs (an orders-of-
    # magnitude perf cliff, and sub-sublane blocks may not even
    # lower); such lengths must pad instead, loudly
    floor = min(128, seq)
    block = min(block, seq)
    while block > floor and seq % block:
        block //= 2
    if seq % block:
        raise ValueError(
            f"seq length {seq} has no block divisor >= {floor}; pad "
            f"the sequence to a multiple of 128 for the pallas path")
    return block


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *rest, scale: float,
                  causal: bool, block_q: int, block_k: int,
                  q_offset: int, with_lse: bool):
    from jax.experimental import pallas as pl

    if with_lse:
        lse_ref, acc_ref, m_ref, l_ref = rest
    else:
        acc_ref, m_ref, l_ref = rest

    i = pl.program_id(2)  # q block
    j = pl.program_id(3)  # k block
    nk = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, DEFAULT_MASK_VALUE)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    run = True
    if causal:
        # whole block above the diagonal contributes nothing; q_offset
        # shifts local q rows to their global positions (decode-style
        # rectangular causal: q_offset = sk - sq anchors bottom-right)
        run = (j * block_k) <= (q_offset + i * block_q + block_q - 1)

    @pl.when(run if causal else True)
    def _compute():
        # inputs stay bf16 — the MXU runs bf16 x bf16 at full rate with
        # f32 accumulation via preferred_element_type; casting to f32
        # first would halve matmul throughput for zero extra precision
        q = q_ref[0, 0]                               # [bq, d]
        k = k_ref[0, 0]                               # [bk, d]
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk] f32
        if causal:
            rows = q_offset + i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, DEFAULT_MASK_VALUE)
        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == nk - 1)
    def _finalize():
        o_ref[0, 0] = (acc_ref[:] /
                       jnp.maximum(l_ref[:, :1], 1e-30)).astype(o_ref.dtype)
        if with_lse:
            # logsumexp per query row, broadcast across the 128 lanes
            # (sublane->lane transposes don't lower, so LSE lives as a
            # lane-replicated [.., 128] plane end to end)
            lse_ref[0, 0] = m_ref[:] + jnp.log(
                jnp.maximum(l_ref[:], 1e-30))


def _flash_forward(q, k, v, causal: bool, scale: float, block_q: int,
                   block_k: int, interpret: bool, with_lse: bool = False,
                   q_offset: int = 0):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, sq, d = q.shape
    sk = k.shape[-2]
    if q_offset < 0:
        raise ValueError(f"q_offset must be >= 0, got {q_offset}")
    if causal and sq != sk and q_offset == 0:
        # with no offset the kernels anchor the causal mask at row 0
        # (rows >= cols) while mha_reference anchors rectangular inputs
        # bottom-right (tril with k=sk-sq, decode semantics: the last
        # query row is position sk-1) — letting this through would
        # silently diverge from the other impls
        raise ValueError(
            f"causal pallas flash attention with sq ({sq}) != sk ({sk}) "
            f"needs an explicit query anchor: pass q_offset=sk-sq "
            f"({sk - sq}) for bottom-right (decode) alignment, use "
            f"impl='xla' (blockwise_attention handles the offset), or "
            f"pad q to sk.")
    block_q = _fit_block(block_q, sq)
    block_k = _fit_block(block_k, sk)
    grid = (b, h, sq // block_q, sk // block_k)
    kernel = functools.partial(_flash_kernel, scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k,
                               q_offset=q_offset, with_lse=with_lse)
    out_specs = [pl.BlockSpec((1, 1, block_q, d),
                              lambda b_, h_, i, j: (b_, h_, i, 0))]
    out_shape = [jax.ShapeDtypeStruct(q.shape, q.dtype)]
    if with_lse:
        out_specs.append(pl.BlockSpec((1, 1, block_q, 128),
                                      lambda b_, h_, i, j: (b_, h_, i, 0)))
        out_shape.append(jax.ShapeDtypeStruct((b, h, sq, 128), jnp.float32))
    res = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, i, j: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda b_, h_, i, j: (b_, h_, j, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda b_, h_, i, j: (b_, h_, j, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return (res[0], res[1]) if with_lse else res[0]


# ---------------------------------------------------------------------------
# Pallas flash attention backward (FlashAttention-2 style dq / dk / dv)
# ---------------------------------------------------------------------------
#
# Residuals are (q, k, v, o, lse): the big P matrix is never stored.  The
# backward recomputes p = exp(s - lse) blockwise inside two kernels:
#   dkv: grid (b, h, Nk, Nq) — for a fixed KV block, accumulate over q
#        blocks   dv_j += p^T do,   dk_j += scale * ds^T q
#   dq:  grid (b, h, Nq, Nk) — for a fixed Q block, accumulate over k
#        blocks   dq_i += scale * ds k
# with ds = p * (dp - di), dp = do v^T, di = rowsum(do * o) - dlse (the
# dlse term folds the cotangent of the lse output into the same kernel:
# d lse_i / d s_ik = p_ik).  di and lse ride as lane-replicated
# [B, H, S, 128] planes (see _flash_kernel._finalize).


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, di_ref,
                          dk_ref, dv_ref, dk_acc, dv_acc, *, scale: float,
                          causal: bool, block_q: int, block_k: int,
                          q_offset: int):
    from jax.experimental import pallas as pl

    j = pl.program_id(2)   # k block (outer)
    i = pl.program_id(3)   # q block (inner, sequential accumulation)
    nq = pl.num_programs(3)

    @pl.when(i == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    run = True
    if causal:
        run = (j * block_k) <= (q_offset + i * block_q + block_q - 1)

    @pl.when(run if causal else True)
    def _compute():
        q = q_ref[0, 0]                                 # [bq, d]
        k = k_ref[0, 0]                                 # [bk, d]
        v = v_ref[0, 0]
        do = do_ref[0, 0]                               # [bq, d]
        lse = lse_ref[0, 0][:, :1]                      # [bq, 1] f32
        di = di_ref[0, 0][:, :1]                        # [bq, 1] f32
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk]
        p = jnp.exp(s - lse)
        if causal:
            rows = q_offset + i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            p = jnp.where(rows >= cols, p, 0.0)
        # dv += p^T do  (contract the q axis of both)
        dv_acc[:] = dv_acc[:] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # [bq, bk]
        ds = (p * (dp - di) * scale).astype(q.dtype)
        # dk += ds^T q
        dk_acc[:] = dk_acc[:] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(i == nq - 1)
    def _finalize():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, di_ref,
                         dq_ref, dq_acc, *, scale: float, causal: bool,
                         block_q: int, block_k: int, q_offset: int):
    from jax.experimental import pallas as pl

    i = pl.program_id(2)   # q block (outer)
    j = pl.program_id(3)   # k block (inner, sequential accumulation)
    nk = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    run = True
    if causal:
        run = (j * block_k) <= (q_offset + i * block_q + block_q - 1)

    @pl.when(run if causal else True)
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0][:, :1]
        di = di_ref[0, 0][:, :1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        p = jnp.exp(s - lse)
        if causal:
            rows = q_offset + i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            p = jnp.where(rows >= cols, p, 0.0)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - di) * scale).astype(q.dtype)
        dq_acc[:] = dq_acc[:] + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(j == nk - 1)
    def _finalize():
        dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)


def _flash_backward(q, k, v, o, lse128, do, dlse, causal: bool,
                    scale: float, block_q: int, block_k: int,
                    interpret: bool, q_offset: int = 0):
    """dq, dk, dv from residuals.  lse128: [B,H,Sq,128] lane-replicated
    logsumexp; dlse: [B,H,Sq] cotangent of the lse output or None."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, sq, d = q.shape
    sk = k.shape[-2]
    # bwd blocks are capped at 512x512: four [bq, bk] f32 intermediates
    # live at once (s, p, dp, ds), twice the fwd's VMEM appetite
    block_q = _fit_block(min(block_q, 512), sq)
    block_k = _fit_block(min(block_k, 512), sk)

    di = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    if dlse is not None:
        di = di - dlse
    di128 = jnp.broadcast_to(di[..., None], (b, h, sq, 128))

    def qspec(rev):
        # rev: grid is (b, h, kblock, qblock); else (b, h, qblock, kblock)
        if rev:
            return pl.BlockSpec((1, 1, block_q, d),
                                lambda b_, h_, j, i: (b_, h_, i, 0))
        return pl.BlockSpec((1, 1, block_q, d),
                            lambda b_, h_, i, j: (b_, h_, i, 0))

    def kspec(rev):
        if rev:
            return pl.BlockSpec((1, 1, block_k, d),
                                lambda b_, h_, j, i: (b_, h_, j, 0))
        return pl.BlockSpec((1, 1, block_k, d),
                            lambda b_, h_, i, j: (b_, h_, j, 0))

    def lanespec(rev):
        if rev:
            return pl.BlockSpec((1, 1, block_q, 128),
                                lambda b_, h_, j, i: (b_, h_, i, 0))
        return pl.BlockSpec((1, 1, block_q, 128),
                            lambda b_, h_, i, j: (b_, h_, i, 0))

    dkv_kernel = functools.partial(
        _flash_bwd_dkv_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, q_offset=q_offset)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(b, h, sk // block_k, sq // block_q),
        in_specs=[qspec(True), kspec(True), kspec(True), qspec(True),
                  lanespec(True), lanespec(True)],
        out_specs=[kspec(True), kspec(True)],
        out_shape=[jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse128, di128)

    dq_kernel = functools.partial(
        _flash_bwd_dq_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, q_offset=q_offset)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(b, h, sq // block_q, sk // block_k),
        in_specs=[qspec(False), kspec(False), kspec(False), qspec(False),
                  lanespec(False), lanespec(False)],
        out_specs=qspec(False),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse128, di128)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None, block_q: int = 512,
                    block_k: int = 1024, interpret: bool = False,
                    q_offset: int = 0):
    """Pallas TPU flash attention, forward AND backward kernels (the
    backward is the FlashAttention-2 dq/dk/dv pair above — no XLA
    recompute fallback).

    q_offset (static): global position of q's row 0 in the causal mask.
    Pass sk - sq for bottom-right (decode) alignment of causal
    rectangular inputs, matching mha_reference's tril(k=sk-sq)."""
    scale = (q.shape[-1] ** -0.5) if scale is None else scale
    return _flash_forward(q, k, v, causal, scale, block_q, block_k,
                          interpret, q_offset=q_offset)


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret,
               q_offset):
    scale_ = (q.shape[-1] ** -0.5) if scale is None else scale
    out, lse128 = _flash_forward(q, k, v, causal, scale_, block_q, block_k,
                                 interpret, with_lse=True,
                                 q_offset=q_offset)
    return out, (q, k, v, out, lse128)


def _flash_bwd(causal, scale, block_q, block_k, interpret, q_offset,
               res, g):
    q, k, v, o, lse128 = res
    scale_ = (q.shape[-1] ** -0.5) if scale is None else scale
    return _flash_backward(q, k, v, o, lse128, g, None, causal, scale_,
                           block_q, block_k, interpret, q_offset=q_offset)


flash_attention.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def flash_attention_with_lse(q, k, v, causal: bool = False,
                             scale: Optional[float] = None,
                             block_q: int = 512, block_k: int = 1024,
                             interpret: bool = False, q_offset: int = 0):
    """(out, lse) variant for partial-softmax composition (ring
    attention): lse is [B, H, Sq] f32 logsumexp of the scaled scores.
    Differentiable in both outputs — the lse cotangent folds into the
    same backward kernels (di -= dlse)."""
    scale_ = (q.shape[-1] ** -0.5) if scale is None else scale
    out, lse128 = _flash_forward(q, k, v, causal, scale_, block_q, block_k,
                                 interpret, with_lse=True,
                                 q_offset=q_offset)
    return out, lse128[..., 0]


def _flash_lse_fwd(q, k, v, causal, scale, block_q, block_k, interpret,
                   q_offset):
    scale_ = (q.shape[-1] ** -0.5) if scale is None else scale
    out, lse128 = _flash_forward(q, k, v, causal, scale_, block_q, block_k,
                                 interpret, with_lse=True,
                                 q_offset=q_offset)
    return (out, lse128[..., 0]), (q, k, v, out, lse128)


def _flash_lse_bwd(causal, scale, block_q, block_k, interpret, q_offset,
                   res, g):
    q, k, v, o, lse128 = res
    do, dlse = g
    scale_ = (q.shape[-1] ** -0.5) if scale is None else scale
    return _flash_backward(q, k, v, o, lse128, do, dlse, causal, scale_,
                           block_q, block_k, interpret, q_offset=q_offset)


flash_attention_with_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def attention(q, k, v, causal: bool = False, scale: Optional[float] = None,
              impl: str = "auto", block_q: Optional[int] = None,
              block_k: Optional[int] = None):
    """Dispatching attention: blockwise XLA by default, Pallas on request.

    q,k,v: [batch, heads, seq, head_dim]

    Block defaults are per-path (v5e-measured optima differ 4x): the
    XLA scan wants small KV blocks (256 — deeper fusion per step), the
    pallas grid wants fat ones (512x1024 — fewer sequential programs).
    """
    sq, sk = q.shape[-2], k.shape[-2]
    if impl == "auto":
        # v5e measurements (GPT-2-small training, tokens/s), with the
        # native FlashAttention-2 dq/dk/dv bwd kernels: pallas beats XLA
        # blockwise at EVERY seq — 512 B=16: 99.5k vs 75.7k (+31%, MFU
        # .40 vs .31); 4096: 59.5k vs 19.8k (3.0x, MFU .37); 8192: 37.0k
        # vs 11.3k (3.3x, MFU .32).  (Before the bwd kernels existed the
        # custom_vjp fell back to a full blockwise recompute and lost
        # everywhere — that's why this dispatch was XLA-only through
        # round 4.)  XLA remains the portable path: CPU meshes, seqs not
        # a multiple of 128, and anything interpret-mode.
        # causal rectangular with sq > sk still routes to XLA (a
        # negative q_offset has no causal interpretation here); sk >= sq
        # runs in pallas with the bottom-right anchor via q_offset
        if (jax.default_backend() == "tpu"
                and sq % 128 == 0 and sk % 128 == 0
                and not (causal and sq > sk)):
            impl = "pallas"
        else:
            impl = "xla"
    # bottom-right-aligned causal mask for rectangular inputs, matching
    # mha_reference's tril(k=sk-sq) decode semantics
    qoff = (sk - sq) if (causal and sk > sq) else 0
    if impl == "pallas":
        return flash_attention(q, k, v, causal, scale, block_q or 512,
                               block_k or 1024, False, qoff)
    if impl == "pallas_interpret":
        return flash_attention(q, k, v, causal, scale, block_q or 512,
                               block_k or 1024, True, qoff)
    if impl == "xla":
        return blockwise_attention(q, k, v, causal=causal, scale=scale,
                                   block_k=block_k or 256,
                                   q_offset=(sk - sq) if causal else 0)
    if impl == "reference":
        return mha_reference(q, k, v, causal=causal, scale=scale)
    raise ValueError(f"unknown attention impl {impl!r}")
