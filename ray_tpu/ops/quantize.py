"""Block-wise int8 quantization: the compressed-collective building block.

EQuARX ("Efficient Quantized AllReduce in XLA", PAPERS.md) recovers ~2x
allreduce speedups by moving gradients as int8 blocks with per-block
scales instead of f32.  This module provides the quantize/dequantize
primitives that `collective/compression.py` and the quantized
`collective/xla_group.py` collectives compose:

  * Pallas TPU kernels — per-block absmax reduction, scale, round (round
    half-to-even, or stochastic via the on-core PRNG) fused in VMEM, so
    the quantize never round-trips HBM per block.
  * An XLA-lowered fallback with IDENTICAL numerics (same rounding mode,
    same scale formula), so CPU meshes and tier-1 tests exercise the
    real arithmetic, not a mock.

Layout contract (shared with the collectives): an array is flattened,
zero-padded to a multiple of `block_size`, and viewed as
[nblocks, block_size]; block b covers flat elements
[b*block_size, (b+1)*block_size).  scales[b] = absmax(block b)/127 (1.0
for an all-zero block), values are the clipped rounded ratios in int8.
Zero padding quantizes to exact zeros, so the trailing remainder of a
non-multiple array survives a round trip untouched.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

INT8_MAX = 127.0


def padded_len(n: int, block_size: int) -> int:
    """Smallest multiple of block_size >= n."""
    return n + (-n) % block_size


def num_blocks(n: int, block_size: int) -> int:
    return padded_len(n, block_size) // block_size


def _as_blocks(x, block_size: int):
    """Flatten + zero-pad to [nblocks, block_size] f32."""
    flat = x.reshape(-1).astype(jnp.float32)
    n = flat.shape[0]
    pad = padded_len(n, block_size) - n
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat.reshape(-1, block_size)


def _block_scales(blocks):
    absmax = jnp.max(jnp.abs(blocks), axis=-1, keepdims=True)
    return jnp.where(absmax > 0, absmax / INT8_MAX, 1.0)


# ---------------------------------------------------------------------------
# XLA fallback (CPU/TPU, in-jit traceable — the tier-1 numerics path)
# ---------------------------------------------------------------------------


def _quantize_xla(blocks, stochastic: bool, key):
    scales = _block_scales(blocks)
    # reciprocal-multiply, in lockstep with compression.compress_array:
    # 1/scale rounds identically under IEEE on numpy and XLA, so the host
    # codec stays bit-exact with this path
    y = blocks * (1.0 / scales)
    if stochastic:
        # unbiased: floor(y + u), u ~ U[0,1) — E[q] = y exactly
        u = jax.random.uniform(key, y.shape, jnp.float32)
        q = jnp.floor(y + u)
    else:
        q = jnp.round(y)  # round half-to-even, same as the kernel
    q = jnp.clip(q, -INT8_MAX, INT8_MAX).astype(jnp.int8)
    return q, scales[:, 0]


def _dequantize_xla(q_blocks, scales):
    return q_blocks.astype(jnp.float32) * scales[:, None]


# ---------------------------------------------------------------------------
# Pallas TPU kernels
# ---------------------------------------------------------------------------

# Rows of [block_size] blocks handled per grid step; int8 tiles are
# (32, 128) so stay a multiple of 32 sublanes.
_KERNEL_ROWS = 32


def _quantize_kernel(seed_ref, x_ref, q_ref, s_ref, *, stochastic: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    x = x_ref[:]                                        # [rows, block] f32
    absmax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    scale = jnp.where(absmax > 0, absmax / INT8_MAX, 1.0)
    y = x * (1.0 / scale)   # lockstep with _quantize_xla / host codec
    if stochastic:
        pltpu.prng_seed(seed_ref[0] + pl.program_id(0))
        bits = pltpu.bitcast(pltpu.prng_random_bits(y.shape), jnp.uint32)
        # top 24 bits -> u in [0, 1); floor(y + u) is unbiased
        u = (bits >> 8).astype(jnp.float32) * (1.0 / (1 << 24))
        y = jnp.floor(y + u)
    else:
        y = jnp.round(y)
    q_ref[:] = jnp.clip(y, -INT8_MAX, INT8_MAX).astype(jnp.int8)
    # scales ride as a lane-replicated [rows, 128] plane (sublane->lane
    # transposes don't lower; same layout trick as attention's LSE)
    s_ref[:] = jnp.broadcast_to(scale, s_ref.shape)


def _dequantize_kernel(q_ref, s_ref, o_ref):
    o_ref[:] = q_ref[:].astype(jnp.float32) * s_ref[:, :1]


def _dequant_accum_kernel(q_ref, s_ref, o_ref):
    # q [world, rows, block] int8, s [world, rows, 128] lane-replicated
    # scales -> o [rows, block] f32: dequantize every peer's rows and
    # accumulate in VMEM, so the [world, n] f32 expansion of the separate
    # dequantize-then-sum path never exists in HBM.
    q = q_ref[:].astype(jnp.float32)
    o_ref[:] = jnp.sum(q * s_ref[:, :, :1], axis=0)


def _pad_rows(blocks, rows_mult: int):
    nblocks = blocks.shape[0]
    pad = (-nblocks) % rows_mult
    if pad:
        blocks = jnp.pad(blocks, ((0, pad), (0, 0)))
    return blocks, nblocks


def _quantize_pallas(blocks, stochastic: bool, seed, interpret: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    block_size = blocks.shape[1]
    blocks, nblocks = _pad_rows(blocks, _KERNEL_ROWS)
    rows = blocks.shape[0]
    kernel = functools.partial(_quantize_kernel, stochastic=stochastic)
    seed_arr = jnp.asarray([seed], jnp.int32)
    q, s = pl.pallas_call(
        kernel,
        grid=(rows // _KERNEL_ROWS,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((_KERNEL_ROWS, block_size), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((_KERNEL_ROWS, block_size), lambda i: (i, 0)),
            pl.BlockSpec((_KERNEL_ROWS, 128), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, block_size), jnp.int8),
            jax.ShapeDtypeStruct((rows, 128), jnp.float32),
        ],
        interpret=interpret,
    )(seed_arr, blocks)
    return q[:nblocks], s[:nblocks, 0]


def _dequantize_pallas(q_blocks, scales, interpret: bool):
    from jax.experimental import pallas as pl

    block_size = q_blocks.shape[1]
    q_blocks, nblocks = _pad_rows(q_blocks, _KERNEL_ROWS)
    rows = q_blocks.shape[0]
    s128 = jnp.broadcast_to(scales[:, None], (nblocks, 128))
    if rows != nblocks:
        s128 = jnp.pad(s128, ((0, rows - nblocks), (0, 0)))
    out = pl.pallas_call(
        _dequantize_kernel,
        grid=(rows // _KERNEL_ROWS,),
        in_specs=[
            pl.BlockSpec((_KERNEL_ROWS, block_size), lambda i: (i, 0)),
            pl.BlockSpec((_KERNEL_ROWS, 128), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((_KERNEL_ROWS, block_size), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, block_size), jnp.float32),
        interpret=interpret,
    )(q_blocks, s128)
    return out[:nblocks]


def _dequant_accum_pallas(q, scales, world: int, block_size: int,
                          interpret: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nblk = scales.shape[0] // world
    q3 = q.reshape(world, nblk, block_size)
    s3 = jnp.broadcast_to(scales.reshape(world, nblk, 1), (world, nblk, 128))
    rows = nblk
    pad = (-rows) % _KERNEL_ROWS
    if pad:
        q3 = jnp.pad(q3, ((0, 0), (0, pad), (0, 0)))
        s3 = jnp.pad(s3, ((0, 0), (0, pad), (0, 0)))
        rows += pad
    out = pl.pallas_call(
        _dequant_accum_kernel,
        grid=(rows // _KERNEL_ROWS,),
        in_specs=[
            pl.BlockSpec((world, _KERNEL_ROWS, block_size),
                         lambda i: (0, i, 0)),
            pl.BlockSpec((world, _KERNEL_ROWS, 128), lambda i: (0, i, 0)),
        ],
        out_specs=pl.BlockSpec((_KERNEL_ROWS, block_size), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, block_size), jnp.float32),
        interpret=interpret,
    )(q3, s3)
    return out[:nblk].reshape(-1)


def _pick_impl(impl: str, block_size: int) -> str:
    if impl != "auto":
        return impl
    # pallas wants a lane-aligned block; anything else takes the XLA path
    if jax.default_backend() == "tpu" and block_size % 128 == 0:
        return "pallas"
    return "xla"


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------


def quantize_blockwise(x, block_size: int = 256, *, stochastic: bool = False,
                       key=None, seed: int = 0,
                       impl: str = "auto") -> Tuple[jax.Array, jax.Array]:
    """Quantize any-shape float array to (values int8 [npad], scales f32
    [nblocks]) under the module's block layout.  Traceable (fixed shapes
    given static block_size), so it composes into shard_map collectives.

    stochastic: unbiased stochastic rounding — `key` (jax PRNG key) on
    the XLA path, `seed` (int32) on the pallas path.
    """
    if block_size <= 0:
        raise ValueError(f"block_size must be positive, got {block_size}")
    blocks = _as_blocks(x, block_size)
    impl = _pick_impl(impl, block_size)
    if impl in ("pallas", "pallas_interpret"):
        q, s = _quantize_pallas(blocks, stochastic, seed,
                                interpret=(impl == "pallas_interpret"))
    elif impl == "xla":
        if stochastic and key is None:
            key = jax.random.PRNGKey(seed)
        q, s = _quantize_xla(blocks, stochastic, key)
    else:
        raise ValueError(f"unknown quantize impl {impl!r}")
    return q.reshape(-1), s


def dequantize_blockwise(q, scales, shape, dtype, block_size: int = 256,
                         impl: str = "auto") -> jax.Array:
    """Inverse of quantize_blockwise: back to `shape`/`dtype`, dropping
    the zero padding."""
    q_blocks = q.reshape(-1, block_size)
    impl = _pick_impl(impl, block_size)
    if impl in ("pallas", "pallas_interpret"):
        out = _dequantize_pallas(q_blocks, scales,
                                 interpret=(impl == "pallas_interpret"))
    elif impl == "xla":
        out = _dequantize_xla(q_blocks, scales)
    else:
        raise ValueError(f"unknown quantize impl {impl!r}")
    n = 1
    for d in shape:
        n *= d
    return out.reshape(-1)[:n].reshape(shape).astype(dtype)


def dequantize_accumulate(q, scales, world: int, block_size: int = 256,
                          impl: str = "auto") -> jax.Array:
    """Fused dequantize-and-reduce of `world` peers' quantized blocks.

    q is int8 [world * n] (n a block multiple), scales f32
    [world * n/block_size]; returns f32 [n] = sum over peers of their
    dequantized contribution — the accumulate half of the quantized
    reduce-scatter.  On the pallas path the int8 load, scale multiply
    and the sum over peers happen in one VMEM pass; the XLA fallback
    lowers the identical expression (same accumulation structure and f32
    dtype), so CPU tier-1 exercises the same numerics."""
    impl = _pick_impl(impl, block_size)
    if impl in ("pallas", "pallas_interpret"):
        return _dequant_accum_pallas(q, scales, world, block_size,
                                     interpret=(impl == "pallas_interpret"))
    if impl == "xla":
        q3 = q.reshape(world, -1, block_size).astype(jnp.float32)
        return (q3 * scales.reshape(world, -1)[:, :, None]).sum(
            axis=0).reshape(-1)
    raise ValueError(f"unknown quantize impl {impl!r}")


# ---------------------------------------------------------------------------
# Fused quantize -> shard-exchange -> accumulate (single TPU kernel)
# ---------------------------------------------------------------------------

# One VMEM-resident kernel per device does the whole reduce-scatter hop:
# quantize all per-peer sub-chunks, push each peer its int8 chunk + scales
# over the interconnect with async remote DMA, and dequantize-accumulate
# arrivals — no HBM round trip between the stages, which is the EQuARX
# fusion argument.  Deterministic rounding only (the staged path serves
# stochastic).  The exchange at offset o is the cyclic shift my->my+o+1,
# so every device sends and receives on the same semaphore slot and one
# descriptor's wait() covers both directions (the ring-collective pattern
# from the TPU guide, generalized to all-to-all).

_FUSED_COLLECTIVE_ID = 13


def _fused_rs_kernel(x_ref, o_ref, qs, ss, qr, sr, send_sem, recv_sem,
                     *, axis: str, world: int, nblk: int, block: int,
                     use_barrier: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    my = jax.lax.axis_index(axis)
    b = x_ref[:].reshape(world * nblk, block)
    absmax = jnp.max(jnp.abs(b), axis=-1, keepdims=True)
    scale = jnp.where(absmax > 0, absmax / INT8_MAX, 1.0)
    q = jnp.clip(jnp.round(b * (1.0 / scale)), -INT8_MAX,
                 INT8_MAX).astype(jnp.int8)
    qs[:] = q.reshape(world, nblk, block)
    ss[:] = jnp.broadcast_to(scale.reshape(world, nblk, 1),
                             (world, nblk, 128))
    # every peer must have its recv buffers live before anyone writes;
    # interpret mode has no barrier primitive (its DMA emulation is
    # already globally ordered), so the barrier only runs compiled
    if use_barrier:
        bar = pltpu.get_barrier_semaphore()
        for off in range(world - 1):
            pltpu.semaphore_signal(
                bar, inc=1, device_id=jax.lax.rem(my + off + 1, world),
                device_id_type=pltpu.DeviceIdType.LOGICAL)
        pltpu.semaphore_wait(bar, world - 1)
    copies = []
    for off in range(world - 1):
        dst = jax.lax.rem(my + off + 1, world)
        # remote row index = sender id, so arrivals never collide
        cp_q = pltpu.make_async_remote_copy(
            src_ref=qs.at[dst], dst_ref=qr.at[my],
            send_sem=send_sem.at[0, off], recv_sem=recv_sem.at[0, off],
            device_id=dst, device_id_type=pltpu.DeviceIdType.LOGICAL)
        cp_s = pltpu.make_async_remote_copy(
            src_ref=ss.at[dst], dst_ref=sr.at[my],
            send_sem=send_sem.at[1, off], recv_sem=recv_sem.at[1, off],
            device_id=dst, device_id_type=pltpu.DeviceIdType.LOGICAL)
        cp_q.start()
        cp_s.start()
        copies.append((cp_q, cp_s))
    # own contribution stays local: VMEM copy overlaps the in-flight DMAs
    own = pl.ds(my, 1)
    qr[own] = qs[own]
    sr[own] = ss[own]
    for cp_q, cp_s in copies:
        cp_q.wait()
        cp_s.wait()
    o_ref[:] = jnp.sum(qr[:].astype(jnp.float32) * sr[:, :, :1], axis=0)


def fused_reduce_scatter(x2d, axis: str, block_size: int = 256,
                         interpret: bool = False) -> jax.Array:
    """One-kernel quantized reduce-scatter hop, called inside a shard_map
    body.  x2d is this device's [world, sub] f32 contributions (sub a
    multiple of block_size); returns f32 [sub]: the sum over all peers of
    their (once-quantized) contribution to this device's chunk.

    TPU-only (remote DMA); numerics match
    quantize_blockwise -> all_to_all -> dequantize_accumulate, which is
    the XLA-lowered fallback the CPU tier-1 suite exercises."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    world, sub = x2d.shape
    if sub % block_size:
        raise ValueError(f"fused_reduce_scatter needs sub ({sub}) to be a "
                         f"multiple of block_size ({block_size})")
    nblk = sub // block_size
    kernel = functools.partial(_fused_rs_kernel, axis=axis, world=world,
                               nblk=nblk, block=block_size,
                               use_barrier=not interpret)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((nblk, block_size), jnp.float32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((world, nblk, block_size), jnp.int8),
            pltpu.VMEM((world, nblk, 128), jnp.float32),
            pltpu.VMEM((world, nblk, block_size), jnp.int8),
            pltpu.VMEM((world, nblk, 128), jnp.float32),
            pltpu.SemaphoreType.DMA((2, world - 1)),
            pltpu.SemaphoreType.DMA((2, world - 1)),
        ],
        # no DCE risk (o_ref is a consumed output), so only the
        # collective id for the cross-device barrier semaphore is needed
        compiler_params=pltpu.TPUCompilerParams(
            collective_id=_FUSED_COLLECTIVE_ID),
        interpret=interpret,
    )(x2d)
    return out.reshape(-1)


def fused_rs_vmem_bytes(world: int, sub: int) -> int:
    """VMEM footprint estimate for fused_reduce_scatter (input + output +
    scratch); callers chunk until this fits comfortably on-core."""
    nblk_bytes = (sub // 256 + 1) * 128 * 4
    return world * (sub * 4 + 2 * sub + 2 * nblk_bytes) + sub * 4


def quantization_error(x, block_size: int = 256, impl: str = "xla"):
    """x - deq(quant(x)): the per-call compression error (what error
    feedback accumulates).  Deterministic rounding only — the stochastic
    path's error depends on the drawn bits."""
    q, s = quantize_blockwise(x, block_size, impl=impl)
    return x - dequantize_blockwise(q, s, x.shape, x.dtype, block_size,
                                    impl=impl)
