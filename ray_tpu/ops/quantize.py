"""Block-wise int8 quantization: the compressed-collective building block.

EQuARX ("Efficient Quantized AllReduce in XLA", PAPERS.md) recovers ~2x
allreduce speedups by moving gradients as int8 blocks with per-block
scales instead of f32.  This module provides the quantize/dequantize
primitives that `collective/compression.py` and the quantized
`collective/xla_group.py` collectives compose:

  * Pallas TPU kernels — per-block absmax reduction, scale, round (round
    half-to-even, or stochastic via the on-core PRNG) fused in VMEM, so
    the quantize never round-trips HBM per block.
  * An XLA-lowered fallback with IDENTICAL numerics (same rounding mode,
    same scale formula), so CPU meshes and tier-1 tests exercise the
    real arithmetic, not a mock.

Layout contract (shared with the collectives): an array is flattened,
zero-padded to a multiple of `block_size`, and viewed as
[nblocks, block_size]; block b covers flat elements
[b*block_size, (b+1)*block_size).  scales[b] = absmax(block b)/127 (1.0
for an all-zero block), values are the clipped rounded ratios in int8.
Zero padding quantizes to exact zeros, so the trailing remainder of a
non-multiple array survives a round trip untouched.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

INT8_MAX = 127.0


def padded_len(n: int, block_size: int) -> int:
    """Smallest multiple of block_size >= n."""
    return n + (-n) % block_size


def num_blocks(n: int, block_size: int) -> int:
    return padded_len(n, block_size) // block_size


def _as_blocks(x, block_size: int):
    """Flatten + zero-pad to [nblocks, block_size] f32."""
    flat = x.reshape(-1).astype(jnp.float32)
    n = flat.shape[0]
    pad = padded_len(n, block_size) - n
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat.reshape(-1, block_size)


def _block_scales(blocks):
    absmax = jnp.max(jnp.abs(blocks), axis=-1, keepdims=True)
    return jnp.where(absmax > 0, absmax / INT8_MAX, 1.0)


# ---------------------------------------------------------------------------
# XLA fallback (CPU/TPU, in-jit traceable — the tier-1 numerics path)
# ---------------------------------------------------------------------------


def _quantize_xla(blocks, stochastic: bool, key):
    scales = _block_scales(blocks)
    y = blocks / scales
    if stochastic:
        # unbiased: floor(y + u), u ~ U[0,1) — E[q] = y exactly
        u = jax.random.uniform(key, y.shape, jnp.float32)
        q = jnp.floor(y + u)
    else:
        q = jnp.round(y)  # round half-to-even, same as the kernel
    q = jnp.clip(q, -INT8_MAX, INT8_MAX).astype(jnp.int8)
    return q, scales[:, 0]


def _dequantize_xla(q_blocks, scales):
    return q_blocks.astype(jnp.float32) * scales[:, None]


# ---------------------------------------------------------------------------
# Pallas TPU kernels
# ---------------------------------------------------------------------------

# Rows of [block_size] blocks handled per grid step; int8 tiles are
# (32, 128) so stay a multiple of 32 sublanes.
_KERNEL_ROWS = 32


def _quantize_kernel(seed_ref, x_ref, q_ref, s_ref, *, stochastic: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    x = x_ref[:]                                        # [rows, block] f32
    absmax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    scale = jnp.where(absmax > 0, absmax / INT8_MAX, 1.0)
    y = x / scale
    if stochastic:
        pltpu.prng_seed(seed_ref[0] + pl.program_id(0))
        bits = pltpu.bitcast(pltpu.prng_random_bits(y.shape), jnp.uint32)
        # top 24 bits -> u in [0, 1); floor(y + u) is unbiased
        u = (bits >> 8).astype(jnp.float32) * (1.0 / (1 << 24))
        y = jnp.floor(y + u)
    else:
        y = jnp.round(y)
    q_ref[:] = jnp.clip(y, -INT8_MAX, INT8_MAX).astype(jnp.int8)
    # scales ride as a lane-replicated [rows, 128] plane (sublane->lane
    # transposes don't lower; same layout trick as attention's LSE)
    s_ref[:] = jnp.broadcast_to(scale, s_ref.shape)


def _dequantize_kernel(q_ref, s_ref, o_ref):
    o_ref[:] = q_ref[:].astype(jnp.float32) * s_ref[:, :1]


def _pad_rows(blocks, rows_mult: int):
    nblocks = blocks.shape[0]
    pad = (-nblocks) % rows_mult
    if pad:
        blocks = jnp.pad(blocks, ((0, pad), (0, 0)))
    return blocks, nblocks


def _quantize_pallas(blocks, stochastic: bool, seed, interpret: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    block_size = blocks.shape[1]
    blocks, nblocks = _pad_rows(blocks, _KERNEL_ROWS)
    rows = blocks.shape[0]
    kernel = functools.partial(_quantize_kernel, stochastic=stochastic)
    seed_arr = jnp.asarray([seed], jnp.int32)
    q, s = pl.pallas_call(
        kernel,
        grid=(rows // _KERNEL_ROWS,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((_KERNEL_ROWS, block_size), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((_KERNEL_ROWS, block_size), lambda i: (i, 0)),
            pl.BlockSpec((_KERNEL_ROWS, 128), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, block_size), jnp.int8),
            jax.ShapeDtypeStruct((rows, 128), jnp.float32),
        ],
        interpret=interpret,
    )(seed_arr, blocks)
    return q[:nblocks], s[:nblocks, 0]


def _dequantize_pallas(q_blocks, scales, interpret: bool):
    from jax.experimental import pallas as pl

    block_size = q_blocks.shape[1]
    q_blocks, nblocks = _pad_rows(q_blocks, _KERNEL_ROWS)
    rows = q_blocks.shape[0]
    s128 = jnp.broadcast_to(scales[:, None], (nblocks, 128))
    if rows != nblocks:
        s128 = jnp.pad(s128, ((0, rows - nblocks), (0, 0)))
    out = pl.pallas_call(
        _dequantize_kernel,
        grid=(rows // _KERNEL_ROWS,),
        in_specs=[
            pl.BlockSpec((_KERNEL_ROWS, block_size), lambda i: (i, 0)),
            pl.BlockSpec((_KERNEL_ROWS, 128), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((_KERNEL_ROWS, block_size), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, block_size), jnp.float32),
        interpret=interpret,
    )(q_blocks, s128)
    return out[:nblocks]


def _pick_impl(impl: str, block_size: int) -> str:
    if impl != "auto":
        return impl
    # pallas wants a lane-aligned block; anything else takes the XLA path
    if jax.default_backend() == "tpu" and block_size % 128 == 0:
        return "pallas"
    return "xla"


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------


def quantize_blockwise(x, block_size: int = 256, *, stochastic: bool = False,
                       key=None, seed: int = 0,
                       impl: str = "auto") -> Tuple[jax.Array, jax.Array]:
    """Quantize any-shape float array to (values int8 [npad], scales f32
    [nblocks]) under the module's block layout.  Traceable (fixed shapes
    given static block_size), so it composes into shard_map collectives.

    stochastic: unbiased stochastic rounding — `key` (jax PRNG key) on
    the XLA path, `seed` (int32) on the pallas path.
    """
    if block_size <= 0:
        raise ValueError(f"block_size must be positive, got {block_size}")
    blocks = _as_blocks(x, block_size)
    impl = _pick_impl(impl, block_size)
    if impl in ("pallas", "pallas_interpret"):
        q, s = _quantize_pallas(blocks, stochastic, seed,
                                interpret=(impl == "pallas_interpret"))
    elif impl == "xla":
        if stochastic and key is None:
            key = jax.random.PRNGKey(seed)
        q, s = _quantize_xla(blocks, stochastic, key)
    else:
        raise ValueError(f"unknown quantize impl {impl!r}")
    return q.reshape(-1), s


def dequantize_blockwise(q, scales, shape, dtype, block_size: int = 256,
                         impl: str = "auto") -> jax.Array:
    """Inverse of quantize_blockwise: back to `shape`/`dtype`, dropping
    the zero padding."""
    q_blocks = q.reshape(-1, block_size)
    impl = _pick_impl(impl, block_size)
    if impl in ("pallas", "pallas_interpret"):
        out = _dequantize_pallas(q_blocks, scales,
                                 interpret=(impl == "pallas_interpret"))
    elif impl == "xla":
        out = _dequantize_xla(q_blocks, scales)
    else:
        raise ValueError(f"unknown quantize impl {impl!r}")
    n = 1
    for d in shape:
        n *= d
    return out.reshape(-1)[:n].reshape(shape).astype(dtype)


def quantization_error(x, block_size: int = 256, impl: str = "xla"):
    """x - deq(quant(x)): the per-call compression error (what error
    feedback accumulates).  Deterministic rounding only — the stochastic
    path's error depends on the drawn bits."""
    q, s = quantize_blockwise(x, block_size, impl=impl)
    return x - dequantize_blockwise(q, s, x.shape, x.dtype, block_size,
                                    impl=impl)
