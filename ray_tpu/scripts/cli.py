"""The ``ray-tpu`` command line interface.

Analog of the reference's `ray` CLI (reference:
python/ray/scripts/scripts.py — start :626, stop :1102, status, submit
:1636, plus the state CLI `ray list/summary/timeline` from
python/ray/util/state/state_cli.py).

Run as ``python -m ray_tpu <command>``.  Cluster bookkeeping: the head
writes ``/tmp/ray_tpu/ray_current_cluster.json`` (control address + daemon
pids) which stop/status/submit read back; ``ray_tpu.init(address="auto")``
uses the same file.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time

CLUSTER_FILE = os.environ.get("RAY_TPU_CLUSTER_FILE",
                              "/tmp/ray_tpu/ray_current_cluster.json")
DEFAULT_PORT = 6380


def _write_cluster_file(info):
    os.makedirs(os.path.dirname(CLUSTER_FILE), exist_ok=True)
    with open(CLUSTER_FILE, "w") as f:
        json.dump(info, f)


def read_cluster_file():
    if not os.path.exists(CLUSTER_FILE):
        return None
    with open(CLUSTER_FILE) as f:
        return json.load(f)


def _resolve_address(args) -> str:
    addr = getattr(args, "address", None)
    if addr and addr != "auto":
        return addr
    info = read_cluster_file()
    if info is None:
        raise SystemExit("no running cluster found (ray-tpu start --head "
                         "first, or pass --address)")
    return info["control_address"]


# -- start / stop / status ---------------------------------------------------

def cmd_start(args):
    from ray_tpu._private import accelerators, common
    from ray_tpu._private.bootstrap import Cluster, _spawn, _wait_ping

    if args.head:
        session_name = f"cli-{int(time.time())}"
        cluster = Cluster(session_name=session_name)
        host = args.node_ip_address
        port = args.port or DEFAULT_PORT
        cluster.control_proc = _spawn(
            [sys.executable, "-m", "ray_tpu._private.control",
             "--host", host, "--port", str(port)],
            os.path.join(cluster.log_dir, "control.log"))
        cluster.control_addr = (host, port)
        _wait_ping(cluster.control_addr, what="control plane")
        control_address = f"{host}:{port}"
    else:
        control_address = _resolve_address(args) if args.address is None \
            else args.address
        cluster = None

    resources = json.loads(args.resources) if args.resources else {}
    if args.num_cpus is not None:
        resources["CPU"] = float(args.num_cpus)
    else:
        resources.setdefault("CPU", float(os.cpu_count() or 1))
    num_tpus = (args.num_tpus if args.num_tpus is not None
                else accelerators.num_tpu_chips())
    if num_tpus:
        resources.setdefault("TPU", float(num_tpus))

    if args.head:
        node = cluster.add_node(resources=resources)
        _write_cluster_file({
            "control_address": control_address,
            "session_dir": cluster.session_dir,
            "control_pid": cluster.control_proc.pid,
            "raylet_pids": [node.proc.pid],
        })
        print(f"ray_tpu head started at {control_address}")
        print(f"  connect: ray_tpu.init(address='{control_address}')  "
              f"or ray_tpu.init(address='auto')")
    else:
        # worker node joining an existing cluster
        from ray_tpu._private.bootstrap import Cluster as _C

        c = _C(session_name=f"cli-worker-{int(time.time())}")
        c.control_addr = tuple(control_address.rsplit(":", 1))
        c.control_addr = (c.control_addr[0], int(c.control_addr[1]))
        node = c.add_node(resources=resources)
        info = read_cluster_file()
        if info:
            info.setdefault("raylet_pids", []).append(node.proc.pid)
            _write_cluster_file(info)
        print(f"ray_tpu node joined {control_address} "
              f"(node id {node.node_id[:12]})")
        cluster = c

    if args.block:
        try:
            while True:
                time.sleep(1)
        except KeyboardInterrupt:
            if cluster is not None:
                cluster.shutdown()


def cmd_config(args):
    """Print the resolved typed flag table (reference: ray_config_def.h
    flags + RAY_<name> env overrides)."""
    from ray_tpu._private.config import describe

    print(describe())


def cmd_debug(args):
    """Attach to an active remote breakpoint (reference: `ray debug` /
    util/rpdb.py)."""
    from ray_tpu._private.protocol import Client
    from ray_tpu.util import rpdb

    address = _resolve_address(args) if args.address is None \
        else args.address
    host, port = address.rsplit(":", 1)
    control = Client((host, int(port)), name="cli-debug")
    try:
        bps = rpdb.list_breakpoints(control)
        if not bps:
            print("no active breakpoints")
            return
        for i, bp in enumerate(bps):
            print(f"[{i}] {bp['id']} pid={bp['pid']} "
                  f"worker={bp.get('worker_id', '?')[:12]}")
        idx = args.index if args.index is not None else 0
        bp = bps[idx]
        print(f"attaching to {bp['id']} — pdb commands go through; "
              f"'c' continues the task and detaches")
        rpdb.attach(bp["addr"])
    finally:
        control.close()


def cmd_up(args):
    """Launch a cluster from a YAML config (reference: `ray up`,
    scripts.py:1337 + autoscaler/_private/commands.py), driving the
    configured node provider."""
    import yaml

    from ray_tpu._private.bootstrap import Cluster, _spawn, _wait_ping
    from ray_tpu.autoscaler.node_provider import make_node_provider

    with open(args.config) as f:
        cfg = yaml.safe_load(f) or {}
    name = cfg.get("cluster_name", "default")
    provider_cfg = dict(cfg.get("provider") or {"type": "local"})
    head_cfg = cfg.get("head_node") or {}
    worker_cfg = cfg.get("worker_nodes") or {}
    n_workers = int(worker_cfg.get("count", cfg.get("min_workers", 0)))

    # 1. control plane
    host = provider_cfg.get("head_ip", "127.0.0.1")
    port = int(provider_cfg.get("port", args.port or DEFAULT_PORT))
    if port == 0:
        from ray_tpu._private.bootstrap import free_port

        port = free_port()
    cluster = Cluster(session_name=f"up-{name}-{int(time.time())}")
    cluster.control_proc = _spawn(
        [sys.executable, "-m", "ray_tpu._private.control",
         "--host", host, "--port", str(port)],
        os.path.join(cluster.log_dir, "control.log"))
    cluster.control_addr = (host, port)
    _wait_ping(cluster.control_addr, what="control plane")
    control_address = f"{host}:{port}"
    provider_cfg["control_address"] = control_address

    # 2. head + worker nodes through the provider
    provider = make_node_provider(provider_cfg, name)
    head_ids = provider.create_node(
        {"resources": head_cfg.get("resources"),
         "labels": {**(head_cfg.get("labels") or {}),
                    "node-type": "head"}},
        {"ray-node-type": "head"}, 1)
    worker_ids = []
    if n_workers:
        worker_ids = provider.create_node(
            {"resources": worker_cfg.get("resources"),
             "labels": {**(worker_cfg.get("labels") or {}),
                        "node-type": "worker"}},
            {"ray-node-type": "worker"}, n_workers)

    pids = []
    for nid in head_ids + worker_ids:
        h = getattr(provider, "_nodes", {}).get(nid, {}).get("handle")
        if h is not None and getattr(h, "proc", None) is not None:
            pids.append(h.proc.pid)
    _write_cluster_file({
        "control_address": control_address,
        "cluster_name": name,
        "session_dir": cluster.session_dir,
        "control_pid": cluster.control_proc.pid,
        "raylet_pids": pids,
    })
    print(f"cluster {name!r} up at {control_address} "
          f"(1 head + {len(worker_ids)} workers)")
    print(f"  connect: ray_tpu.init(address='{control_address}')")


def cmd_down(args):
    """Tear down a cluster started with `up` (reference: `ray down`)."""
    info = read_cluster_file()
    if info is None:
        print("no running cluster")
        return
    cmd_stop(args)


def cmd_stop(args):
    info = read_cluster_file()
    if info is None:
        print("no running cluster")
        return
    pids = [info.get("control_pid")] + info.get("raylet_pids", [])
    killed = 0
    # raylets first so they fan shutdown out to their workers
    for pid in reversed([p for p in pids if p]):
        try:
            os.killpg(os.getpgid(pid), signal.SIGTERM)
            killed += 1
        except (ProcessLookupError, PermissionError):
            try:
                os.kill(pid, signal.SIGTERM)
                killed += 1
            except OSError:
                pass
    try:
        os.remove(CLUSTER_FILE)
    except OSError:
        pass
    print(f"stopped {killed} daemon(s)")


def cmd_status(args):
    from ray_tpu.util.state import api as state

    address = _resolve_address(args)
    nodes = state.list_nodes(address=address)
    total = state.cluster_resources(address=address)
    avail = state.available_resources(address=address)
    actors = state.list_actors(address=address)
    print(f"cluster at {address}")
    print(f"  nodes: {sum(1 for n in nodes if n['state'] == 'ALIVE')} alive"
          f" / {len(nodes)} total")
    for n in nodes:
        print(f"    {n['node_id'][:12]} {n['state']:6} {n['total']}")
    print(f"  resources: {avail} free of {total}")
    alive = sum(1 for a in actors if a.get("state") == "ALIVE")
    print(f"  actors: {alive} alive / {len(actors)} total")


# -- job commands ------------------------------------------------------------

def cmd_submit(args):
    from ray_tpu.job import JobStatus, JobSubmissionClient

    address = _resolve_address(args)
    client = JobSubmissionClient(address=address)
    parts = args.entrypoint
    if parts and parts[0] == "--":
        parts = parts[1:]
    import shlex

    entrypoint = shlex.join(parts)
    sid = client.submit_job(
        entrypoint=entrypoint,
        runtime_env=json.loads(args.runtime_env) if args.runtime_env else None,
        submission_id=args.submission_id)
    print(f"submitted job {sid}")
    if args.no_wait:
        return
    status = client.wait_until_finish(sid, timeout=args.timeout)
    logs = client.get_job_logs(sid)
    if logs:
        sys.stdout.write(logs)
    print(f"job {sid}: {status}")
    if status != JobStatus.SUCCEEDED:
        raise SystemExit(1)


def cmd_job(args):
    from ray_tpu.job import JobSubmissionClient

    client = JobSubmissionClient(address=_resolve_address(args))
    if args.job_cmd == "list":
        for j in client.list_jobs():
            print(f"{j['submission_id']}  {j['status']:10} "
                  f"{j.get('entrypoint', '')[:60]}")
    elif args.job_cmd == "status":
        print(client.get_job_status(args.id))
    elif args.job_cmd == "logs":
        sys.stdout.write(client.get_job_logs(args.id))
    elif args.job_cmd == "stop":
        print("stopped" if client.stop_job(args.id) else "not running")


def cmd_logs(args):
    """`ray-tpu logs [glob]`: list or tail cluster log files (reference:
    `ray logs` state CLI)."""
    from ray_tpu.util.state import api as state

    address = _resolve_address(args)
    if not args.name:
        for nid, logs in state.list_logs(address=address).items():
            for entry in logs:
                print(f"{nid[:12]}  {entry['size_bytes']:>9}  "
                      f"{entry['name']}")
        return
    for nid, text in state.get_log(args.name, address=address,
                                   tail_bytes=args.tail).items():
        if text is None:
            continue
        print(f"==== {nid[:12]}: {args.name}")
        sys.stdout.write(text)


def cmd_serve(args):
    """`serve deploy/status/shutdown` (reference: serve CLI over the
    declarative schema, serve/scripts.py)."""
    import ray_tpu

    ray_tpu.init(address=_resolve_address(args), ignore_reinit_error=True)
    from ray_tpu import serve

    if args.serve_cmd == "deploy":
        names = serve.deploy_config_file(args.config)
        print(f"deployed applications: {', '.join(names)}")
    elif args.serve_cmd == "status":
        for name, st in serve.status().items():
            print(f"{name}: {getattr(st, 'status', st)}")
    elif args.serve_cmd == "shutdown":
        serve.shutdown()
        print("serve shut down")


# -- state commands ----------------------------------------------------------

_LISTABLE = ("nodes", "actors", "tasks", "workers", "objects",
             "placement_groups", "jobs", "cluster_events")


def cmd_list(args):
    from ray_tpu.util.state import api as state

    fn = getattr(state, f"list_{args.resource}")
    rows = fn(address=_resolve_address(args), limit=args.limit)
    if args.format == "json":
        print(json.dumps(rows, indent=2, default=str))
    else:
        for r in rows:
            print(json.dumps(r, default=str))
    print(f"({len(rows)} {args.resource})", file=sys.stderr)


def cmd_summary(args):
    from ray_tpu.util.state import api as state

    fn = getattr(state, f"summarize_{args.resource}")
    print(json.dumps(fn(address=_resolve_address(args)), indent=2,
                     default=str))


def cmd_timeline(args):
    if args.job:
        # training flight-recorder dump: per-step phase breakdowns from
        # every worker of one trial, as Chrome trace-event JSON
        from ray_tpu._private.protocol import Client
        from ray_tpu.telemetry.timeline import (chrome_trace,
                                                collect_remediations,
                                                collect_snapshots)

        address = _resolve_address(args)
        host, port = address.rsplit(":", 1)
        control = Client((host, int(port)), name="cli-timeline")
        try:
            snaps = collect_snapshots(control, trial=args.job)
            rems = collect_remediations(control, trial=args.job)
            trace = chrome_trace(snaps, remediations=rems)
        finally:
            control.close()
        with open(args.output, "w") as f:
            json.dump(trace, f)
        steps = sum(len(s.get("steps", [])) for s in snaps)
        print(f"wrote {args.output} ({len(snaps)} workers, {steps} step "
              f"records, {len(rems)} remediation markers for trial "
              f"{args.job!r})")
        return
    from ray_tpu.util.state import api as state

    state.timeline(args.output, address=_resolve_address(args))
    print(f"wrote {args.output}")


def cmd_trace(args):
    """Reassemble a distributed trace from the control plane's span
    collector: span tree + critical-path phase/process attribution, or
    a cross-trace latency summary with --summary."""
    from ray_tpu._private.protocol import Client
    from ray_tpu.telemetry import trace_assembly as ta

    address = _resolve_address(args)
    host, port = address.rsplit(":", 1)
    control = Client((host, int(port)), name="cli-trace")
    try:
        if args.summary or not args.trace_id:
            summary = ta.summarize(control, job_id=args.job)
            if args.format == "json":
                print(json.dumps(summary, indent=2, default=str))
            else:
                print(ta.render_summary_text(summary))
            return
        spans = ta.fetch_trace(control, args.trace_id)
        if not spans:
            ids = ta.list_trace_ids(control)
            print(f"trace {args.trace_id!r} not found "
                  f"({len(ids)} trace(s) in the collector"
                  + (": " + ", ".join(i[:16] + "…" for i in ids[:8])
                     if ids else "") + ")", file=sys.stderr)
            raise SystemExit(1)
        analysis = ta.analyze(spans)
        if args.output:
            with open(args.output, "w") as f:
                json.dump(ta.chrome_trace(spans), f)
            print(f"wrote {args.output} ({len(spans)} spans)",
                  file=sys.stderr)
        if args.format == "json":
            print(json.dumps(analysis, indent=2, default=str))
        else:
            print(ta.render_text(analysis))
    finally:
        control.close()


def cmd_remediations(args):
    """List a training run's cause→action→effect self-healing log."""
    from ray_tpu._private.protocol import Client
    from ray_tpu.elastic.remediation import fetch_records

    address = _resolve_address(args)
    host, port = address.rsplit(":", 1)
    control = Client((host, int(port)), name="cli-remediations")
    try:
        records = fetch_records(control, args.job)
    finally:
        control.close()
    if args.format == "json":
        print(json.dumps(records, indent=2, default=str))
        return
    if not records:
        print(f"no remediation records for trial {args.job!r}")
        return
    for rec in records:
        cause = rec.get("cause") or {}
        action = rec.get("action") or {}
        effect = rec.get("effect")
        dry = " (dry-run)" if action.get("dry_run") else ""
        print(f"{rec.get('id')}  [{rec.get('mode')}]{dry}")
        print(f"  cause:  rank {cause.get('rank')} straggling — step "
              f"{cause.get('step_s')}s vs gang median "
              f"{cause.get('median_s')}s (x{cause.get('ratio')}), "
              f"sustained {action.get('confirmed_rounds')} rounds")
        tgt = f" node {str(action.get('node_id'))[:12]}" \
            if action.get("node_id") else ""
        world = f" -> world {action.get('new_world')}" \
            if action.get("new_world") is not None else ""
        print(f"  action: {action.get('kind')} rank {action.get('rank')}"
              f"{tgt} (grace {action.get('grace_s')}s){world}")
        if effect is None:
            print("  effect: (not yet measured)")
        else:
            verdict = "recovered" if effect.get("recovered") \
                else "NOT recovered"
            print(f"  effect: gang median busy {effect.get('post_busy_s')}s "
                  f"vs baseline {effect.get('baseline_busy_s')}s over "
                  f"{effect.get('measured_rounds')} rounds — {verdict} "
                  f"(tolerance {effect.get('tolerance'):.0%})")


def cmd_memory(args):
    from ray_tpu.util.state import api as state

    address = _resolve_address(args)
    print(json.dumps(state.summarize_objects(address=address), indent=2))


def cmd_control_stats(args):
    """Control-plane flight recorder: per-handler latency table plus
    loop-lag / KV / pubsub / event-relay counters."""
    from ray_tpu.util.state import api as state

    snap = state.control_stats(address=_resolve_address(args),
                               per_node=args.per_node)
    if args.format == "json":
        print(json.dumps(snap, indent=2, default=str))
        return

    def _table(handlers):
        rows = []
        for method, s in sorted(handlers.items()):
            if not s.get("count") and not args.all:
                continue
            q, h = s.get("queue_ms") or {}, s.get("handle_ms") or {}
            budget = s.get("budget_ms")
            rows.append((
                method, s.get("count", 0), s.get("errors", 0),
                s.get("in_flight", 0),
                f"{q.get('p50_ms', 0):g}/{q.get('p99_ms', 0):g}",
                f"{h.get('p50_ms', 0):g}/{h.get('p99_ms', 0):g}",
                f"{budget:g}" if budget is not None else "-",
                s.get("budget_exceeded", 0) if budget is not None else "-",
            ))
        if not rows:
            print("  (no calls recorded)")
            return
        hdr = ("handler", "count", "err", "infl", "queue p50/p99 ms",
               "handle p50/p99 ms", "budget", "over")
        widths = [max(len(str(r[i])) for r in rows + [hdr])
                  for i in range(len(hdr))]
        for r in [hdr] + rows:
            print("  " + "  ".join(str(v).ljust(w)
                                   for v, w in zip(r, widths)).rstrip())

    c = snap["control"]
    print(f"control plane (up {c.get('uptime_s', 0):.0f}s, "
          f"{c.get('nodes', {}).get('alive', 0)} alive node(s))")
    _table(c.get("handlers") or {})
    loop = c.get("loop") or {}
    lag = loop.get("lag_ms") or {}
    print(f"loop: lag p99 {lag.get('p99_ms', 0):g}ms "
          f"max {lag.get('max_ms', 0):g}ms over {lag.get('count', 0)} "
          f"ticks, {loop.get('frames', 0)} frames in "
          f"{loop.get('drains', 0)} drains "
          f"(max batch {loop.get('max_drain_batch', 0)}), "
          f"{loop.get('connections', 0)} connection(s)")
    kv = c.get("kv") or {}
    if kv:
        print("kv namespaces:")
        for ns, s in sorted(kv.items(), key=lambda i: -i[1]["ops"]):
            print(f"  {ns:24s} ops {s['ops']:<8d} "
                  f"in {s['bytes_in']:<10d} out {s['bytes_out']}")
    ps = c.get("pubsub") or {}
    if ps:
        print("pubsub topics:")
        for t, s in sorted(ps.items(), key=lambda i: -i[1]["publishes"]):
            n = max(1, s.get("publishes", 0))
            print(f"  {t:24s} pub {s['publishes']:<7d} "
                  f"deliv {s['deliveries']:<8d} "
                  f"drop {s['dropped_subscribers']:<4d} "
                  f"fanout avg {s['fanout_ms_total'] / n:.3f}ms "
                  f"max {s['fanout_ms_max']:.3f}ms")
    ev = c.get("events") or {}
    print(f"task events: queue {ev.get('queue_depth', 0)}, "
          f"records {ev.get('task_records', 0)}, "
          f"dropped {ev.get('dropped', 0)}, relay batches "
          f"{ev.get('relay_batches', 0)} "
          f"(+{ev.get('relay_dropped', 0)} dropped in relays)")
    tr = c.get("tracing") or {}
    if tr.get("spans") or tr.get("traces"):
        print(f"trace spans: queue {tr.get('queue_depth', 0)}, "
              f"traces {tr.get('traces', 0)}, "
              f"spans {tr.get('spans', 0)} in "
              f"{tr.get('span_batches', 0)} batches, "
              f"dropped {tr.get('dropped', 0)}, "
              f"per-trace overflow {tr.get('span_overflow', 0)}, "
              f"evicted {tr.get('traces_evicted', 0)}")
    for nid, r in (snap.get("raylets") or {}).items():
        if "error" in r:
            print(f"raylet {nid[:12]}: error: {r['error']}")
            continue
        rl = r.get("loop") or {}
        rlag = rl.get("lag_ms") or {}
        print(f"raylet {nid[:12]} (loop lag p99 "
              f"{rlag.get('p99_ms', 0):g}ms)")
        _table(r.get("handlers") or {})


def cmd_device_stats(args):
    """Device runtime observability: per-program compile/recompile
    counts with recompile cause diffs, storm advisories, and HBM /
    KV-page memory census per worker."""
    from ray_tpu.util.state import api as state

    snap = state.device_stats(address=_resolve_address(args))
    if args.format == "json":
        print(json.dumps(snap, indent=2, default=str))
        return

    def _mb(n):
        return f"{n / (1 << 20):.1f}MB"

    def _cause(cause):
        if isinstance(cause, dict):
            note = cause.get("note")
            cause = cause.get("changes")
            if not cause:
                return note or "-"
        if not cause:
            return "-"
        parts = [f"{c.get('arg')}: {c.get('kind')} "
                 f"{c.get('old')} -> {c.get('new')}" for c in cause[:3]]
        if len(cause) > 3:
            parts.append(f"(+{len(cause) - 3} more)")
        return "; ".join(parts)

    progs = snap.get("programs") or {}
    print(f"compilation ledger: {len(snap.get('workers') or {})} "
          f"worker(s), {snap.get('total_compiles', 0)} compile(s), "
          f"{snap.get('total_recompiles', 0)} recompile(s), "
          f"live HBM {_mb(snap.get('live_bytes', 0))}")
    if progs:
        rows = [(name, st["compiles"], st["recompiles"],
                 st["storm_episodes"], st["workers"],
                 _cause(st.get("last_cause")))
                for name, st in sorted(progs.items())]
        hdr = ("program", "compiles", "recomp", "storms", "workers",
               "last recompile cause")
        widths = [max(len(str(r[i])) for r in rows + [hdr])
                  for i in range(len(hdr))]
        for r in [hdr] + rows:
            print("  " + "  ".join(str(v).ljust(w)
                                   for v, w in zip(r, widths)).rstrip())
    else:
        print("  (no compiles recorded)")
    advs = snap.get("advisories") or []
    if advs:
        print("advisories:")
        for a in advs[-10:]:
            kind = a.get("kind", "?")
            if kind == "recompile_storm":
                print(f"  [{a.get('worker_id', '?')[:12]}] storm: "
                      f"{a.get('program')} x{a.get('compiles_in_window')}"
                      f" in {a.get('window_s')}s — "
                      f"{_cause(a.get('cause'))}")
            elif kind == "memory_watermark":
                print(f"  [{a.get('worker_id', '?')[:12]}] watermark: "
                      f"live {_mb(a.get('live_bytes', 0))} >= "
                      f"{_mb(a.get('watermark_bytes', 0))}")
            else:
                print(f"  [{a.get('worker_id', '?')[:12]}] {kind}: {a}")
    for wid, wsnap in sorted((snap.get("workers") or {}).items()):
        mem = wsnap.get("memory") or {}
        live = mem.get("live") or {}
        line = (f"worker {wid[:16]}: live {_mb(live.get('total_bytes', 0))}"
                f" in {live.get('count', 0)} buffer(s)")
        owners = mem.get("owners") or {}
        for tag, rep in sorted(owners.items()):
            pages = rep.get("pages")
            if isinstance(pages, dict):
                line += (f"; {tag}: pages free {pages.get('free', 0)} "
                         f"used {pages.get('used', 0)} "
                         f"shared {pages.get('shared', 0)} "
                         f"cow {pages.get('cow', 0)}")
            elif "bytes" in rep:
                line += f"; {tag}: {_mb(rep.get('bytes', 0))}"
        print(line)


def cmd_analyze(args):
    from ray_tpu import analysis
    from ray_tpu.analysis import baseline as bl

    findings = analysis.run_analysis(args.paths or None)
    bl_path = args.baseline or bl.default_path()
    if args.update_baseline:
        bl.save(bl_path, findings)
        print(f"baseline updated: {len(findings)} finding(s) -> {bl_path}")
        return
    known = bl.load(bl_path)
    new, suppressed, stale = bl.diff(findings, known)
    if args.format == "json":
        print(json.dumps({
            "new": [{"key": f.key, "line": f.line, "file": f.file,
                     "message": f.message} for f in new],
            "suppressed": len(suppressed),
            "stale": stale,
        }, indent=2))
    else:
        for f in new:
            print(f"NEW  {f.render()}")
        if args.verbose:
            for f in suppressed:
                print(f"okay {f.render()}  [baselined]")
        for k in stale:
            print(f"stale baseline entry (fixed?): {k}")
        print(f"analyze: {len(new)} new, {len(suppressed)} baselined, "
              f"{len(stale)} stale")
    if new:
        print("new findings: fix them or re-run with --update-baseline",
              file=sys.stderr)
        raise SystemExit(1)


# -- parser ------------------------------------------------------------------

def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ray-tpu", description="ray_tpu cluster CLI")
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("start", help="start a head or worker node")
    sp.add_argument("--head", action="store_true")
    sp.add_argument("--address", default=None,
                    help="control address to join (worker nodes)")
    sp.add_argument("--port", type=int, default=None)
    sp.add_argument("--node-ip-address", default="127.0.0.1")
    sp.add_argument("--num-cpus", type=float, default=None)
    sp.add_argument("--num-tpus", type=float, default=None)
    sp.add_argument("--resources", default=None, help="JSON dict")
    sp.add_argument("--block", action="store_true")
    sp.set_defaults(fn=cmd_start)

    sp = sub.add_parser("config", help="print the resolved flag table")
    sp.set_defaults(fn=cmd_config)

    sp = sub.add_parser("debug", help="attach to a remote breakpoint")
    sp.add_argument("--address", default=None)
    sp.add_argument("--index", type=int, default=None,
                    help="breakpoint index (default: first)")
    sp.set_defaults(fn=cmd_debug)

    sp = sub.add_parser("up", help="launch a cluster from a YAML config")
    sp.add_argument("config", help="cluster YAML (cluster_name, provider, "
                                   "head_node, worker_nodes)")
    sp.add_argument("--port", type=int, default=None)
    sp.set_defaults(fn=cmd_up)

    sp = sub.add_parser("down", help="tear down the cluster from `up`")
    sp.set_defaults(fn=cmd_down)

    sp = sub.add_parser("stop", help="stop the local cluster")
    sp.set_defaults(fn=cmd_stop)

    sp = sub.add_parser("status", help="cluster status")
    sp.add_argument("--address", default=None)
    sp.set_defaults(fn=cmd_status)

    sp = sub.add_parser("submit", help="submit a job")
    sp.add_argument("--address", default=None)
    sp.add_argument("--runtime-env", default=None, help="JSON dict")
    sp.add_argument("--submission-id", default=None)
    sp.add_argument("--no-wait", action="store_true")
    sp.add_argument("--timeout", type=float, default=3600.0)
    sp.add_argument("entrypoint", nargs=argparse.REMAINDER)
    sp.set_defaults(fn=cmd_submit)

    sp = sub.add_parser("job", help="manage jobs")
    jsub = sp.add_subparsers(dest="job_cmd", required=True)
    for c in ("list", "status", "logs", "stop"):
        jp = jsub.add_parser(c)
        jp.add_argument("--address", default=None)
        if c != "list":
            jp.add_argument("id")
    sp.set_defaults(fn=cmd_job)

    sp = sub.add_parser("logs", help="list/tail cluster log files")
    sp.add_argument("name", nargs="?", default=None,
                    help="log file name (omit to list)")
    sp.add_argument("--tail", type=int, default=64 * 1024,
                    help="bytes from the end")
    sp.add_argument("--address", default=None)
    sp.set_defaults(fn=cmd_logs)

    sp = sub.add_parser("serve", help="manage serve applications")
    ssub = sp.add_subparsers(dest="serve_cmd", required=True)
    dp = ssub.add_parser("deploy", help="deploy apps from a YAML config")
    dp.add_argument("config")
    dp.add_argument("--address", default=None)
    stp = ssub.add_parser("status")
    stp.add_argument("--address", default=None)
    shp = ssub.add_parser("shutdown")
    shp.add_argument("--address", default=None)
    sp.set_defaults(fn=cmd_serve)

    sp = sub.add_parser("list", help="list cluster entities")
    sp.add_argument("resource", choices=_LISTABLE)
    sp.add_argument("--address", default=None)
    sp.add_argument("--limit", type=int, default=100)
    sp.add_argument("--format", choices=("jsonl", "json"), default="jsonl")
    sp.set_defaults(fn=cmd_list)

    sp = sub.add_parser("summary", help="summarize tasks/actors/objects")
    sp.add_argument("resource", choices=("tasks", "actors", "objects"))
    sp.add_argument("--address", default=None)
    sp.set_defaults(fn=cmd_summary)

    sp = sub.add_parser("timeline", help="export Chrome trace (pass a "
                        "trial name for the training flight recorder)")
    sp.add_argument("job", nargs="?", default=None,
                    help="trial name: dump that run's per-step telemetry "
                         "instead of the cluster task timeline")
    sp.add_argument("-o", "--output", default="timeline.json")
    sp.add_argument("--address", default=None)
    sp.set_defaults(fn=cmd_timeline)

    sp = sub.add_parser(
        "trace",
        help="reassemble a distributed trace (span tree + critical-path "
             "attribution) from the control-plane span collector")
    sp.add_argument("trace_id", nargs="?", default=None,
                    help="32-hex trace id (from a span record or "
                         "BENCH_TASKS.json critical_path row)")
    sp.add_argument("--summary", action="store_true",
                    help="aggregate phase attribution across all stored "
                         "traces instead of showing one")
    sp.add_argument("--job", default=None,
                    help="with --summary: only traces touching this job")
    sp.add_argument("-o", "--output", default=None,
                    help="also write the trace as Perfetto/Chrome "
                         "trace-event JSON")
    sp.add_argument("--address", default=None)
    sp.add_argument("--format", choices=("text", "json"), default="text")
    sp.set_defaults(fn=cmd_trace)

    sp = sub.add_parser("remediations",
                        help="list a run's cause→action→effect "
                             "self-healing log")
    sp.add_argument("job", help="trial name")
    sp.add_argument("--address", default=None)
    sp.add_argument("--format", choices=("text", "json"), default="text")
    sp.set_defaults(fn=cmd_remediations)

    sp = sub.add_parser(
        "analyze",
        help="static concurrency/JAX-purity analysis (AST-based)")
    sp.add_argument("paths", nargs="*",
                    help="files or directories (default: the ray_tpu "
                         "package)")
    sp.add_argument("--baseline", default=None,
                    help="baseline JSON path (default: "
                         "<repo>/analysis_baseline.json)")
    sp.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from this scan")
    sp.add_argument("--format", choices=("text", "json"), default="text")
    sp.add_argument("-v", "--verbose", action="store_true",
                    help="also list baselined findings")
    sp.set_defaults(fn=cmd_analyze)

    sp = sub.add_parser("memory", help="object store summary")
    sp.add_argument("--address", default=None)
    sp.set_defaults(fn=cmd_memory)

    sp = sub.add_parser(
        "control-stats",
        help="control-plane flight recorder: per-handler RPC latency, "
             "loop lag, KV/pubsub/event counters")
    sp.add_argument("--address", default=None)
    sp.add_argument("--per-node", action="store_true",
                    help="also query every raylet's rpc/loop stats")
    sp.add_argument("--all", action="store_true",
                    help="include handlers with zero calls")
    sp.add_argument("--format", choices=("text", "json"), default="text")
    sp.set_defaults(fn=cmd_control_stats)

    sp = sub.add_parser(
        "device-stats",
        help="XLA compilation ledger + device-memory census: per-program "
             "compile/recompile counts, recompile cause diffs, storm "
             "advisories, HBM/KV-page occupancy")
    sp.add_argument("--address", default=None)
    sp.add_argument("--format", choices=("text", "json"), default="text")
    sp.set_defaults(fn=cmd_device_stats)

    return p


def main(argv=None):
    args = build_parser().parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
