"""WorkerGroup: the gang of training worker actors.

Mirrors the reference (reference: python/ray/train/_internal/
worker_group.py — WorkerGroup, RayTrainWorker): N actors created inside a
placement group, each exposing `execute` (run an arbitrary fn in the worker)
plus the session lifecycle used by the BackendExecutor.
"""

from __future__ import annotations

import logging
import os
import socket
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.util import (PlacementGroupSchedulingStrategy, placement_group,
                          remove_placement_group)

from .checkpoint import Checkpoint
from .session import TrainContext, TrainSession, _set_session

logger = logging.getLogger(__name__)


class RayTrainWorker:
    """The actor class running on every training worker."""

    def __init__(self):
        self._session: Optional[TrainSession] = None

    # -- generic execution -------------------------------------------------

    def execute(self, fn: Callable, *args, **kwargs):
        return fn(*args, **kwargs)

    def node_metadata(self) -> Dict[str, Any]:
        # TPU presence detected on THIS worker's node (libtpu device files /
        # explicit platform pin), not the driver's environment.
        has_tpu = (os.path.exists("/dev/accel0")
                   or os.path.exists("/dev/vfio/0")
                   or os.environ.get("JAX_PLATFORMS", "") == "tpu")
        return {
            "hostname": socket.gethostname(),
            "pid": os.getpid(),
            "node_ip": os.environ.get("RAY_TPU_NODE_IP", "127.0.0.1"),
            # which raylet hosts this worker — the elastic supervisor
            # matches drain notices (keyed by node_id) to workers
            "node_id": os.environ.get("RAY_TPU_NODE_ID"),
            "has_tpu": has_tpu,
        }

    def set_env_vars(self, env: Dict[str, str]):
        os.environ.update(env)

    def ping(self) -> bool:
        """Cheap liveness probe (elastic recovery separates slow from
        dead with a short-timeout ping rather than waiting for the
        heartbeat-timeout death declaration)."""
        return True

    # -- session lifecycle -------------------------------------------------

    def start_session(self, ctx: TrainContext, train_fn: Callable,
                      config: Dict[str, Any],
                      checkpoint: Optional[Checkpoint],
                      upload_dir: Optional[str],
                      dataset_shards: Optional[Dict[str, Any]] = None,
                      start_iteration: int = 0):
        import inspect

        params = inspect.signature(train_fn).parameters
        wrapped = (lambda: train_fn(config)) if params else train_fn
        self._session = TrainSession(ctx, wrapped, checkpoint=checkpoint,
                                     checkpoint_upload_dir=upload_dir,
                                     dataset_shards=dataset_shards,
                                     start_iteration=start_iteration)
        self._session.start()
        return True

    def next_result(self):
        assert self._session is not None, "session not started"
        return self._session.next_result()

    def end_session(self):
        if self._session is not None:
            self._session.finish()
            self._session = None
            _set_session(None)
        return True

    def abort_session(self) -> bool:
        """Unwind the user loop without killing the worker process — the
        elastic restart path keeps surviving actors alive (their
        emergency-checkpoint vaults are the recovery source).

        Short join: a loop blocked inside a collective (waiting on a
        peer that just died) unwinds on its own once the kv poll times
        out; recovery must not wait for it — this call doubles as the
        driver's reachability probe and has to answer fast."""
        if self._session is None:
            return False
        self._session.abort(timeout=0.2)
        self._session = None
        _set_session(None)
        return True


class Worker:
    def __init__(self, actor, metadata: Dict[str, Any]):
        self.actor = actor
        self.metadata = metadata


class WorkerGroup:
    def __init__(self, num_workers: int, bundles: List[Dict[str, float]],
                 placement_strategy: str = "PACK",
                 actor_cls=RayTrainWorker):
        self.num_workers = num_workers
        # bumped by shrink_to(); backends fold it into collective group
        # names so a rebuilt gang never collides with the old rendezvous
        self.incarnation = 0
        self._pg = placement_group(bundles, strategy=placement_strategy)
        if not self._pg.ready(timeout=60.0):
            remove_placement_group(self._pg)
            try:
                state = (f"cluster={ray_tpu.cluster_resources()} "
                         f"available={ray_tpu.available_resources()}")
            except Exception:
                state = "(cluster state unavailable)"
            raise RuntimeError(
                f"could not reserve {bundles} for {num_workers} training "
                f"workers (cluster too small?); {state}")
        remote_cls = ray_tpu.remote(actor_cls)
        self.workers: List[Worker] = []
        handles = []
        try:
            for i in range(num_workers):
                b = bundles[i]
                handles.append(remote_cls.options(
                    num_cpus=b.get("CPU", 0),
                    num_tpus=b.get("TPU", 0) or None,
                    resources={k: v for k, v in b.items()
                               if k not in ("CPU", "TPU")} or None,
                    max_concurrency=2,  # next_result blocks; keep control lane free
                    scheduling_strategy=PlacementGroupSchedulingStrategy(
                        self._pg, placement_group_bundle_index=i),
                ).remote())
            metas = ray_tpu.get([h.node_metadata.remote() for h in handles])
        except Exception:
            for h in handles:
                try:
                    ray_tpu.kill(h)
                except Exception:
                    pass
            try:
                remove_placement_group(self._pg)
            except Exception:
                pass
            raise
        self.workers = [Worker(h, m) for h, m in zip(handles, metas)]

    @property
    def placement_group(self):
        return self._pg

    def execute(self, fn: Callable, *args, **kwargs) -> List[Any]:
        """Run fn on every worker, return all results (ordered by rank)."""
        return ray_tpu.get(self.execute_async(fn, *args, **kwargs))

    def execute_async(self, fn: Callable, *args, **kwargs):
        from ray_tpu._private import common as _common

        _common._ensure_picklable_by_value(fn)
        return [w.actor.execute.remote(fn, *args, **kwargs)
                for w in self.workers]

    def execute_single(self, rank: int, fn: Callable, *args, **kwargs) -> Any:
        return ray_tpu.get(
            self.workers[rank].actor.execute.remote(fn, *args, **kwargs))

    # -- elastic support ---------------------------------------------------

    def ping_workers(self, timeout: float = 5.0) -> List[bool]:
        """Probe every worker with a shared deadline; True per index that
        answered.  Does not wait for the control plane's death declaration
        — a worker that can't answer within `timeout` is treated as lost
        by the elastic recovery path regardless of its official state."""
        import time

        refs = [w.actor.ping.remote() for w in self.workers]
        deadline = time.monotonic() + timeout
        alive = []
        for ref in refs:
            budget = max(0.05, deadline - time.monotonic())
            try:
                alive.append(bool(ray_tpu.get(ref, timeout=budget)))
            except Exception:
                alive.append(False)
        return alive

    def shrink_to(self, keep_indices: List[int]):
        """Rebuild the gang from the surviving subset, in the given order.

        Dropped actors are killed best-effort; the placement group is
        kept (its bundles on dead nodes are simply unused — recreating a
        PG mid-recovery would race the drain deadline)."""
        keep = set(keep_indices)
        for i, w in enumerate(self.workers):
            if i not in keep:
                try:
                    ray_tpu.kill(w.actor)
                except Exception:
                    pass
        self.workers = [self.workers[i] for i in keep_indices]
        self.num_workers = len(self.workers)
        self.incarnation += 1

    def shutdown(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w.actor)
            except Exception:
                pass
        self.workers = []
        try:
            remove_placement_group(self._pg)
        except Exception:
            pass
