"""BackendExecutor: drives the training worker group from the driver side.

Mirrors the reference (reference: python/ray/train/_internal/
backend_executor.py — start :135, _create_placement_group :219,
start_training :451, get_next_results :578, _restart :759): create the gang
placement group + WorkerGroup, run backend setup hooks, start per-worker
sessions, and poll results in lockstep.  Worker death surfaces as
TrainingWorkerError so the trainer can tear down and restart the whole
group from the latest checkpoint (elastic recovery; a jax SPMD program
cannot survive losing a participant mid-step, so whole-group restart is
the only sound recovery unit on TPU).
"""

from __future__ import annotations

import logging
import os
from collections import defaultdict
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu import ActorDiedError, WorkerCrashedError

from .backend import BackendConfig, JaxConfig
from .checkpoint import Checkpoint
from .config import ScalingConfig
from .session import TrainContext
from .worker_group import WorkerGroup

logger = logging.getLogger(__name__)


class TrainingWorkerError(Exception):
    """A worker actor died mid-training (restartable condition)."""


class WorkerDrainedError(TrainingWorkerError):
    """A node hosting training workers posted a drain notice: restart
    proactively (before the host disappears) rather than reactively."""


class WorkerQuarantinedError(TrainingWorkerError):
    """Remediation quarantined a sustained straggler's node: rebalance
    the gang off it (the host is alive — merely benched — so its vault
    remains a recovery source)."""


class EmergencyRecoveryError(Exception):
    """Elastic in-memory recovery is not possible (no quorum of
    replicated shards / too few survivors); fall back to the
    storage-checkpoint restart path."""


class TrainingFailedError(Exception):
    """User train code raised; not restartable."""


class BackendExecutor:
    def __init__(self, backend_config: Optional[BackendConfig] = None,
                 scaling_config: Optional[ScalingConfig] = None):
        self._backend_config = backend_config or JaxConfig()
        self._scaling = scaling_config or ScalingConfig()
        self._backend = self._backend_config.backend_cls()()
        self.worker_group: Optional[WorkerGroup] = None
        self._elastic = getattr(self._backend_config, "elastic", None)
        self._draining_nodes: set = set()
        self._quarantined_nodes: set = set()
        self._drain_listener_installed = False
        # rounds consumed since the last (re)start — the elastic restart
        # resumes session iteration numbering from here
        self.rounds_consumed = 0
        # GoodputAccountant installed by the trainer; drain/recover paths
        # stamp state transitions through it when present
        self.goodput = None
        # per-incarnation effective-rate records feeding goodput-predicted
        # width selection in elastic_recover
        from ray_tpu.elastic.resume import IncarnationHistory

        self.history = IncarnationHistory()

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        s = self._scaling
        if self._elastic is not None:
            self._elastic.validate_for(s.num_workers)
        self.worker_group = WorkerGroup(
            s.num_workers, s.as_placement_group_bundles(),
            placement_strategy=s.placement_strategy)
        self._backend.on_start(self.worker_group, self._backend_config)
        if self._elastic is not None:
            self._install_drain_listener()

    # -- drain notices -----------------------------------------------------

    def _install_drain_listener(self):
        """Track node_draining advisories from the control plane's pubsub
        (drivers already subscribe to the `node` topic)."""
        if self._drain_listener_installed:
            return
        try:
            from ray_tpu._private.core import current_core

            current_core().add_push_handler("pub:node", self._on_node_event)
            self._drain_listener_installed = True
        except Exception:
            logger.warning("could not install drain listener; elastic "
                           "recovery will rely on worker death only",
                           exc_info=True)

    def _remove_drain_listener(self):
        if not self._drain_listener_installed:
            return
        self._drain_listener_installed = False
        try:
            from ray_tpu._private.core import current_core

            current_core().remove_push_handler("pub:node",
                                               self._on_node_event)
        except Exception:
            pass

    def _on_node_event(self, payload: Dict[str, Any]):
        event = payload.get("event")
        node = payload.get("node") or {}
        nid = node.get("node_id")
        if not nid:
            return
        if event == "draining":
            self._draining_nodes.add(nid)
            if self.goodput is not None:
                try:
                    if self.drain_pending():
                        self.goodput.transition("draining", node=nid)
                except Exception:
                    pass
        elif event in ("drain_canceled", "removed"):
            self._draining_nodes.discard(nid)
            if event == "removed":
                self._quarantined_nodes.discard(nid)
        elif event == "quarantined":
            self._quarantined_nodes.add(nid)
        elif event == "quarantine_cleared":
            self._quarantined_nodes.discard(nid)

    def drain_pending(self) -> bool:
        """True when any current training worker sits on a draining node."""
        if not self._draining_nodes or self.worker_group is None:
            return False
        return any(w.metadata.get("node_id") in self._draining_nodes
                   for w in self.worker_group.workers)

    def quarantine_worker(self, rank: int, reason: str,
                          grace_s: float = 600.0) -> Optional[str]:
        """Remediation enforcement: bench the node hosting ``rank`` on
        the control plane (scheduler avoidance + ``node_quarantined``
        pubsub) and exclude it locally so the next ``elastic_recover``
        sheds it.  Returns the node id (None when unknown).  The local
        exclusion is authoritative — a pubsub lag or an unreachable
        control must not leave the straggler in the rebuilt gang."""
        wg = self.worker_group
        if wg is None or not (0 <= rank < len(wg.workers)):
            return None
        nid = wg.workers[rank].metadata.get("node_id")
        if not nid:
            return None
        self._quarantined_nodes.add(nid)
        try:
            from ray_tpu._private.core import current_core

            current_core().control.call("report_quarantine", {
                "node_id": nid, "grace_s": grace_s, "reason": reason,
            }, timeout=5.0)
        except Exception:
            logger.warning("could not report quarantine of node %s to the "
                           "control plane (local exclusion still applies)",
                           nid[:12], exc_info=True)
        return nid

    def _contexts(self, experiment_name: str, trial_name: str,
                  trial_dir: str) -> List[TrainContext]:
        """Compute world/local/node ranks from worker placement (the
        reference groups by node ip, backend_executor.py:388)."""
        ips = []
        for w in self.worker_group.workers:
            ip = w.metadata.get("node_ip", "?")
            if ip not in ips:
                ips.append(ip)
        ctxs = []
        local_rank_counter: Dict[str, int] = defaultdict(int)
        for i, w in enumerate(self.worker_group.workers):
            ip = w.metadata.get("node_ip", "?")
            lr = local_rank_counter[ip]
            local_rank_counter[ip] += 1
            ctxs.append(TrainContext(
                world_size=self.worker_group.num_workers,
                world_rank=i,
                local_rank=lr,
                node_rank=ips.index(ip),
                experiment_name=experiment_name,
                trial_name=trial_name,
                trial_id=trial_name,
                trial_dir=trial_dir,
            ))
        for ctx in ctxs:
            ip = self.worker_group.workers[ctx.world_rank].metadata.get(
                "node_ip", "?")
            ctx.local_world_size = local_rank_counter[ip]
        ec = self._elastic
        if ec is not None:
            n = self.worker_group.num_workers
            inc = getattr(self.worker_group, "incarnation", 0)
            if ec.global_batch_size:
                from ray_tpu.elastic.resume import (batch_offsets,
                                                    per_replica_batches)

                batches = per_replica_batches(ec.global_batch_size, n)
                offsets = batch_offsets(batches)
            for ctx in ctxs:
                ctx.extra["elastic_incarnation"] = inc
                if ec.global_batch_size:
                    # the contract that keeps resumed runs comparable to
                    # uninterrupted ones: sum(per_replica_batch) == global
                    # at every width
                    ctx.extra["global_batch_size"] = ec.global_batch_size
                    ctx.extra["per_replica_batch"] = batches[ctx.world_rank]
                    ctx.extra["batch_offset"] = offsets[ctx.world_rank]
        try:
            from ray_tpu.telemetry import resolve_telemetry

            tc = resolve_telemetry(
                getattr(self._backend_config, "telemetry", None))
            for ctx in ctxs:
                ctx.extra["telemetry"] = tc.to_dict()
        except Exception:
            pass
        return ctxs

    def start_training(self, train_fn: Callable, config: Dict[str, Any],
                       experiment_name: str, trial_name: str, trial_dir: str,
                       checkpoint: Optional[Checkpoint] = None,
                       dataset_shards_per_worker: Optional[List[Dict[str, Any]]] = None,
                       start_iteration: int = 0,
                       per_worker_checkpoints: Optional[List[Optional[Checkpoint]]] = None):
        from . import storage

        storage.makedirs(trial_dir)
        from ray_tpu._private import common as _common

        _common._ensure_picklable_by_value(train_fn)
        self._backend.on_training_start(self.worker_group,
                                        self._backend_config)
        ctxs = self._contexts(experiment_name, trial_name, trial_dir)
        shards = dataset_shards_per_worker or [None] * len(ctxs)
        cks = per_worker_checkpoints or [checkpoint] * len(ctxs)
        refs = [
            w.actor.start_session.remote(ctxs[i], train_fn, config,
                                         cks[i], trial_dir, shards[i],
                                         start_iteration)
            for i, w in enumerate(self.worker_group.workers)
        ]
        self._get_with_failure_handling(refs)
        import time as _time

        self.history.begin(getattr(self.worker_group, "incarnation", 0),
                           self.worker_group.num_workers,
                           self.rounds_consumed, _time.monotonic())

    def get_next_results(self) -> Optional[List[tuple]]:
        """One lockstep round of next_result() from every worker.

        Returns None when all workers finished; raises TrainingFailedError
        on a user exception; TrainingWorkerError on actor death;
        WorkerDrainedError (before issuing the round) when a hosting node
        posted a drain notice — restarting at a report() boundary is what
        keeps elastic recovery deterministic.
        """
        if self._elastic is not None and self.drain_pending():
            draining = sorted(
                n for n in self._draining_nodes
                if any(w.metadata.get("node_id") == n
                       for w in self.worker_group.workers))
            raise WorkerDrainedError(
                f"training workers on draining node(s) {draining}")
        refs = [w.actor.next_result.remote()
                for w in self.worker_group.workers]
        results = self._get_with_failure_handling(refs)
        kinds = {r[0] for r in results}
        if kinds == {"finished"}:
            return None
        if "finished" in kinds:
            # some workers returned while others still report: the loop is
            # mis-specified (unequal iteration counts); fail loudly.
            raise TrainingFailedError(
                "training workers returned out of sync: some finished while "
                "others are still reporting; ensure every worker runs the "
                "same number of report() calls")
        self.rounds_consumed += 1
        return results

    def _get_with_failure_handling(self, refs):
        try:
            return ray_tpu.get(refs)
        except (ActorDiedError, WorkerCrashedError) as e:
            raise TrainingWorkerError(str(e)) from e
        except (TrainingWorkerError, TrainingFailedError):
            raise
        except ray_tpu.TaskError as e:
            raise TrainingFailedError(str(e)) from e

    # -- elastic recovery --------------------------------------------------

    def elastic_recover(self):
        """Shrink-to-fit restart after a drain notice or worker death.

        Sequence (all with short timeouts — the whole point is finishing
        inside the drain grace / well under the death-timeout interval):

          1. abort sessions on every reachable worker (frees their result
             lanes; survivors stay alive — their in-memory vaults are the
             recovery source),
          2. pick survivors = reachable workers NOT on draining nodes,
          3. select the freshest fully-covered snapshot step across ALL
             reachable vaults (draining hosts are still up and fetchable),
          4. pull the shard payloads to the driver BEFORE shrinking,
          5. shrink the gang to the largest feasible width, re-run backend
             setup (new collective group incarnation, re-armed
             checkpointers),
          6. hand back per-rank EmergencyCheckpoints (old-world shards
             folded onto new ranks) for a fresh start_training call.

        Returns (per_worker_checkpoints, step, new_world_size).
        Raises EmergencyRecoveryError when in-memory recovery can't work;
        InsufficientWorkersError when survivors < min_workers.
        """
        import time

        from ray_tpu.elastic.emergency import (EmergencyCheckpoint,
                                               _fetch, _inventory,
                                               fold_shards, select_quorum)
        from ray_tpu.elastic.resume import choose_width

        ec = self._elastic
        if ec is None:
            raise EmergencyRecoveryError("no ElasticConfig on the backend")
        wg = self.worker_group
        if wg is None:
            raise EmergencyRecoveryError("worker group not started")
        # close the dying incarnation's history record — its effective
        # rate (recovery churn included) informs the width choice below
        self.history.end(self.rounds_consumed, time.monotonic())
        if self.goodput is not None:
            try:
                self.goodput.transition("recovering")
            except Exception:
                pass
        t0 = time.monotonic()

        # 1. abort + reachability probe in one pass: a worker that can't
        # abort within the budget is treated as gone.
        abort_refs = [(i, w.actor.abort_session.remote())
                      for i, w in enumerate(wg.workers)]
        deadline = time.monotonic() + ec.recover_timeout_s
        reachable: List[int] = []
        for i, ref in abort_refs:
            budget = max(0.05, deadline - time.monotonic())
            try:
                ray_tpu.get(ref, timeout=budget)
                reachable.append(i)
            except Exception:
                pass

        # 2. survivors exclude draining hosts (reachable now but won't be
        # for long) and quarantined ones (alive but benched by
        # remediation — keeping a sustained straggler in the new gang
        # would defeat the rebalance).
        tainted = self._draining_nodes | self._quarantined_nodes
        survivors = [i for i in reachable
                     if wg.workers[i].metadata.get("node_id")
                     not in tainted]

        # 3. freshest quorum across every vault we can still read.
        inv_refs = [(i, wg.workers[i].actor.execute.remote(_inventory))
                    for i in reachable]
        deadline = time.monotonic() + ec.recover_timeout_s
        inventories: Dict[int, Any] = {}
        for i, ref in inv_refs:
            budget = max(0.05, deadline - time.monotonic())
            try:
                inventories[i] = ray_tpu.get(ref, timeout=budget)
            except Exception:
                pass
        quorum = select_quorum(inventories)
        if quorum is None:
            raise EmergencyRecoveryError(
                "no snapshot step is fully covered by surviving vaults "
                f"(inventories from {sorted(inventories)})")
        step, old_world, holders = quorum

        # 4. pull payloads while the draining hosts are still up.
        payload_refs = [
            (sid, wg.workers[widx].actor.execute.remote(_fetch, step, sid))
            for sid, widx in holders.items()]
        payloads: Dict[int, bytes] = {}
        deadline = time.monotonic() + ec.replicate_timeout_s
        for sid, ref in payload_refs:
            budget = max(0.05, deadline - time.monotonic())
            try:
                b = ray_tpu.get(ref, timeout=budget)
            except Exception as e:
                raise EmergencyRecoveryError(
                    f"failed to fetch shard {sid} of step {step}: {e}") from e
            if b is None:  # vault pruned between inventory and fetch
                raise EmergencyRecoveryError(
                    f"shard {sid} of step {step} vanished from its vault")
            payloads[sid] = b

        # 5. shrink to the goodput-predicted width and re-run backend
        # setup on the new gang.
        new_n = choose_width(len(survivors), ec.min_workers,
                             ec.max_workers, ec.workers_per_replica,
                             history=self.history)
        keep = survivors[:new_n]
        logger.warning(
            "elastic recovery: step=%d old_world=%d survivors=%s -> "
            "new_world=%d (draining=%s quarantined=%s)", step, old_world,
            survivors, new_n, sorted(self._draining_nodes),
            sorted(self._quarantined_nodes))
        wg.shrink_to(keep)
        self._backend.on_start(wg, self._backend_config)

        # 6. fold old-world shards onto the new ranks.
        cks = []
        for r in range(new_n):
            shards = {sid: payloads[sid]
                      for sid in fold_shards(old_world, r, new_n)}
            cks.append(EmergencyCheckpoint(step=step,
                                           source_world_size=old_world,
                                           shards=shards))
        logger.info("elastic recovery completed in %.2fs",
                    time.monotonic() - t0)
        if self.goodput is not None:
            try:
                self.goodput.note_incarnation(
                    getattr(wg, "incarnation", 0))
            except Exception:
                pass
        return cks, step, new_n

    def finish_training(self):
        if self.worker_group is None:
            return
        try:
            ray_tpu.get([w.actor.end_session.remote()
                         for w in self.worker_group.workers])
        except Exception:
            pass

    def shutdown(self):
        self._remove_drain_listener()
        if self.worker_group is not None:
            try:
                self._backend.on_shutdown(self.worker_group,
                                          self._backend_config)
            except Exception:
                pass
            self.worker_group.shutdown()
            self.worker_group = None

    def restart(self):
        """Tear down and respawn the whole group (reference:
        backend_executor.py:759 _restart)."""
        self.shutdown()
        self.start()
