"""BackendExecutor: drives the training worker group from the driver side.

Mirrors the reference (reference: python/ray/train/_internal/
backend_executor.py — start :135, _create_placement_group :219,
start_training :451, get_next_results :578, _restart :759): create the gang
placement group + WorkerGroup, run backend setup hooks, start per-worker
sessions, and poll results in lockstep.  Worker death surfaces as
TrainingWorkerError so the trainer can tear down and restart the whole
group from the latest checkpoint (elastic recovery; a jax SPMD program
cannot survive losing a participant mid-step, so whole-group restart is
the only sound recovery unit on TPU).
"""

from __future__ import annotations

import logging
import os
from collections import defaultdict
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu import ActorDiedError, WorkerCrashedError

from .backend import BackendConfig, JaxConfig
from .checkpoint import Checkpoint
from .config import ScalingConfig
from .session import TrainContext
from .worker_group import WorkerGroup

logger = logging.getLogger(__name__)


class TrainingWorkerError(Exception):
    """A worker actor died mid-training (restartable condition)."""


class TrainingFailedError(Exception):
    """User train code raised; not restartable."""


class BackendExecutor:
    def __init__(self, backend_config: Optional[BackendConfig] = None,
                 scaling_config: Optional[ScalingConfig] = None):
        self._backend_config = backend_config or JaxConfig()
        self._scaling = scaling_config or ScalingConfig()
        self._backend = self._backend_config.backend_cls()()
        self.worker_group: Optional[WorkerGroup] = None

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        s = self._scaling
        self.worker_group = WorkerGroup(
            s.num_workers, s.as_placement_group_bundles(),
            placement_strategy=s.placement_strategy)
        self._backend.on_start(self.worker_group, self._backend_config)

    def _contexts(self, experiment_name: str, trial_name: str,
                  trial_dir: str) -> List[TrainContext]:
        """Compute world/local/node ranks from worker placement (the
        reference groups by node ip, backend_executor.py:388)."""
        ips = []
        for w in self.worker_group.workers:
            ip = w.metadata.get("node_ip", "?")
            if ip not in ips:
                ips.append(ip)
        ctxs = []
        local_rank_counter: Dict[str, int] = defaultdict(int)
        for i, w in enumerate(self.worker_group.workers):
            ip = w.metadata.get("node_ip", "?")
            lr = local_rank_counter[ip]
            local_rank_counter[ip] += 1
            ctxs.append(TrainContext(
                world_size=self.worker_group.num_workers,
                world_rank=i,
                local_rank=lr,
                node_rank=ips.index(ip),
                experiment_name=experiment_name,
                trial_name=trial_name,
                trial_id=trial_name,
                trial_dir=trial_dir,
            ))
        for ctx in ctxs:
            ip = self.worker_group.workers[ctx.world_rank].metadata.get(
                "node_ip", "?")
            ctx.local_world_size = local_rank_counter[ip]
        return ctxs

    def start_training(self, train_fn: Callable, config: Dict[str, Any],
                       experiment_name: str, trial_name: str, trial_dir: str,
                       checkpoint: Optional[Checkpoint] = None,
                       dataset_shards_per_worker: Optional[List[Dict[str, Any]]] = None,
                       start_iteration: int = 0):
        from . import storage

        storage.makedirs(trial_dir)
        from ray_tpu._private import common as _common

        _common._ensure_picklable_by_value(train_fn)
        self._backend.on_training_start(self.worker_group,
                                        self._backend_config)
        ctxs = self._contexts(experiment_name, trial_name, trial_dir)
        shards = dataset_shards_per_worker or [None] * len(ctxs)
        refs = [
            w.actor.start_session.remote(ctxs[i], train_fn, config,
                                         checkpoint, trial_dir, shards[i],
                                         start_iteration)
            for i, w in enumerate(self.worker_group.workers)
        ]
        self._get_with_failure_handling(refs)

    def get_next_results(self) -> Optional[List[tuple]]:
        """One lockstep round of next_result() from every worker.

        Returns None when all workers finished; raises TrainingFailedError
        on a user exception; TrainingWorkerError on actor death.
        """
        refs = [w.actor.next_result.remote()
                for w in self.worker_group.workers]
        results = self._get_with_failure_handling(refs)
        kinds = {r[0] for r in results}
        if kinds == {"finished"}:
            return None
        if "finished" in kinds:
            # some workers returned while others still report: the loop is
            # mis-specified (unequal iteration counts); fail loudly.
            raise TrainingFailedError(
                "training workers returned out of sync: some finished while "
                "others are still reporting; ensure every worker runs the "
                "same number of report() calls")
        return results

    def _get_with_failure_handling(self, refs):
        try:
            return ray_tpu.get(refs)
        except (ActorDiedError, WorkerCrashedError) as e:
            raise TrainingWorkerError(str(e)) from e
        except (TrainingWorkerError, TrainingFailedError):
            raise
        except ray_tpu.TaskError as e:
            raise TrainingFailedError(str(e)) from e

    def finish_training(self):
        if self.worker_group is None:
            return
        try:
            ray_tpu.get([w.actor.end_session.remote()
                         for w in self.worker_group.workers])
        except Exception:
            pass

    def shutdown(self):
        if self.worker_group is not None:
            try:
                self._backend.on_shutdown(self.worker_group,
                                          self._backend_config)
            except Exception:
                pass
            self.worker_group.shutdown()
            self.worker_group = None

    def restart(self):
        """Tear down and respawn the whole group (reference:
        backend_executor.py:759 _restart)."""
        self.shutdown()
        self.start()
