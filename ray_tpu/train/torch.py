"""TorchTrainer + torch train-loop utilities.

Reference parity: python/ray/train/torch/ — ``TorchTrainer``
(torch_trainer.py) is a DataParallelTrainer whose backend sets up
torch.distributed; ``prepare_model`` / ``prepare_data_loader``
(train_loop_utils.py) wrap the user's model in DDP and the loader in a
DistributedSampler.  CPU/gloo here (no CUDA on TPU hosts); the jax path
(JaxTrainer) is the accelerated one.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, Optional

from .backend import TorchConfig
from .trainer import JaxTrainer

__all__ = ["TorchTrainer", "TorchConfig", "prepare_model",
           "prepare_data_loader", "get_device"]


class TorchTrainer(JaxTrainer):
    """Data-parallel torch training on the cluster (reference:
    train/torch/torch_trainer.py).

    Same orchestration as JaxTrainer (BackendExecutor -> WorkerGroup ->
    per-worker train loop with session.report), with the torch process
    group as the backend::

        def train_loop(config):
            model = prepare_model(Net())
            for epoch in range(3):
                ...
                session.report({"loss": loss.item()})

        TorchTrainer(train_loop,
                     scaling_config=ScalingConfig(num_workers=2)).fit()
    """

    def __init__(self, train_loop_per_worker: Callable, *,
                 train_loop_config: Optional[Dict[str, Any]] = None,
                 torch_config: Optional[TorchConfig] = None,
                 **kwargs):
        kwargs.setdefault("backend_config", torch_config or TorchConfig())
        super().__init__(train_loop_per_worker,
                         train_loop_config=train_loop_config, **kwargs)


def get_device():
    import torch

    return torch.device("cpu")


def prepare_model(model, *, ddp_kwargs: Optional[Dict[str, Any]] = None):
    """Wrap in DistributedDataParallel when a process group is up
    (reference: train_loop_utils.py prepare_model)."""
    import torch.distributed as dist

    if dist.is_available() and dist.is_initialized() \
            and dist.get_world_size() > 1:
        from torch.nn.parallel import DistributedDataParallel

        return DistributedDataParallel(model, **(ddp_kwargs or {}))
    return model


def prepare_data_loader(data_loader):
    """Re-build the loader with a DistributedSampler so each rank sees
    its shard (reference: train_loop_utils.py prepare_data_loader)."""
    import torch.distributed as dist
    from torch.utils.data import DataLoader
    from torch.utils.data.distributed import DistributedSampler

    if not (dist.is_available() and dist.is_initialized()
            and dist.get_world_size() > 1):
        return data_loader
    sampler = DistributedSampler(data_loader.dataset,
                                 num_replicas=dist.get_world_size(),
                                 rank=dist.get_rank())
    return DataLoader(data_loader.dataset,
                      batch_size=data_loader.batch_size,
                      sampler=sampler,
                      num_workers=0,
                      collate_fn=data_loader.collate_fn,
                      drop_last=data_loader.drop_last)


def get_world_rank() -> int:
    return int(os.environ.get("RAY_TPU_TRAIN_WORLD_RANK", "0"))


def get_world_size() -> int:
    return int(os.environ.get("RAY_TPU_TRAIN_WORLD_SIZE", "1"))
