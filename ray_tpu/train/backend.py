"""Training backends: per-framework worker-group setup.

Mirrors the reference's Backend/BackendConfig split (reference:
python/ray/train/backend.py; torch impl train/torch/config.py — sets
MASTER_ADDR/PORT then torch.distributed.init_process_group on every worker;
XLA variant train/torch/xla/config.py:20).

The TPU-native backend is `JaxConfig`: instead of a process-group library
call, workers are wired into ONE jax runtime:

  * multi-host SPMD mode ("spmd"): rank 0's node hosts the jax
    coordination service; every worker calls
    jax.distributed.initialize(coordinator, num_processes, process_id),
    after which `jax.devices()` spans all hosts' chips and pjit/shard_map
    programs compile ICI/DCN collectives across the whole slice.
  * local mode ("local", the CI/CPU path): each worker keeps its own local
    jax runtime; cross-worker reductions go through the control-plane KV
    collective group (ray_tpu.collective) — the Gloo-equivalent plane.
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass
from typing import TYPE_CHECKING, Any, Dict, Optional, Union

from ray_tpu.collective.compression import CompressionConfig, parse_compression

if TYPE_CHECKING:
    from ray_tpu.elastic.config import ElasticConfig
    from ray_tpu.parallel.mpmd import PipelineConfig
    from ray_tpu.telemetry.config import TelemetryConfig

logger = logging.getLogger(__name__)


def publish_run_state(trial_name: str, status: str, *, name: str,
                      workers: int, rounds: int,
                      metrics: Optional[Dict[str, Any]] = None,
                      telemetry: Optional[Dict[str, Any]] = None):
    """Run-state snapshot into the control KV (ns 'train') for the
    dashboard and the autoscaler's LoadMetrics (reference:
    TrainStateActor feeding dashboard/modules/train/train_head.py).
    Advisory, never raises: a run must not fail because the dashboard
    missed a frame.  Shared by JaxTrainer and non-Trainer run loops
    (the Podracer Sebulba supervisor) so every training-shaped workload
    speaks one state schema — including the telemetry.goodput field the
    autoscaler's GoodputPolicy scales on."""
    try:
        import json as _json
        import time as _time

        from ray_tpu._private.api import current_core

        state: Dict[str, Any] = {
            "name": name, "trial": trial_name, "status": status,
            "workers": workers, "rounds": rounds,
            "last_metrics": metrics, "ts": _time.time(),
        }
        if telemetry is not None:
            state["telemetry"] = telemetry
        current_core().control.call("kv_put", {
            "ns": "train", "key": trial_name,
            "val": _json.dumps(state).encode()})
    except Exception:
        pass


@dataclass
class BackendConfig:
    def backend_cls(self):
        return Backend


class Backend:
    """Hooks called by the BackendExecutor around the worker group."""

    share_env_vars = ()

    def on_start(self, worker_group, backend_config: BackendConfig):
        pass

    def on_training_start(self, worker_group, backend_config: BackendConfig):
        pass

    def on_shutdown(self, worker_group, backend_config: BackendConfig):
        pass


@dataclass
class TorchConfig(BackendConfig):
    """Backend config for torch.distributed training (reference:
    train/torch/config.py TorchConfig — sets MASTER_ADDR/PORT, then
    init_process_group on every worker).  gloo is the portable default;
    nccl has no TPU analog here (device collectives belong to the jax
    path)."""

    backend: str = "gloo"
    init_method: str = "tcp"
    timeout_s: float = 120.0

    def backend_cls(self):
        return _TorchBackend


def _setup_torch_group(init_method: str, backend: str, world_size: int,
                       rank: int, timeout_s: float):
    import datetime

    import torch.distributed as dist

    if dist.is_initialized():
        dist.destroy_process_group()
    dist.init_process_group(
        backend=backend, init_method=init_method,
        world_size=world_size, rank=rank,
        timeout=datetime.timedelta(seconds=timeout_s))
    return {"rank": dist.get_rank(), "world_size": dist.get_world_size()}


def _teardown_torch_group():
    import torch.distributed as dist

    if dist.is_initialized():
        dist.destroy_process_group()
    return True


class _TorchBackend(Backend):
    def on_start(self, worker_group, backend_config: "TorchConfig"):
        import ray_tpu
        from ray_tpu._private.protocol import free_port

        n = worker_group.num_workers
        head_ip = worker_group.workers[0].metadata.get("node_ip",
                                                       "127.0.0.1")
        # probe the port on rank 0's host — the torch master binds there,
        # not on the driver (reference picks the port on the worker too)
        port = ray_tpu.get(
            worker_group.workers[0].actor.execute.remote(free_port),
            timeout=60)
        init_method = f"tcp://{head_ip}:{port}"
        env = {"MASTER_ADDR": head_ip, "MASTER_PORT": str(port),
               "RAY_TPU_TRAIN_WORLD_SIZE": str(n)}
        ray_tpu.get([
            w.actor.set_env_vars.remote({**env,
                                         "RAY_TPU_TRAIN_WORLD_RANK": str(i)})
            for i, w in enumerate(worker_group.workers)])
        if n > 1 or backend_config.init_method == "always":
            refs = [w.actor.execute.remote(
                        _setup_torch_group, init_method,
                        backend_config.backend, n, i,
                        backend_config.timeout_s)
                    for i, w in enumerate(worker_group.workers)]
            infos = ray_tpu.get(refs)
            logger.info("torch.distributed initialized: %s", infos[0])

    def on_shutdown(self, worker_group, backend_config: "TorchConfig"):
        import ray_tpu

        try:
            ray_tpu.get([w.actor.execute.remote(_teardown_torch_group)
                         for w in worker_group.workers], timeout=30)
        except Exception:
            pass


@dataclass
class TensorflowConfig(BackendConfig):
    """Backend config for TF MultiWorkerMirroredStrategy training
    (reference: train/tensorflow/config.py — builds TF_CONFIG with the
    worker gang's host:port list and each rank's task index)."""

    port_base: int = 0  # 0 = probe free ports on the workers

    def backend_cls(self):
        return _TensorflowBackend


def _setup_tf_config(workers: list, index: int):
    import json
    import os

    os.environ["TF_CONFIG"] = json.dumps({
        "cluster": {"worker": workers},
        "task": {"type": "worker", "index": index},
    })
    return workers[index]


class _TensorflowBackend(Backend):
    def on_start(self, worker_group, backend_config: "TensorflowConfig"):
        import ray_tpu
        from ray_tpu._private.protocol import free_port

        n = worker_group.num_workers
        if backend_config.port_base:
            # deterministic ports for firewalled clusters
            ports = [backend_config.port_base + i for i in range(n)]
        else:
            ports = ray_tpu.get(
                [w.actor.execute.remote(free_port)
                 for w in worker_group.workers], timeout=60)
        hosts = [w.metadata.get("node_ip", "127.0.0.1")
                 for w in worker_group.workers]
        gang = [f"{h}:{p}" for h, p in zip(hosts, ports)]
        env = {"RAY_TPU_TRAIN_WORLD_SIZE": str(n)}
        ray_tpu.get([
            w.actor.set_env_vars.remote({**env,
                                         "RAY_TPU_TRAIN_WORLD_RANK": str(i)})
            for i, w in enumerate(worker_group.workers)])
        ray_tpu.get([w.actor.execute.remote(_setup_tf_config, gang, i)
                     for i, w in enumerate(worker_group.workers)])
        logger.info("TF_CONFIG distributed gang: %s", gang)


@dataclass
class JaxConfig(BackendConfig):
    """Backend config for JAX/TPU training.

    mode: "auto" picks "spmd" when workers hold TPU chips, else "local".
    coordinator_port: jax coordination service port (spmd mode).
    """

    mode: str = "auto"
    coordinator_port: int = 8476
    collective_group: str = "train"
    # e.g. {"dp": -1}: after jax init every worker builds this mesh over
    # its visible devices and installs it as the process default
    # (parallel.set_default_mesh) — iter_jax_batches then auto-shards
    # batches and inbound jax.Arrays restore their shardings with no
    # per-callsite plumbing
    mesh_shape: Optional[Dict[str, int]] = None
    # gradient-sync compression for the gang: a CompressionConfig or spec
    # string ("int8", "int8:block=512,ef=1",
    # "int8:chunks=4,bucket=8388608" for the pipelined-chunk and
    # gradient-bucket knobs).  Installed as every worker's group
    # default, so collective.allreduce / GradientSynchronizer compress
    # (and bucket/pipeline) without per-call plumbing; None defers to
    # the RAY_TPU_COLLECTIVE_COMPRESSION flag
    compression: Union[None, str, CompressionConfig] = None
    # opt into preemption-aware elastic training: peer-replicated
    # emergency checkpoints + shrink-to-fit restarts (see
    # ray_tpu.elastic.ElasticConfig / COMPONENTS.md)
    elastic: Optional["ElasticConfig"] = None
    # training flight recorder (ray_tpu.telemetry): None/True = on with
    # defaults; TelemetryConfig(...) to tune ring size / straggler
    # thresholds; False to disable step timing + goodput accounting
    telemetry: Union[None, bool, Dict[str, Any],
                     "TelemetryConfig"] = None
    # MPMD pipeline parallelism across worker gangs: a
    # parallel.mpmd.PipelineConfig (stages/schedule/microbatches) or a
    # spec string ("stages=4,schedule=1f1b,microbatches=8").  Published
    # to every worker as RAY_TPU_TRAIN_PIPELINE so the train fn can
    # build its stage via PipelineConfig.from_env(); string annotation +
    # lazy parse keep control-plane processes jax-free
    pipeline: Union[None, str, "PipelineConfig"] = None

    def backend_cls(self):
        return _JaxBackend


def _setup_jax_spmd(coordinator: str, num_processes: int, process_id: int):
    import jax

    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)
    return {"process_index": jax.process_index(),
            "device_count": jax.device_count(),
            "local_device_count": jax.local_device_count()}


def _install_default_mesh(shape: Dict[str, int]):
    from ray_tpu.parallel import make_mesh, set_default_mesh

    mesh = make_mesh(**shape)
    set_default_mesh(mesh)
    return {"mesh": {a: int(s) for a, s in mesh.shape.items()}}


def _setup_jax_local(group_name: str, world_size: int, rank: int,
                     compression: str = ""):
    from ray_tpu import collective
    from ray_tpu.collective.compression import set_group_compression

    collective.init_collective_group(world_size, rank, backend="kv",
                                     group_name=group_name)
    if compression:
        set_group_compression(compression)
    return {"process_index": rank, "device_count": None,
            "local_device_count": None}


class _JaxBackend(Backend):
    def on_start(self, worker_group, backend_config: JaxConfig):
        n = worker_group.num_workers
        mode = backend_config.mode
        if mode == "auto":
            all_tpu = all(w.metadata.get("has_tpu")
                          for w in worker_group.workers)
            mode = "spmd" if (all_tpu and n > 1) else "local"
        self.mode = mode

        # publish the gang layout to every worker's env (the analog of
        # _share_cuda_visible_devices, reference: backend_executor.py:271)
        env = {"RAY_TPU_TRAIN_WORLD_SIZE": str(n)}
        cc = parse_compression(backend_config.compression)
        comp_spec = cc.to_spec() if cc is not None else ""
        if comp_spec:
            # the flag form reaches subprocesses a worker may itself
            # spawn; the group default below covers the workers directly
            env["RAY_TPU_COLLECTIVE_COMPRESSION"] = comp_spec
        if backend_config.pipeline is not None:
            pcfg = backend_config.pipeline
            if isinstance(pcfg, str):
                # validate the spec here, on the driver, where the error
                # is actionable — not inside N workers
                from ray_tpu.parallel.mpmd import PipelineConfig
                pcfg = PipelineConfig.from_spec(pcfg)
            env["RAY_TPU_TRAIN_PIPELINE"] = pcfg.to_spec()
        import ray_tpu

        ray_tpu.get([
            w.actor.set_env_vars.remote({**env,
                                         "RAY_TPU_TRAIN_WORLD_RANK": str(i)})
            for i, w in enumerate(worker_group.workers)])

        if mode == "spmd" and n > 1:
            head_ip = worker_group.workers[0].metadata.get("node_ip",
                                                           "127.0.0.1")
            coordinator = f"{head_ip}:{backend_config.coordinator_port}"
            refs = [w.actor.execute.remote(_setup_jax_spmd, coordinator, n, i)
                    for i, w in enumerate(worker_group.workers)]
            infos = ray_tpu.get(refs)
            logger.info("jax.distributed initialized: %s", infos[0])
        elif n > 1:
            # incarnation in the name: an elastically rebuilt gang must
            # never rendezvous with stale members of the old group
            inc = getattr(worker_group, "incarnation", 0)
            group = (f"{backend_config.collective_group}"
                     f"-{id(worker_group)}-i{inc}")
            self._group = group
            ray_tpu.get([
                w.actor.set_env_vars.remote(
                    {"RAY_TPU_TRAIN_COLLECTIVE_GROUP": group})
                for w in worker_group.workers])
            refs = [w.actor.execute.remote(_setup_jax_local, group, n, i,
                                           comp_spec)
                    for i, w in enumerate(worker_group.workers)]
            ray_tpu.get(refs)
        if backend_config.elastic is not None:
            self._init_emergency_checkpointers(worker_group,
                                               backend_config.elastic)
        if backend_config.mesh_shape:
            # after jax init so spmd workers see the global device set
            meshes = ray_tpu.get([
                w.actor.execute.remote(_install_default_mesh,
                                       dict(backend_config.mesh_shape))
                for w in worker_group.workers])
            logger.info("default mesh installed on %d workers: %s",
                        n, meshes[0])

    def _init_emergency_checkpointers(self, worker_group, ec):
        """Arm per-worker EmergencyCheckpointers.  Tag carries the gang
        incarnation: snapshots from before a shrink stay readable in the
        vault (recovery source) but new writes land under the new tag."""
        import ray_tpu
        from ray_tpu.elastic.emergency import _init_worker_checkpointer

        n = worker_group.num_workers
        inc = getattr(worker_group, "incarnation", 0)
        tag = f"em-{id(worker_group)}-i{inc}"
        ray_tpu.get([
            w.actor.execute.remote(
                _init_worker_checkpointer, tag, i, n,
                ec.replication_factor, ec.keep_steps, ec.snapshot_every,
                ec.replicate_timeout_s)
            for i, w in enumerate(worker_group.workers)])
        logger.info("emergency checkpointers armed: tag=%s world=%d k=%d",
                    tag, n, ec.replication_factor)

    def on_shutdown(self, worker_group, backend_config: JaxConfig):
        if getattr(self, "mode", None) == "local" and worker_group.workers:
            import ray_tpu
            from ray_tpu import collective

            group = getattr(self, "_group", None)
            if group:
                try:
                    ray_tpu.get([
                        w.actor.execute.remote(
                            collective.destroy_collective_group, group)
                        for w in worker_group.workers])
                except Exception:
                    pass


# Alias matching the reference's naming convention for TPU users.
TPUConfig = JaxConfig
