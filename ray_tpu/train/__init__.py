"""ray_tpu.train: distributed training orchestration for JAX on TPU.

Mirrors the reference's Ray Train surface (reference: python/ray/train/):
JaxTrainer + JaxConfig replace TorchTrainer + TorchConfig; report/
get_context/get_checkpoint/get_dataset_shard match the reference's
module-level session API (train/_internal/session.py:667-790).
"""

from .backend import (Backend, BackendConfig, JaxConfig, TensorflowConfig,
                      TorchConfig, TPUConfig, publish_run_state)
from .backend_executor import (BackendExecutor, TrainingFailedError,
                               TrainingWorkerError, WorkerDrainedError)
from .checkpoint import Checkpoint
from .checkpoint_manager import CheckpointManager
from .config import (CheckpointConfig, CompressionConfig, FailureConfig,
                     RunConfig, ScalingConfig)
from .result import Result
from .session import (TrainContext, get_checkpoint, get_context,
                      get_dataset_shard, report)
from .gbdt import (GBDTTrainer, LightGBMTrainer, SklearnGBDTTrainer,
                   XGBoostTrainer)
from .trainer import DataParallelTrainer, JaxTrainer
from .worker_group import WorkerGroup
from ray_tpu.elastic.config import ElasticConfig

__all__ = [
    "Backend", "BackendConfig", "BackendExecutor", "Checkpoint",
    "CheckpointConfig", "CheckpointManager", "CompressionConfig",
    "DataParallelTrainer", "ElasticConfig",
    "FailureConfig", "GBDTTrainer", "JaxConfig", "JaxTrainer",
    "LightGBMTrainer", "Result", "RunConfig",
    "ScalingConfig", "SklearnGBDTTrainer", "TensorflowConfig",
    "TorchConfig", "TPUConfig", "XGBoostTrainer",
    "TrainContext",
    "TrainingFailedError",
    "TrainingWorkerError", "WorkerDrainedError", "WorkerGroup",
    "get_checkpoint", "get_context",
    "get_dataset_shard", "publish_run_state", "report",
]
