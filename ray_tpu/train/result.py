"""Result: what Trainer.fit / Tuner.fit hand back per trial.

Mirrors the reference (reference: python/ray/train/_internal/result.py /
air Result): final metrics, latest + best checkpoints, run path, error.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from .checkpoint import Checkpoint


@dataclass
class Result:
    metrics: Optional[Dict[str, Any]]
    checkpoint: Optional[Checkpoint]
    path: str
    error: Optional[BaseException] = None
    metrics_dataframe: Optional[Any] = None
    best_checkpoints: List[Tuple[Checkpoint, Dict[str, Any]]] = field(
        default_factory=list)

    @property
    def config(self) -> Optional[Dict[str, Any]]:
        return (self.metrics or {}).get("config")
