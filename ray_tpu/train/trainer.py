"""JaxTrainer: the data-parallel trainer for JAX/TPU training loops.

Mirrors the reference's DataParallelTrainer (reference:
python/ray/train/data_parallel_trainer.py; fit flow
train/base_trainer.py:567): spawn a worker gang, run
`train_loop_per_worker` on every worker, stream reported results back,
persist + rank checkpoints, and restart the group from the latest
checkpoint on worker failure (FailureConfig.max_failures).

TPU-native differences from the torch trainer it mirrors:
  * the backend wires workers into one jax runtime (see backend.JaxConfig)
    instead of a torch.distributed process group;
  * data parallelism inside the loop is a sharded mesh axis (pjit `dp`),
    so gradient sync is compiled into the step as an ICI psum rather than
    an allreduce library call on the hot path.

When used under Tune, `JaxTrainer.as_trainable()` adapts the same run loop
to a Tune trainable (the reference runs Train on top of Tune the same way,
base_trainer.py:567-623).
"""

from __future__ import annotations

import logging
import os
import re
import time
from typing import Any, Callable, Dict, List, Optional

from .backend import BackendConfig, JaxConfig
from .backend_executor import (BackendExecutor, TrainingFailedError,
                               TrainingWorkerError, WorkerDrainedError,
                               WorkerQuarantinedError)
from .checkpoint import Checkpoint
from .checkpoint_manager import CheckpointManager
from .config import RunConfig, ScalingConfig
from .result import Result

logger = logging.getLogger(__name__)


def _find_latest_checkpoint(trial_dir: str,
                            world_size: int = 1) -> Optional[Checkpoint]:
    """Scan <trial_dir>/checkpoint_* for the newest complete checkpoint.

    Complete = a `.complete_rank_k` marker exists for EVERY rank (markers
    are written after each rank's copy/upload lands): a checkpoint where
    one worker died mid-report has a subset of ranks and restoring from
    it would hand the missing ranks someone else's shard — or nothing.
    Works on local dirs and remote URIs alike (train.storage)."""
    from . import storage

    need = {f".complete_rank_{k}" for k in range(world_size)}
    cands = []
    for name in storage.listdir(trial_dir):
        if not re.fullmatch(r"checkpoint_\d+", name):
            continue
        cdir = storage.join(trial_dir, name)
        if need <= set(storage.listdir(cdir)):
            cands.append((name, cdir))
    if not cands:
        return None
    return Checkpoint(max(cands)[1])


class JaxTrainer:
    def __init__(self, train_loop_per_worker: Callable,
                 *, train_loop_config: Optional[Dict[str, Any]] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 backend_config: Optional[BackendConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None):
        self._train_fn = train_loop_per_worker
        self._config = dict(train_loop_config or {})
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.backend_config = backend_config or JaxConfig()
        self._datasets = datasets or {}
        self._resume_checkpoint = resume_from_checkpoint

    # -- dataset sharding --------------------------------------------------

    def _shard_datasets(self, n: int) -> Optional[List[Dict[str, Any]]]:
        """Per-worker dataset shards.  ray_tpu.data Datasets shard via
        streaming_split (one coordinator actor streams blocks; workers get
        serializable DataIterators — reference: get_dataset_shard returns
        a DataIterator backed by streaming_split(equal=True)); anything
        else is replicated."""
        if not self._datasets:
            return None
        per_worker: List[Dict[str, Any]] = [dict() for _ in range(n)]
        for name, ds in self._datasets.items():
            shards = None
            streaming_split = getattr(ds, "streaming_split", None)
            split = getattr(ds, "split", None)  # ray_tpu.data Dataset
            if callable(streaming_split):
                shards = streaming_split(n, equal=True)
            elif callable(split):
                try:
                    shards = split(n, equal=True)
                except TypeError:
                    shards = split(n)
            if shards is None or len(shards) != n:
                shards = [ds] * n
            for i in range(n):
                per_worker[i][name] = shards[i]
        return per_worker

    # -- the run loop (shared by fit() and the Tune trainable) -------------

    def _publish_state(self, trial_name: str, status: str,
                       metrics: Optional[Dict[str, Any]], rounds: int,
                       telemetry: Optional[Dict[str, Any]] = None):
        """Run-state snapshot into the control KV (ns 'train') for the
        dashboard (reference: TrainStateActor feeding
        dashboard/modules/train/train_head.py) — advisory, never fails
        the run."""
        from ray_tpu.train.backend import publish_run_state

        publish_run_state(trial_name, status,
                          name=self.run_config.name,
                          workers=self.scaling_config.num_workers,
                          rounds=rounds, metrics=metrics,
                          telemetry=telemetry)

    def _run(self, trial_dir: str, experiment_name: str, trial_name: str,
             on_report: Optional[Callable[[Dict[str, Any]], None]] = None,
             ) -> Result:
        ckpt_mgr = CheckpointManager(self.run_config.checkpoint_config)
        max_failures = self.run_config.failure_config.max_failures
        failures = 0
        restore = self._resume_checkpoint
        executor = BackendExecutor(self.backend_config, self.scaling_config)
        # flight recorder: goodput state machine + cross-worker straggler
        # detection, armed before start() so early drain notices stamp
        goodput = aggregator = remediation = None
        try:
            from ray_tpu.telemetry import (GoodputAccountant, StepAggregator,
                                           resolve_telemetry,
                                           set_current_accountant)

            _tc = resolve_telemetry(
                getattr(self.backend_config, "telemetry", None))
            if _tc.enabled:
                goodput = GoodputAccountant()
                aggregator = StepAggregator(_tc, trial=trial_name)
                executor.goodput = goodput
                set_current_accountant(goodput)
                # close the detect->act loop: the engine watches the
                # aggregator's straggler episodes and (in enforce mode)
                # quarantines + rebalances; advisory mode records only
                _ec = getattr(self.backend_config, "elastic", None)
                if _ec is not None and \
                        getattr(_ec, "remediation_mode", "off") != "off":
                    from ray_tpu.elastic.remediation import RemediationEngine

                    remediation = RemediationEngine(_ec, trial=trial_name)
        except Exception:
            pass

        def _telemetry_state():
            if goodput is None and aggregator is None:
                return None
            out: Dict[str, Any] = {}
            if goodput is not None:
                out["goodput"] = goodput.report()
            if aggregator is not None:
                out["stragglers"] = aggregator.summary()
            if remediation is not None:
                out["remediations"] = remediation.summary()
            return out

        executor.start()
        last_metrics: Optional[Dict[str, Any]] = None
        error: Optional[BaseException] = None
        n = self.scaling_config.num_workers
        rounds = 0  # report rounds consumed, survives restarts
        elastic = getattr(self.backend_config, "elastic", None)
        # safety net: every genuine loss shrinks the width toward
        # min_workers, so recoveries are naturally bounded — this cap only
        # guards against a pathological notice/restart loop
        elastic_recoveries = 0
        max_elastic_recoveries = 2 * n + 2
        per_worker_cks: Optional[List[Optional[Checkpoint]]] = None
        self._publish_state(trial_name, "RUNNING", None, 0)
        try:
            while True:
                try:
                    executor.start_training(
                        self._train_fn, self._config, experiment_name,
                        trial_name, trial_dir, checkpoint=restore,
                        dataset_shards_per_worker=self._shard_datasets(n),
                        start_iteration=rounds,
                        per_worker_checkpoints=per_worker_cks)
                    per_worker_cks = None
                    if goodput is not None:
                        goodput.transition(
                            "productive",
                            incarnation=getattr(executor.worker_group,
                                                "incarnation", 0))
                    while True:
                        results = executor.get_next_results()
                        if results is None:
                            break
                        rounds += 1
                        if aggregator is not None:
                            aggregator.ingest_round([
                                m.get("telemetry")
                                if isinstance(m, dict) else None
                                for _, m, _ in results])
                            if remediation is not None:
                                try:
                                    from ray_tpu.telemetry import (
                                        device as _devtel)

                                    for adv in (_devtel.get_ledger()
                                                .drain_advisories()):
                                        remediation.observe_advisory(adv)
                                except Exception:
                                    pass
                                decision = remediation.observe_round(
                                    aggregator)
                                if decision is not None:
                                    nid = executor.quarantine_worker(
                                        decision["rank"],
                                        reason=decision["reason"],
                                        grace_s=decision["grace_s"])
                                    remediation.note_enforced(decision, nid)
                                    raise WorkerQuarantinedError(
                                        f"rank {decision['rank']} (node "
                                        f"{str(nid)[:12]}) quarantined: "
                                        f"{decision['reason']}")
                        # rank-0 metrics are authoritative (reference keeps
                        # per-rank results; rank 0 drives callbacks)
                        _, metrics, ckpt_path = results[0]
                        ckpt_paths = {p for _, _, p in results if p}
                        last_metrics = metrics
                        if ckpt_paths:
                            assert len(ckpt_paths) == 1, (
                                f"workers reported different checkpoint dirs: "
                                f"{ckpt_paths}")
                            ckpt = Checkpoint(next(iter(ckpt_paths)))
                            ckpt_mgr.register_checkpoint(ckpt, metrics or {})
                        if on_report is not None and metrics is not None:
                            on_report(metrics)
                        self._publish_state(trial_name, "RUNNING",
                                            metrics, rounds,
                                            telemetry=_telemetry_state())
                    executor.finish_training()
                    break
                except TrainingWorkerError as e:
                    if goodput is not None:
                        goodput.transition(
                            "draining" if isinstance(e, WorkerDrainedError)
                            else "recovering")
                    if (elastic is not None
                            and elastic_recoveries < max_elastic_recoveries):
                        try:
                            cks, step, new_n = executor.elastic_recover()
                        except Exception as rec_err:
                            logger.warning(
                                "elastic recovery unavailable (%s); falling "
                                "back to storage-checkpoint restart",
                                rec_err)
                        else:
                            # in-memory recovery: does NOT count against
                            # max_failures (bounded by width shrinking to
                            # min_workers + the recoveries cap above)
                            elastic_recoveries += 1
                            per_worker_cks = cks
                            n = new_n
                            ckpt_mgr.note_emergency(step)
                            if remediation is not None:
                                remediation.note_recovered(new_n, step)
                            logger.warning(
                                "elastic recovery %d: resuming %d-wide from "
                                "replicated snapshot step=%d (trigger: %s)",
                                elastic_recoveries, new_n, step, e)
                            self._publish_state(trial_name, "RESTARTING",
                                                last_metrics, rounds,
                                                telemetry=_telemetry_state())
                            continue
                    failures += 1
                    if max_failures != -1 and failures > max(max_failures, 0):
                        error = e
                        break
                    logger.warning(
                        "training worker died (%s); restarting group "
                        "(failure %d/%s) from latest checkpoint", e,
                        failures, max_failures if max_failures != -1 else "inf")
                    # the dir scan is marker-validated (an upload that
                    # died with its worker left no marker), so it is the
                    # safe restore source; the manager's latest may point
                    # at an in-flight upload
                    restore = (_find_latest_checkpoint(trial_dir, n)
                               or self._resume_checkpoint)
                    executor.restart()
                    # a full restart rebuilds at the configured width even
                    # after elastic shrinks
                    n = executor.worker_group.num_workers
                    per_worker_cks = None
                except TrainingFailedError as e:
                    error = e
                    break
        finally:
            executor.shutdown()
            if goodput is not None:
                try:
                    goodput.transition("idle")
                    from ray_tpu.telemetry import set_current_accountant

                    set_current_accountant(None)
                except Exception:
                    pass
            self._publish_state(trial_name,
                                "ERRORED" if error else "FINISHED",
                                last_metrics, rounds,
                                telemetry=_telemetry_state())
        return Result(metrics=last_metrics,
                      checkpoint=ckpt_mgr.latest_checkpoint,
                      path=trial_dir, error=error,
                      best_checkpoints=ckpt_mgr.best_checkpoints())

    def fit(self) -> Result:
        from ray_tpu._private.usage_stats import record_library_usage

        record_library_usage("train")
        from . import storage

        name = self.run_config.name or f"JaxTrainer_{int(time.time())}"
        exp_dir = storage.join(self.run_config.resolved_storage_path(), name)
        trial_name = f"{name}_00000"
        trial_dir = storage.join(exp_dir, trial_name)
        storage.makedirs(trial_dir)
        callbacks = list(self.run_config.callbacks or ())
        on_report = None
        trial_shim = None
        if callbacks:
            # standalone fit() fires RunConfig.callbacks too (reference:
            # trainers always run through Tune's callback plumbing); the
            # shim carries the trial fields callbacks read
            trial_shim = type("TrialShim", (), {})()
            trial_shim.trial_id = trial_name
            trial_shim.trial_dir = trial_dir
            trial_shim.config = dict(self._config)

            def on_report(metrics, _t=trial_shim):
                for cb in callbacks:
                    cb.on_trial_result(_t, metrics)

        result = self._run(trial_dir, name, trial_name, on_report=on_report)
        for cb in callbacks:
            if result.error is not None:
                cb.on_trial_error(trial_shim)
            else:
                cb.on_trial_complete(trial_shim)
        if result.error is not None:
            raise TrainingFailedError(
                f"training failed: {result.error}") from result.error
        return result

    # -- Tune integration --------------------------------------------------

    def as_trainable(self):
        """Adapt this trainer into a Tune function-trainable.  Tune merges
        each trial's hyperparameter `config` into train_loop_config."""
        trainer = self

        def _trainable(config, tune_session):
            import copy

            t = copy.copy(trainer)
            t._config = {**trainer._config, **config}
            t._resume_checkpoint = (tune_session.get_checkpoint()
                                    or trainer._resume_checkpoint)
            result = t._run(tune_session.trial_dir,
                            tune_session.experiment_name,
                            tune_session.trial_name,
                            on_report=tune_session.report)
            if result.error is not None:
                raise result.error
            return result.metrics

        _trainable.__name__ = "JaxTrainerTrainable"
        _trainable._is_trainer_adapter = True
        _trainable._scaling_config = self.scaling_config
        return _trainable


# Torch users of the reference map to this 1:1.
DataParallelTrainer = JaxTrainer
