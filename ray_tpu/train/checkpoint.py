"""Checkpoint: a directory handle on persistent storage.

Mirrors the reference (reference: python/ray/train/_checkpoint.py:56
Checkpoint — "a reference to data persisted as a directory"): create from a
local directory, materialize to a local directory, read/write metadata.
Model state inside the directory is the user's format — for JAX models the
idiomatic content is an orbax/flax serialized pytree (msgpack) written by
the training loop.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
from contextlib import contextmanager
from typing import Any, Dict, Optional

_METADATA_FILE = ".metadata.json"


class Checkpoint:
    def __init__(self, path: str):
        self.path = os.path.abspath(os.path.expanduser(path))

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        if not os.path.isdir(path):
            raise ValueError(f"not a directory: {path}")
        return cls(path)

    def to_directory(self, path: Optional[str] = None) -> str:
        """Copy checkpoint contents into `path` (default: temp dir)."""
        dest = path or tempfile.mkdtemp(prefix="ckpt-")
        os.makedirs(dest, exist_ok=True)
        if os.path.abspath(dest) != self.path:
            shutil.copytree(self.path, dest, dirs_exist_ok=True)
        return dest

    @contextmanager
    def as_directory(self):
        """Yield a local directory view without copying when already local."""
        yield self.path

    def get_metadata(self) -> Dict[str, Any]:
        p = os.path.join(self.path, _METADATA_FILE)
        if not os.path.exists(p):
            return {}
        with open(p) as f:
            return json.load(f)

    def set_metadata(self, metadata: Dict[str, Any]) -> None:
        with open(os.path.join(self.path, _METADATA_FILE), "w") as f:
            json.dump(metadata, f)

    def update_metadata(self, metadata: Dict[str, Any]) -> None:
        m = self.get_metadata()
        m.update(metadata)
        self.set_metadata(m)

    def __reduce__(self):
        return (Checkpoint, (self.path,))

    def __repr__(self):
        return f"Checkpoint(path={self.path})"

    def __eq__(self, other):
        return isinstance(other, Checkpoint) and other.path == self.path

    def __hash__(self):
        return hash(self.path)
