"""Checkpoint: a directory handle on persistent storage.

Mirrors the reference (reference: python/ray/train/_checkpoint.py:56
Checkpoint — "a reference to data persisted as a directory"): create from a
local directory, materialize to a local directory, read/write metadata.
Model state inside the directory is the user's format — for JAX models the
idiomatic content is an orbax/flax serialized pytree (msgpack) written by
the training loop.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
from contextlib import contextmanager
from typing import Any, Dict, Optional

_METADATA_FILE = ".metadata.json"


class Checkpoint:
    """`path` may be a local directory or a remote URI (s3://, gs://,
    mock-remote://...); remote checkpoints materialize through
    `to_directory`/`as_directory` via train.storage (reference:
    train/_checkpoint.py Checkpoint carries a pyarrow filesystem the
    same way)."""

    def __init__(self, path: str):
        from . import storage

        if storage.is_uri(path):
            self.path = path
        else:
            self.path = os.path.abspath(os.path.expanduser(path))

    @property
    def is_remote(self) -> bool:
        from . import storage

        return storage.is_uri(self.path)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        if not os.path.isdir(path):
            raise ValueError(f"not a directory: {path}")
        return cls(path)

    def to_directory(self, path: Optional[str] = None,
                     subdir: Optional[str] = None) -> str:
        """Copy checkpoint contents into `path` (default: temp dir);
        remote checkpoints are downloaded.

        `subdir` limits the transfer to one subdirectory (e.g.
        ``rank_3``): on a pod restore every host holds the same logical
        checkpoint URI but needs only its own shard — downloading all N
        rank dirs to all N hosts would be an N^2 transfer."""
        from . import storage

        src = storage.join(self.path, subdir) if subdir else self.path
        dest = path or tempfile.mkdtemp(prefix="ckpt-")
        os.makedirs(dest, exist_ok=True)
        if self.is_remote:
            storage.download_dir(src, dest)
        elif os.path.abspath(dest) != os.path.abspath(src):
            shutil.copytree(src, dest, dirs_exist_ok=True)
        return dest

    @contextmanager
    def as_directory(self, subdir: Optional[str] = None):
        """Yield a local directory view; remote checkpoints download to a
        temp dir that is removed afterwards, local ones yield in place.
        `subdir` narrows the view (and the download) to one
        subdirectory — see to_directory."""
        from . import storage

        if self.is_remote:
            dest = self.to_directory(subdir=subdir)
            try:
                yield dest
            finally:
                shutil.rmtree(dest, ignore_errors=True)
        else:
            yield storage.join(self.path, subdir) if subdir else self.path

    def get_metadata(self) -> Dict[str, Any]:
        from . import storage

        p = storage.join(self.path, _METADATA_FILE)
        if not storage.exists(p):
            return {}
        return json.loads(storage.read_text(p))

    def set_metadata(self, metadata: Dict[str, Any]) -> None:
        from . import storage

        storage.write_text(storage.join(self.path, _METADATA_FILE),
                           json.dumps(metadata))

    def update_metadata(self, metadata: Dict[str, Any]) -> None:
        m = self.get_metadata()
        m.update(metadata)
        self.set_metadata(m)

    def __reduce__(self):
        return (Checkpoint, (self.path,))

    def __repr__(self):
        return f"Checkpoint(path={self.path})"

    def __eq__(self, other):
        return isinstance(other, Checkpoint) and other.path == self.path

    def __hash__(self):
        return hash(self.path)
