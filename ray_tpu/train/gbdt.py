"""Gradient-boosted-tree trainers (XGBoost / LightGBM / sklearn).

Reference: python/ray/train/xgboost/xgboost_trainer.py:74 and
train/lightgbm/ — the "Simple*Trainer" shape: the boosting library runs
INSIDE a training worker actor on materialized dataset shards; the
worker-group / session / checkpoint / Result plumbing is the same
JaxTrainer stack, so RunConfig storage (incl. remote URIs), Tune
integration, and restore all come for free.

The library import happens lazily on the WORKER at fit time: a missing
library raises a clear error there, and the trainer classes themselves
import cleanly everywhere (the environment-gating pattern this repo uses
for optional deps).  `SklearnGBDTTrainer` backs the same machinery with
sklearn's HistGradientBoosting (always present in this image), which
keeps the whole path testable without xgboost/lightgbm installed.
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Any, Callable, Dict, Optional

from .checkpoint import Checkpoint
from .config import RunConfig, ScalingConfig
from .result import Result
from .trainer import JaxTrainer

_MODEL_FILE = "model.bin"
_META_FILE = "gbdt.json"


def _to_xy(ds, label_column: str):
    """Materialize a (features-dataframe, label-array) pair from a
    ray_tpu.data Dataset, a pandas DataFrame, or a dict of arrays."""
    import numpy as np
    import pandas as pd

    if hasattr(ds, "to_pandas"):
        df = ds.to_pandas()
    elif hasattr(ds, "iter_batches"):
        # a DataIterator (get_dataset_shard): drain it into one frame
        frames = [pd.DataFrame(b) for b in ds.iter_batches(
            batch_size=4096, batch_format="pandas")]
        df = pd.concat(frames, ignore_index=True)
    elif isinstance(ds, pd.DataFrame):
        df = ds
    else:
        df = pd.DataFrame(ds)
    y = np.asarray(df[label_column])
    X = df.drop(columns=[label_column])
    return X, y


# -- per-framework train/load hooks ----------------------------------------

def _train_xgboost(X, y, params, num_boost_round, model_path):
    import xgboost as xgb

    dtrain = xgb.DMatrix(X, label=y)
    evals_result: Dict[str, Any] = {}
    booster = xgb.train(params, dtrain, num_boost_round=num_boost_round,
                        evals=[(dtrain, "train")],
                        evals_result=evals_result, verbose_eval=False)
    booster.save_model(model_path)
    metrics = {k: float(v[-1])
               for k, v in evals_result.get("train", {}).items()}
    return metrics


def _train_lightgbm(X, y, params, num_boost_round, model_path):
    import lightgbm as lgb

    dtrain = lgb.Dataset(X, label=y)
    evals_result: Dict[str, Any] = {}
    booster = lgb.train(params, dtrain, num_boost_round=num_boost_round,
                        valid_sets=[dtrain], valid_names=["train"],
                        callbacks=[lgb.record_evaluation(evals_result)])
    booster.save_model(model_path)
    metrics = {k: float(v[-1])
               for k, v in evals_result.get("train", {}).items()}
    return metrics


def _train_sklearn(X, y, params, num_boost_round, model_path):
    import pickle

    import numpy as np
    from sklearn.ensemble import (HistGradientBoostingClassifier,
                                  HistGradientBoostingRegressor)

    params = dict(params)
    objective = params.pop("objective", "regression")
    cls = (HistGradientBoostingClassifier
           if str(objective).startswith(("binary", "multi", "class"))
           else HistGradientBoostingRegressor)
    model = cls(max_iter=num_boost_round, **params)
    model.fit(X, y)
    with open(model_path, "wb") as f:
        pickle.dump(model, f)
    pred = model.predict(X)
    if cls is HistGradientBoostingRegressor:
        return {"rmse": float(np.sqrt(np.mean((pred - y) ** 2)))}
    return {"accuracy": float(np.mean(pred == y))}


_FRAMEWORKS: Dict[str, Callable] = {
    "xgboost": _train_xgboost,
    "lightgbm": _train_lightgbm,
    "sklearn": _train_sklearn,
}


def _gbdt_loop(config):
    """train_loop_per_worker: rank 0 boosts on the materialized data and
    checkpoints the model; other ranks report in lockstep (the reference
    likewise drives the library from inside the worker group)."""
    from ray_tpu import train as train_api

    import shutil

    ctx = train_api.get_context()
    framework = config["framework"]
    if ctx.get_world_rank() != 0:
        # report WITH an (empty) checkpoint dir: the all-ranks
        # completion markers make the checkpoint restorable
        # (_find_latest_checkpoint requires every rank's marker)
        d = tempfile.mkdtemp(prefix="gbdt-empty-")
        try:
            train_api.report({"rank": ctx.get_world_rank()},
                             checkpoint=Checkpoint(d))
        finally:
            shutil.rmtree(d, ignore_errors=True)
        return
    import ray_tpu

    train_fn = _FRAMEWORKS[framework]
    ds = config["dataset"]
    if isinstance(ds, ray_tpu.ObjectRef):
        ds = ray_tpu.get(ds, timeout=600)  # driver-materialized frame
    X, y = _to_xy(ds, config["label_column"])
    ckpt_dir = tempfile.mkdtemp(prefix="gbdt-")
    try:
        try:
            metrics = train_fn(X, y, config["params"],
                               config["num_boost_round"],
                               os.path.join(ckpt_dir, _MODEL_FILE))
        except ImportError as e:
            raise ImportError(
                f"{framework} is not installed in this environment; "
                f"install it or use SklearnGBDTTrainer") from e
        with open(os.path.join(ckpt_dir, _META_FILE), "w") as f:
            json.dump({"framework": framework,
                       "label_column": config["label_column"]}, f)
        # report persists the checkpoint (local copy, or a pre-upload
        # snapshot for remote storage) before returning: the source dir
        # is free to go
        train_api.report({**metrics, "framework": framework},
                         checkpoint=Checkpoint(ckpt_dir))
    finally:
        shutil.rmtree(ckpt_dir, ignore_errors=True)


class GBDTTrainer:
    """Common driver (reference: the shared GBDTTrainer base under
    xgboost/lightgbm trainers)."""

    framework = "sklearn"

    def __init__(self, *, params: Optional[Dict[str, Any]] = None,
                 label_column: str = "label",
                 num_boost_round: int = 10,
                 datasets: Optional[Dict[str, Any]] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None):
        datasets = dict(datasets or {})
        if "train" not in datasets:
            raise ValueError('datasets={"train": ...} is required')
        self._ds = datasets["train"]
        n_workers = (scaling_config or ScalingConfig()).num_workers
        if hasattr(self._ds, "streaming_split") and n_workers > 1:
            raise ValueError(
                "GBDT training consumes the dataset on one worker; use "
                "num_workers=1 with a ray_tpu.data Dataset (in-memory "
                "frames may use more workers — extras idle)")
        self._params = dict(params or {})
        self._label_column = label_column
        self._num_boost_round = num_boost_round
        self._scaling_config = scaling_config or ScalingConfig(
            num_workers=1)
        self._run_config = run_config

    def fit(self) -> Result:
        ds = self._ds
        if hasattr(ds, "streaming_split"):
            # boosting consumes the WHOLE table on one worker anyway (the
            # reference materializes to a DMatrix in memory), so
            # materialize driver-side AT FIT TIME (construction stays
            # lazy/cheap) and ship via the object store: one upload,
            # reused across elastic restarts.  Distributed (rabit-style)
            # boosting is not implemented.
            import ray_tpu

            inline = ray_tpu.put(ds.to_pandas())
        else:
            inline = ds  # plain in-memory data rides the config directly
        trainer = JaxTrainer(
            _gbdt_loop,
            train_loop_config={
                "framework": self.framework,
                "params": self._params,
                "label_column": self._label_column,
                "num_boost_round": self._num_boost_round,
                "dataset": inline,
            },
            datasets=None,
            scaling_config=self._scaling_config,
            run_config=self._run_config,
        )
        return trainer.fit()

    @staticmethod
    def get_model(checkpoint: Checkpoint):
        """Load the boosted model back from a checkpoint (reference:
        XGBoostTrainer.get_model)."""
        with checkpoint.as_directory() as d:
            sub = d
            # multi-rank layout nests rank dirs; rank 0 holds the model
            if not os.path.exists(os.path.join(d, _META_FILE)) and \
                    os.path.isdir(os.path.join(d, "rank_0")):
                sub = os.path.join(d, "rank_0")
            meta = json.load(open(os.path.join(sub, _META_FILE)))
            path = os.path.join(sub, _MODEL_FILE)
            fw = meta["framework"]
            if fw == "xgboost":
                import xgboost as xgb

                booster = xgb.Booster()
                booster.load_model(path)
                return booster
            if fw == "lightgbm":
                import lightgbm as lgb

                return lgb.Booster(model_file=path)
            import pickle

            with open(path, "rb") as f:
                return pickle.load(f)


class XGBoostTrainer(GBDTTrainer):
    """reference: train/xgboost/xgboost_trainer.py:74"""

    framework = "xgboost"


class LightGBMTrainer(GBDTTrainer):
    """reference: train/lightgbm/lightgbm_trainer.py"""

    framework = "lightgbm"


class SklearnGBDTTrainer(GBDTTrainer):
    """sklearn HistGradientBoosting backend: same trainer machinery,
    always runnable in this image."""

    framework = "sklearn"
