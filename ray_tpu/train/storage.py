"""Run storage: local-or-remote filesystem access for checkpoints and
experiment state.

Mirrors the reference's StorageContext (reference:
python/ray/train/_internal/storage.py:358 — pyarrow.fs-backed persistence
to local dirs, s3://, gs://, hdfs://).  Here the abstraction is fsspec:
every path either has a URI scheme (routed through the fsspec filesystem
for that scheme) or is a plain local path (plain os/shutil fast path).

Multi-host TPU pods have NO shared local disk: each host's worker uploads
its own checkpoint shard directly to the remote filesystem, which is the
only way `JaxTrainer` runs on a real pod can persist anything.

A `mock-remote://` scheme is registered for tests: it exercises the full
remote code path (every byte moves through the fsspec AbstractFileSystem
API — upload/download/ls/open, no os.path shortcuts) while persisting in
a plain directory the test can inspect out-of-band.
"""

from __future__ import annotations

import os
import posixpath
import shutil
import threading
from typing import List, Optional, Tuple

__all__ = [
    "StorageContext", "is_uri", "join", "makedirs", "exists", "listdir",
    "upload_dir", "download_dir", "rmtree", "read_text", "write_text",
    "append_text",
]


# scheme dispatch + mock-remote:// live in _private.fileio so that
# ray_tpu.data shares the exact same resolution path (one registration,
# one set of semantics for every byte that leaves the host)
from ray_tpu._private.fileio import fs_for as _fs_and_path  # noqa: E402
from ray_tpu._private.fileio import is_uri  # noqa: F401,E402
from ray_tpu._private.fileio import \
    register_mock_remote as _register_mock_remote  # noqa: F401,E402


def join(base: str, *parts: str) -> str:
    if is_uri(base):
        return posixpath.join(base, *parts)
    return os.path.join(base, *parts)


def makedirs(path: str) -> None:
    if is_uri(path):
        fs, p = _fs_and_path(path)
        fs.makedirs(p, exist_ok=True)
    else:
        os.makedirs(path, exist_ok=True)


def exists(path: str) -> bool:
    if is_uri(path):
        fs, p = _fs_and_path(path)
        return fs.exists(p)
    return os.path.exists(path)


def listdir(path: str) -> List[str]:
    """Base names of entries under `path` ([] when absent)."""
    if is_uri(path):
        fs, p = _fs_and_path(path)
        if not fs.exists(p):
            return []
        return [posixpath.basename(e.rstrip("/"))
                for e in fs.ls(p, detail=False)]
    if not os.path.isdir(path):
        return []
    return os.listdir(path)


def upload_dir(local_dir: str, dest: str) -> None:
    """Recursively copy a local directory into `dest` (URI or local)."""
    if is_uri(dest):
        fs, p = _fs_and_path(dest)
        fs.makedirs(p, exist_ok=True)
        # fs.put(recursive) with a trailing-slash source copies contents
        fs.put(os.path.join(local_dir, ""), p, recursive=True)
    else:
        shutil.copytree(local_dir, dest, dirs_exist_ok=True)


def download_dir(src: str, local_dir: str) -> None:
    if is_uri(src):
        fs, p = _fs_and_path(src)
        os.makedirs(local_dir, exist_ok=True)
        fs.get(p.rstrip("/") + "/", os.path.join(local_dir, ""),
               recursive=True)
    else:
        shutil.copytree(src, local_dir, dirs_exist_ok=True)


def rmtree(path: str) -> None:
    if is_uri(path):
        fs, p = _fs_and_path(path)
        try:
            fs.rm(p, recursive=True)
        except FileNotFoundError:
            pass
    else:
        shutil.rmtree(path, ignore_errors=True)


def read_text(path: str) -> str:
    if is_uri(path):
        fs, p = _fs_and_path(path)
        with fs.open(p, "r") as f:
            return f.read()
    with open(path) as f:
        return f.read()


def write_text(path: str, text: str) -> None:
    if is_uri(path):
        fs, p = _fs_and_path(path)
        with fs.open(p, "w") as f:
            f.write(text)
    else:
        with open(path, "w") as f:
            f.write(text)


def append_text(path: str, text: str) -> None:
    if is_uri(path):
        # remote object stores have no append: read-modify-write (state
        # files here are small jsonl logs; fine for the control path)
        old = read_text(path) if exists(path) else ""
        write_text(path, old + text)
    else:
        with open(path, "a") as f:
            f.write(text)


class StorageContext:
    """Bundles a run's storage root with async checkpoint upload
    (reference: train/_internal/storage.py:358 StorageContext).

    Uploads are pipelined: `upload_dir_async` returns immediately and the
    next call (or `wait`) joins the previous upload first, so step N+1's
    compute overlaps step N's upload — the reference's async persistence
    pattern without unbounded in-flight state."""

    def __init__(self, storage_path: str, experiment_name: str = "",
                 trial_name: str = ""):
        self.storage_path = storage_path
        self.experiment_name = experiment_name
        self.trial_name = trial_name
        self._upload_thread: Optional[threading.Thread] = None
        self._upload_error: Optional[BaseException] = None

    @property
    def is_remote(self) -> bool:
        return is_uri(self.storage_path)

    @property
    def experiment_dir(self) -> str:
        return join(self.storage_path, self.experiment_name) \
            if self.experiment_name else self.storage_path

    @property
    def trial_dir(self) -> str:
        return join(self.experiment_dir, self.trial_name) \
            if self.trial_name else self.experiment_dir

    def upload_dir_async(self, local_dir: str, dest: str,
                         on_complete=None) -> None:
        self.wait()

        def run():
            try:
                upload_dir(local_dir, dest)
                if on_complete is not None:
                    on_complete()
            except BaseException as e:  # surfaced on next wait()
                self._upload_error = e

        self._upload_thread = threading.Thread(
            target=run, daemon=True, name="ckpt-upload")
        self._upload_thread.start()

    def wait(self, timeout: Optional[float] = None) -> None:
        """Join the in-flight upload; re-raise its error, if any.

        A timed-out join leaves the upload tracked (still in flight):
        callers must not mistake a timeout for completion — the
        completion marker is only written by the upload itself."""
        t = self._upload_thread
        if t is not None:
            t.join(timeout=timeout)
            if t.is_alive():
                raise TimeoutError(
                    f"checkpoint upload still in flight after {timeout}s")
            self._upload_thread = None
        if self._upload_error is not None:
            e, self._upload_error = self._upload_error, None
            raise e
