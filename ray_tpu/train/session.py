"""Per-worker training session: the bridge between the user's
train_loop_per_worker and the driver-side BackendExecutor.

Mirrors the reference (reference: python/ray/train/_internal/session.py —
_TrainSession :111, report :403, module-level report/get_context :667/:754):
the user loop runs in a thread inside the worker actor; `report()` persists
an optional checkpoint to run storage and enqueues the metrics, which the
actor's `next_result()` hands to the driver.  `report()` blocks until the
driver consumed the previous result — that back-pressure keeps all workers
in lockstep on the report boundary (the reference does the same via
a result queue of size 1).
"""

from __future__ import annotations

import os
import queue
import shutil
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional

from .checkpoint import Checkpoint


@dataclass
class TrainContext:
    """What a worker knows about its place in the run."""

    world_size: int = 1
    world_rank: int = 0
    local_rank: int = 0
    local_world_size: int = 1
    node_rank: int = 0
    experiment_name: str = ""
    trial_name: str = ""
    trial_id: str = ""
    trial_dir: str = ""
    extra: Dict[str, Any] = field(default_factory=dict)

    def get_world_size(self) -> int:
        return self.world_size

    def get_world_rank(self) -> int:
        return self.world_rank

    def get_local_rank(self) -> int:
        return self.local_rank

    def get_local_world_size(self) -> int:
        return self.local_world_size

    def get_node_rank(self) -> int:
        return self.node_rank

    def get_trial_dir(self) -> str:
        return self.trial_dir

    def get_experiment_name(self) -> str:
        return self.experiment_name

    def get_trial_name(self) -> str:
        return self.trial_name

    def get_trial_id(self) -> str:
        return self.trial_id


class _FinishedMarker:
    def __init__(self, error: Optional[BaseException] = None,
                 final: Optional[Dict[str, Any]] = None):
        self.error = error
        self.final = final


class SessionAborted(BaseException):
    """Raised inside the user train loop when the driver aborts the session
    (trial paused/stopped).  BaseException so user `except Exception`
    blocks don't swallow it; `finally` blocks (worker-group shutdown,
    placement-group release) still run as the loop unwinds."""


class TrainSession:
    """Owns the user-loop thread inside one training worker."""

    def __init__(self, ctx: TrainContext, train_fn: Callable[[], Any],
                 checkpoint: Optional[Checkpoint] = None,
                 checkpoint_upload_dir: Optional[str] = None,
                 dataset_shards: Optional[Dict[str, Any]] = None,
                 start_iteration: int = 0):
        from .storage import StorageContext

        self.ctx = ctx
        self._train_fn = train_fn
        self._restore_checkpoint = checkpoint
        self._upload_dir = checkpoint_upload_dir
        self._storage = StorageContext(
            checkpoint_upload_dir or ctx.trial_dir or ".",
            ctx.experiment_name, ctx.trial_name)
        self._dataset_shards = dataset_shards or {}
        self._results: "queue.Queue" = queue.Queue(maxsize=1)
        self._continue = threading.Semaphore(0)
        # after an elastic restart the new session continues numbering from
        # the rounds already consumed, so checkpoint_<n> dirs never collide
        # with (and never clobber) pre-failure checkpoints
        self._iteration = start_iteration
        self._aborted = False
        # flight recorder (ISSUE 5): one StepTimer per session, armed from
        # the telemetry config BackendExecutor rode in through ctx.extra
        self._step_timer = None
        self._flush_interval = 2.0
        tel = ctx.extra.get("telemetry")
        if tel is None or (isinstance(tel, dict) and tel.get("enabled", True)):
            try:
                from ray_tpu.telemetry import StepTimer, resolve_telemetry

                tc = resolve_telemetry(tel)
                if tc.enabled:
                    self._step_timer = StepTimer(
                        ring_size=tc.ring_size,
                        rank=ctx.world_rank,
                        incarnation=int(
                            ctx.extra.get("elastic_incarnation", 0)),
                        trial=ctx.trial_name)
                    self._flush_interval = tc.flush_interval_s
            except Exception:
                pass
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"train-rank{ctx.world_rank}")
        self._started = False

    # -- lifecycle (called from the worker actor) --------------------------

    def start(self):
        self._started = True
        _set_session(self)
        self._thread.start()

    def _run(self):
        # this helper thread IS the actor task's work: adopt its context
        # so a blocking get() inside the user loop (dataset shards,
        # collective rendezvous) lends the worker's CPUs — without this,
        # 2 train workers blocked on a 1-CPU split coordinator deadlock
        # a fully-booked cluster
        from ray_tpu._private.core import adopt_task_context

        adopt_task_context()
        # bind THIS loop thread to THIS session: after an elastic abort +
        # restart, a zombie loop thread (still unwinding from a blocked
        # collective) must see its own aborted session — not the fresh
        # one installed in the module global — so its next report()
        # raises SessionAborted instead of corrupting the new lockstep
        _tls.session = self
        if self._step_timer is not None:
            from ray_tpu.telemetry import recorder as _recorder

            _recorder.set_current_timer(self._step_timer)
            self._step_timer.step_start(self._iteration)
        try:
            out = self._train_fn()
            # the last checkpoint upload may still be in flight: the
            # driver reads `latest complete checkpoint` right after the
            # finish marker, so land it (and surface its error) first
            self._storage.wait()
            if self._step_timer is not None:
                # final forced flush: the worker group is torn down right
                # after the finish marker, and a worker shorter-lived than
                # FLUSH_INTERVAL_S would otherwise never land its ring or
                # its Prometheus series in KV
                from ray_tpu.telemetry import recorder as _recorder
                from ray_tpu.util.metrics import _registry as _mreg

                _recorder.flush_snapshot(self._step_timer, force=True)
                try:
                    _mreg.flush()
                except Exception:
                    pass
            self._results.put(_FinishedMarker(final=out if isinstance(out, dict) else None))
        except SessionAborted:
            return  # driver-initiated teardown; nobody is consuming results
        except BaseException as e:  # surfaced to the driver, not swallowed
            if self._aborted:
                return
            self._results.put(_FinishedMarker(error=e))

    def next_result(self, timeout: Optional[float] = None):
        """Blocking: next reported result, or a finish/error marker.

        Returns ("result", metrics, ckpt_path) | ("finished", final, None)
        and raises the user exception on failure.
        """
        item = self._results.get(timeout=timeout)
        if isinstance(item, _FinishedMarker):
            if item.error is not None:
                raise item.error
            return ("finished", item.final, None)
        metrics, ckpt_path = item
        self._continue.release()  # unblock the user loop's report()
        return ("result", metrics, ckpt_path)

    def finish(self, timeout: float = 10.0):
        if self._started:
            self._thread.join(timeout=timeout)

    def abort(self, timeout: float = 10.0):
        """Unwind the user loop: its next (or currently blocked) report()
        raises SessionAborted, so nested resources held by the loop (worker
        groups, placement groups) are released by its finally blocks."""
        self._aborted = True
        self._continue.release()
        # drain a possibly queued result so a blocked put() can't wedge
        try:
            self._results.get_nowait()
        except queue.Empty:
            pass
        # ... and unblock a next_result() call already parked on the
        # queue: the worker actor has bounded concurrency, so a forever-
        # blocked result lane would wedge the actor after an elastic
        # restart (the driver abandons the old ref, but the lane must
        # free itself)
        try:
            self._results.put_nowait(
                _FinishedMarker(error=RuntimeError("session aborted")))
        except queue.Full:
            pass
        if self._started:
            self._thread.join(timeout=timeout)

    # -- user-facing (called from the train loop thread) -------------------

    def report(self, metrics: Dict[str, Any],
               checkpoint: Optional[Checkpoint] = None) -> None:
        if self._aborted:
            raise SessionAborted()
        self._iteration += 1
        ckpt_path = None
        timer = self._step_timer
        if checkpoint is not None:
            if timer is not None:
                with timer.phase("checkpoint"):
                    ckpt_path = self._persist_checkpoint(checkpoint)
            else:
                ckpt_path = self._persist_checkpoint(checkpoint)
        metrics = dict(metrics)
        if timer is not None:
            rec = timer.step_end(step=self._iteration - 1)
            if rec is not None and "telemetry" not in metrics:
                metrics["telemetry"] = rec
            from ray_tpu.telemetry import recorder as _recorder

            _recorder.flush_snapshot(timer,
                                     interval_s=self._flush_interval)
        self._results.put((metrics, ckpt_path))
        self._continue.acquire()  # lockstep with the driver's consumption
        if self._aborted:
            raise SessionAborted()
        if timer is not None:
            # start the next step only after the driver consumed this
            # round: the lockstep wait is driver time, not step time
            timer.step_start(self._iteration)

    def _persist_checkpoint(self, checkpoint: Checkpoint) -> str:
        """Copy the worker-local checkpoint dir into run storage.

        Layout: <trial_dir>/checkpoint_<iter>/rank_<k>/... so multi-host
        sharded checkpoints (each host saving its param shards, the orbax
        pattern) land in one logical checkpoint directory.

        Remote storage (URI trial dir): each worker uploads its own shard
        directly to the remote filesystem — multi-host pods have no
        shared local disk.  Uploads are ASYNC and pipelined (snapshot the
        dir now, upload in the background, write the completion marker
        only after the upload lands): the next training step overlaps the
        previous upload, and restore paths skip marker-less dirs.
        """
        from . import storage

        base = self._upload_dir or self.ctx.trial_dir
        dest = storage.join(base, f"checkpoint_{self._iteration - 1:06d}")
        if self.ctx.world_size > 1:
            dest_rank = storage.join(dest, f"rank_{self.ctx.world_rank}")
        else:
            dest_rank = dest
        marker = storage.join(
            dest, f".complete_rank_{self.ctx.world_rank}")
        if storage.is_uri(base):
            import tempfile

            # snapshot before returning: the user loop may rewrite the
            # local dir while the background upload is still reading it
            snap = tempfile.mkdtemp(prefix="ckpt-up-")
            shutil.copytree(checkpoint.path, snap, dirs_exist_ok=True)

            def on_complete(_snap=snap, _marker=marker):
                storage.write_text(_marker, "")
                shutil.rmtree(_snap, ignore_errors=True)

            self._storage.upload_dir_async(snap, dest_rank,
                                           on_complete=on_complete)
            return dest
        os.makedirs(dest, exist_ok=True)
        if os.path.abspath(checkpoint.path) != os.path.abspath(dest_rank):
            shutil.copytree(checkpoint.path, dest_rank, dirs_exist_ok=True)
        # completion marker, written last: restore paths skip checkpoint
        # dirs that died mid-copy (no marker present)
        with open(marker, "w"):
            pass
        return dest

    def get_checkpoint(self) -> Optional[Checkpoint]:
        return self._restore_checkpoint

    def get_dataset_shard(self, name: str = "train"):
        return self._dataset_shards.get(name)


# -- module-level accessors (the `ray_tpu.train.report(...)` API) ----------

_session_lock = threading.Lock()
_session: Optional[TrainSession] = None
_tls = threading.local()


def _set_session(s: Optional[TrainSession]):
    global _session
    with _session_lock:
        _session = s


def _get_session() -> Optional[TrainSession]:
    # loop threads resolve their own session (see TrainSession._run);
    # anything else (actor control lane, user helper threads) gets the
    # process-current one
    tls = getattr(_tls, "session", None)
    return tls if tls is not None else _session


def report(metrics: Dict[str, Any],
           checkpoint: Optional[Checkpoint] = None) -> None:
    """Report metrics (+ optional checkpoint) from the train loop
    (reference: session.py:667)."""
    s = _get_session()
    if s is None:
        raise RuntimeError("ray_tpu.train.report() called outside a "
                           "training worker")
    s.report(metrics, checkpoint)


def get_context() -> TrainContext:
    s = _get_session()
    if s is None:
        return TrainContext()
    return s.ctx


def get_checkpoint() -> Optional[Checkpoint]:
    s = _get_session()
    return None if s is None else s.get_checkpoint()


def get_dataset_shard(name: str = "train"):
    s = _get_session()
    return None if s is None else s.get_dataset_shard(name)
