"""TensorflowTrainer: TF MultiWorkerMirroredStrategy on the cluster.

Reference parity: python/ray/train/tensorflow/ — TensorflowTrainer
(tensorflow_trainer.py) is a DataParallelTrainer whose backend publishes
TF_CONFIG across the worker group; the user's loop opens
``tf.distribute.MultiWorkerMirroredStrategy()`` which reads it.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from .backend import TensorflowConfig
from .trainer import JaxTrainer

__all__ = ["TensorflowTrainer", "TensorflowConfig", "prepare_dataset_shard"]


class TensorflowTrainer(JaxTrainer):
    """Same orchestration as JaxTrainer with the TF_CONFIG backend::

        def loop(config):
            strategy = tf.distribute.MultiWorkerMirroredStrategy()
            with strategy.scope():
                model = ...
            ...
            session.report({"loss": ...})

        TensorflowTrainer(loop,
                          scaling_config=ScalingConfig(num_workers=2)).fit()
    """

    def __init__(self, train_loop_per_worker: Callable, *,
                 train_loop_config: Optional[Dict[str, Any]] = None,
                 tensorflow_config: Optional[TensorflowConfig] = None,
                 **kwargs):
        kwargs.setdefault("backend_config",
                          tensorflow_config or TensorflowConfig())
        super().__init__(train_loop_per_worker,
                         train_loop_config=train_loop_config, **kwargs)


def prepare_dataset_shard(dataset):
    """Disable TF auto-sharding on an already-per-worker dataset
    (reference: train/tensorflow/train_loop_utils.py)."""
    import tensorflow as tf

    options = tf.data.Options()
    options.experimental_distribute.auto_shard_policy = \
        tf.data.experimental.AutoShardPolicy.OFF
    return dataset.with_options(options)
