"""Checkpoint retention + best-checkpoint tracking.

Mirrors the reference (reference: python/ray/train/_internal/
checkpoint_manager.py): every reported checkpoint is registered with its
metrics; retention keeps the `num_to_keep` best by the configured score
attribute (or the most recent, when no attribute is set).
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional, Tuple

from .checkpoint import Checkpoint
from .config import CheckpointConfig

logger = logging.getLogger(__name__)


class _TrackedCheckpoint:
    __slots__ = ("checkpoint", "metrics", "index")

    def __init__(self, checkpoint: Checkpoint, metrics: Dict[str, Any],
                 index: int):
        self.checkpoint = checkpoint
        self.metrics = metrics
        self.index = index


class CheckpointManager:
    def __init__(self, config: Optional[CheckpointConfig] = None):
        self.config = config or CheckpointConfig()
        self._checkpoints: List[_TrackedCheckpoint] = []
        self._next_index = 0
        # emergency (in-memory, peer-replicated) tier: recovery events are
        # recorded, not retained — the payloads live in worker vaults, not
        # in run storage, so retention/scoring never applies to them
        self._emergency_events: List[Dict[str, Any]] = []

    def register_checkpoint(self, checkpoint: Checkpoint,
                            metrics: Dict[str, Any]) -> None:
        self._checkpoints.append(
            _TrackedCheckpoint(checkpoint, dict(metrics), self._next_index))
        self._next_index += 1
        self._enforce_retention()

    def _score(self, t: _TrackedCheckpoint) -> float:
        attr = self.config.checkpoint_score_attribute
        if attr is None:
            return float(t.index)  # newest wins
        v = t.metrics.get(attr)
        if v is None:
            logger.warning("checkpoint %s lacks score attribute %r",
                           t.checkpoint.path, attr)
            return float("-inf")
        return float(v) if self.config.checkpoint_score_order == "max" else -float(v)

    def _enforce_retention(self) -> None:
        keep = self.config.num_to_keep
        if keep is None or len(self._checkpoints) <= keep:
            return
        # the most recent checkpoint is the resume point: never evicted
        latest = max(self._checkpoints, key=lambda t: t.index)
        ranked = sorted((t for t in self._checkpoints if t is not latest),
                        key=self._score, reverse=True)
        from . import storage

        while len(self._checkpoints) > keep and ranked:
            t = ranked.pop()
            self._checkpoints.remove(t)
            storage.rmtree(t.checkpoint.path)

    def note_emergency(self, step: int,
                       metadata: Optional[Dict[str, Any]] = None) -> None:
        """Record an emergency-tier recovery (elastic restart restored
        from peer-replicated shards at `step`)."""
        import time

        self._emergency_events.append({
            "step": int(step), "tier": "emergency", "ts": time.time(),
            **(metadata or {})})

    @property
    def emergency_events(self) -> List[Dict[str, Any]]:
        return list(self._emergency_events)

    @property
    def latest_checkpoint(self) -> Optional[Checkpoint]:
        if not self._checkpoints:
            return None
        return max(self._checkpoints, key=lambda t: t.index).checkpoint

    @property
    def best_checkpoint(self) -> Optional[Checkpoint]:
        if not self._checkpoints:
            return None
        return max(self._checkpoints, key=self._score).checkpoint

    def best_checkpoints(self) -> List[Tuple[Checkpoint, Dict[str, Any]]]:
        return [(t.checkpoint, t.metrics)
                for t in sorted(self._checkpoints, key=self._score,
                                reverse=True)]
