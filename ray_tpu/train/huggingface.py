"""HuggingFace Transformers integration for Train.

Reference parity: python/ray/train/huggingface/ — the current-API
pattern is TorchTrainer + ``prepare_trainer`` + ``RayTrainReportCallback``
(transformers/_transformers_utils.py): the user's train loop builds a
normal ``transformers.Trainer``; the callback forwards its logs to
``session.report`` and the worker-group torch process group makes HF's
own distributed handling data-parallel.

Usage::

    def loop(config):
        trainer = transformers.Trainer(model=..., args=..., ...)
        trainer.add_callback(RayTrainReportCallback())
        trainer = prepare_trainer(trainer)
        trainer.train()

    TorchTrainer(loop, scaling_config=ScalingConfig(num_workers=2)).fit()
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

__all__ = ["RayTrainReportCallback", "prepare_trainer"]


def _transformers():
    import transformers

    return transformers


class RayTrainReportCallback:
    """Forwards HF Trainer logs (and checkpoint saves) to
    ``session.report`` (reference: RayTrainReportCallback)."""

    def __new__(cls, *a, **kw):
        # subclass TrainerCallback lazily so importing this module never
        # requires transformers
        transformers = _transformers()

        class _Impl(transformers.TrainerCallback):
            def on_log(self, args, state, control, logs=None, **kwargs):
                if not logs or not state.is_world_process_zero:
                    # rank-0 metrics are authoritative; other ranks report
                    # an empty heartbeat so the driver's per-round gather
                    # stays aligned
                    logs = {}
                from ray_tpu.train.session import report

                metrics = dict(logs)
                metrics["step"] = state.global_step
                metrics["epoch"] = state.epoch
                report(metrics)

        return _Impl()


def prepare_trainer(trainer):
    """Adjust a transformers.Trainer for the worker group (reference:
    prepare_trainer): make sure distributed env naming matches what HF /
    accelerate expect from the already-initialized gloo group."""
    world = os.environ.get("RAY_TPU_TRAIN_WORLD_SIZE")
    rank = os.environ.get("RAY_TPU_TRAIN_WORLD_RANK")
    if world and int(world) > 1:
        os.environ.setdefault("WORLD_SIZE", world)
        os.environ.setdefault("RANK", rank or "0")
        os.environ.setdefault("LOCAL_RANK", "0")
    return trainer
