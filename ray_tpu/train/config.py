"""Train run configuration dataclasses.

Mirrors the reference's air configs (reference: python/ray/air/config.py —
ScalingConfig :102, FailureConfig :394, CheckpointConfig :444, RunConfig
:593) with TPU-native resource naming: workers request `num_tpus` (chips)
instead of GPUs, and `topology` describes the per-worker mesh axes.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

# Gradient-sync compression config lives with the collective subsystem;
# re-exported here so training code configures it next to the other run
# configs (JaxConfig(compression=CompressionConfig(...))).
from ray_tpu.collective.compression import CompressionConfig


@dataclass
class ScalingConfig:
    """How many training workers and what each needs.

    num_workers: actor count (one per TPU host in multi-host runs).
    use_tpu: give each worker `tpus_per_worker` TPU chips.
    resources_per_worker: extra custom resources per worker.
    placement_strategy: bundle strategy for the gang placement group —
        PACK keeps workers on one ICI slice when possible.
    """

    num_workers: int = 1
    use_tpu: bool = False
    tpus_per_worker: float = 0.0
    cpus_per_worker: float = 1.0
    resources_per_worker: Optional[Dict[str, float]] = None
    placement_strategy: str = "PACK"
    trainer_resources: Optional[Dict[str, float]] = None

    def bundle(self) -> Dict[str, float]:
        b: Dict[str, float] = dict(self.resources_per_worker or {})
        if self.cpus_per_worker:
            b["CPU"] = float(self.cpus_per_worker)
        if self.use_tpu and self.tpus_per_worker:
            b["TPU"] = float(self.tpus_per_worker)
        return b

    def as_placement_group_bundles(self):
        return [self.bundle() for _ in range(self.num_workers)]


@dataclass
class FailureConfig:
    """max_failures: worker-group restarts tolerated before the run fails;
    -1 means unlimited (reference: air/config.py:394)."""

    max_failures: int = 0


@dataclass
class CheckpointConfig:
    """Checkpoint retention policy (reference: air/config.py:444).

    num_to_keep: keep at most N checkpoints (None = all).
    checkpoint_score_attribute/order: which metric ranks checkpoints for
    retention and `Result.best_checkpoints`.
    """

    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"

    def __post_init__(self):
        if self.num_to_keep is not None and self.num_to_keep <= 0:
            raise ValueError("num_to_keep must be >= 1 or None")
        if self.checkpoint_score_order not in ("max", "min"):
            raise ValueError("checkpoint_score_order must be 'max' or 'min'")


@dataclass
class RunConfig:
    """Run-level config (reference: air/config.py:593).

    storage_path: where checkpoints/results persist — a local directory
    or a remote filesystem URI (s3://, gs://, or any fsspec scheme);
    workers upload checkpoints straight to it, which is how multi-host
    pods (no shared local disk) persist state (see train/storage.py,
    reference: train/_internal/storage.py:358 StorageContext).
    """

    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)
    stop: Optional[Dict[str, Any]] = None  # e.g. {"training_iteration": 10}
    verbose: int = 1
    #: experiment callbacks (tune.Callback subclasses — e.g. the
    #: TBX/W&B/MLflow logger callbacks in ray_tpu.air.integrations);
    #: fire per result in Tuner runs AND standalone trainer.fit()
    #: (reference: air/config.py RunConfig.callbacks)
    callbacks: List[Any] = field(default_factory=list)

    def resolved_storage_path(self) -> str:
        from . import storage

        path = (self.storage_path
                or os.environ.get("RAY_TPU_STORAGE", "~/ray_tpu_results"))
        return path if storage.is_uri(path) else os.path.expanduser(path)
