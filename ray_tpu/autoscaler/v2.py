"""Autoscaler v2: declarative instance-manager + reconciler.

Reference parity: python/ray/autoscaler/v2 — `InstanceStorage` (versioned
instance table, instance_manager/instance_storage.py), `InstanceManager`
(update-based mutations, instance_manager/instance_manager.py), and the
`Reconciler` (instance_manager/reconciler.py) that drives each instance
through its lifecycle:

    QUEUED -> REQUESTED -> ALLOCATED -> RAY_RUNNING
           -> RAY_STOPPING -> TERMINATING -> TERMINATED

Unlike v1's imperative loop (autoscaler.py StandardAutoscaler), v2 first
declares a *target* instance set from resource demand, records it, and
then reconciles observed cloud/node state against the declared state —
so a crashed autoscaler resumes from its instance table instead of
re-deriving intent from scratch.

The cloud layer is the same NodeProvider interface as v1; the demand
scheduler is reused from v1 (ResourceDemandScheduler).
"""

from __future__ import annotations

import logging
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from .autoscaler import LoadMetrics, ResourceDemandScheduler
from .node_provider import (NodeProvider, TAG_NODE_KIND, TAG_NODE_STATUS,
                            TAG_NODE_TYPE)

logger = logging.getLogger(__name__)

# instance lifecycle states (reference: instance_manager.proto Instance)
QUEUED = "QUEUED"
REQUESTED = "REQUESTED"
ALLOCATED = "ALLOCATED"
RAY_RUNNING = "RAY_RUNNING"
RAY_STOPPING = "RAY_STOPPING"
TERMINATING = "TERMINATING"
TERMINATED = "TERMINATED"


@dataclass
class GoodputPolicy:
    """Goodput-driven scaling knobs (the self-healing loop's capacity
    arm): when a training run's goodput sags while demand queues, spare
    capacity is launched ahead of strict bin-packing need; while it
    sags, idle termination pauses so recovery headroom isn't shaved.

    scale_up_below: launch spares when any RUNNING trial's goodput drops
        below this fraction AND queue pressure warrants it.
    scale_down_above: idle termination only proceeds while every
        RUNNING trial's goodput is at/above this fraction.
    min_queue: queued demands required before goodput alone triggers a
        spare launch (goodput sag with an empty queue means the gang is
        recovering, not starved).
    max_extra: cap on goodput-motivated spare instances on the way up at
        any moment (counted against QUEUED/REQUESTED/ALLOCATED).
    """

    scale_up_below: float = 0.7
    scale_down_above: float = 0.95
    min_queue: int = 1
    max_extra: int = 2


@dataclass
class ServeSLOPolicy:
    """Serve-SLO scaling knobs (the inference-side analog of
    GoodputPolicy): when a deployment's decode engines queue past the
    per-replica watermark, breach the p99-TTFT SLO, or shed everything,
    spare capacity is launched ahead of strict bin-packing need; while
    any deployment is under pressure, idle termination pauses.

    max_queue_per_replica: average engine waiting-queue depth per
        RUNNING replica that counts as pressure (0 disables).
    ttft_slo_s: p99 time-to-first-token above this counts as pressure
        (0 disables).
    max_extra: cap on SLO-motivated spare instances on the way up at
        any moment (counted against QUEUED/REQUESTED/ALLOCATED).
    """

    max_queue_per_replica: float = 4.0
    ttft_slo_s: float = 0.0
    max_extra: int = 2


def _serve_pressure(snapshot: Dict[str, Any],
                    pol: "ServeSLOPolicy") -> Optional[str]:
    """First deployment violating the serve SLO, as a human-readable
    reason — None when every deployment is inside its envelope."""
    for name, load in (snapshot.get("serve_load") or {}).items():
        replicas = max(1, int(load.get("replicas", 1) or 1))
        queued = float(load.get("queue_depth", 0) or 0)
        if pol.max_queue_per_replica > 0 \
                and queued / replicas > pol.max_queue_per_replica:
            return (f"{name}: {queued:g} queued across {replicas} "
                    f"replica(s) > {pol.max_queue_per_replica:g}/replica")
        ttft = float(load.get("ttft_p99_s", 0.0) or 0.0)
        if pol.ttft_slo_s > 0 and ttft > pol.ttft_slo_s:
            return (f"{name}: p99 TTFT {ttft:.3f}s > "
                    f"{pol.ttft_slo_s:g}s SLO")
        if int(load.get("accepting", 1) or 0) == 0:
            return f"{name}: every replica shedding"
    return None


def _min_goodput(snapshot: Dict[str, Any]) -> Optional[float]:
    vals = list((snapshot.get("train_goodput") or {}).values())
    return min(vals) if vals else None


def _untainted(nodes: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Drop draining/quarantined nodes from a capacity view: demand that
    only 'fits' on a node the control plane is steering work away from
    is unmet demand, and must drive a launch."""
    return [n for n in nodes
            if not n.get("draining") and not n.get("quarantined")]


@dataclass
class Instance:
    instance_id: str
    instance_type: str
    status: str = QUEUED
    cloud_instance_id: Optional[str] = None
    node_id: Optional[str] = None  # control-plane node id once running
    launch_request_id: str = ""
    status_since: float = field(default_factory=time.monotonic)
    version: int = 0

    def transition(self, status: str):
        self.status = status
        self.status_since = time.monotonic()


class InstanceStorage:
    """Versioned instance table (reference: instance_storage.py).

    Every batch upsert carries the expected table version; a stale writer
    gets a conflict instead of silently clobbering a concurrent update.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._instances: Dict[str, Instance] = {}
        self._version = 0

    @property
    def version(self) -> int:
        with self._lock:
            return self._version

    def batch_upsert(self, instances: List[Instance],
                     expected_version: Optional[int] = None
                     ) -> Tuple[bool, int]:
        with self._lock:
            if expected_version is not None \
                    and expected_version != self._version:
                return False, self._version
            self._version += 1
            for inst in instances:
                inst.version = self._version
                self._instances[inst.instance_id] = inst
            return True, self._version

    def delete(self, instance_ids: List[str]) -> None:
        with self._lock:
            for iid in instance_ids:
                self._instances.pop(iid, None)
            self._version += 1

    def get_instances(self, statuses: Optional[List[str]] = None
                      ) -> Dict[str, Instance]:
        with self._lock:
            return {iid: inst for iid, inst in self._instances.items()
                    if statuses is None or inst.status in statuses}


class InstanceManager:
    """Update-based mutations over the instance table (reference:
    instance_manager.py — callers submit status transitions; direct table
    writes are not exposed)."""

    def __init__(self, storage: Optional[InstanceStorage] = None):
        self.storage = storage or InstanceStorage()

    def add_instances(self, instance_type: str, count: int,
                      launch_request_id: Optional[str] = None
                      ) -> List[Instance]:
        rid = launch_request_id or uuid.uuid4().hex[:12]
        instances = [
            Instance(instance_id=f"inst-{uuid.uuid4().hex[:12]}",
                     instance_type=instance_type,
                     launch_request_id=rid)
            for _ in range(count)
        ]
        self.storage.batch_upsert(instances)
        return instances

    def update_status(self, instance_id: str, status: str, **fields) -> bool:
        insts = self.storage.get_instances()
        inst = insts.get(instance_id)
        if inst is None:
            return False
        inst.transition(status)
        for k, v in fields.items():
            setattr(inst, k, v)
        self.storage.batch_upsert([inst])
        return True


class Reconciler:
    """One reconciliation pass (reference: reconciler.py Reconcile):
    observe cloud + cluster state, declare the target from demand, and
    step every instance toward its goal state."""

    def __init__(self, manager: InstanceManager, provider: NodeProvider,
                 scheduler: ResourceDemandScheduler,
                 load_metrics: LoadMetrics,
                 idle_timeout_s: float = 60.0,
                 request_timeout_s: float = 300.0,
                 goodput_policy: Optional[GoodputPolicy] = None,
                 serve_policy: Optional[ServeSLOPolicy] = None):
        self.im = manager
        self.provider = provider
        self.scheduler = scheduler
        self.load = load_metrics
        self.idle_timeout_s = idle_timeout_s
        self.request_timeout_s = request_timeout_s
        self.goodput_policy = goodput_policy
        self.serve_policy = serve_policy
        self.num_launched = 0
        self.num_terminated = 0
        self.num_goodput_launches = 0
        self.num_goodput_holds = 0
        self.num_serve_launches = 0
        self.num_serve_holds = 0

    # -- observation --------------------------------------------------------

    def _sync_cloud_state(self):
        """Cloud says a requested instance now exists (or a tracked one
        vanished) — move statuses accordingly."""
        alive = set(self.provider.non_terminated_nodes({}))
        for inst in self.im.storage.get_instances().values():
            if inst.status in (REQUESTED,) and inst.cloud_instance_id \
                    and inst.cloud_instance_id in alive:
                self.im.update_status(inst.instance_id, ALLOCATED)
            elif inst.status in (ALLOCATED, RAY_RUNNING) \
                    and inst.cloud_instance_id not in alive:
                # died under us (preemption, manual delete)
                self.im.update_status(inst.instance_id, TERMINATED)
            elif inst.status == TERMINATING \
                    and inst.cloud_instance_id not in alive:
                self.im.update_status(inst.instance_id, TERMINATED)
                self.num_terminated += 1

    def _sync_ray_state(self, snapshot: Dict[str, Any]):
        """A control-plane node appeared on an allocated instance →
        RAY_RUNNING (reference: Reconciler matching ray nodes to
        instances by cloud id)."""
        running_nodes = {n["node_id"] for n in snapshot.get("nodes", [])}
        by_cloud = {}
        for n in snapshot.get("nodes", []):
            cid = (n.get("labels") or {}).get("cloud_instance_id")
            if cid:
                by_cloud[cid] = n["node_id"]
        for inst in self.im.storage.get_instances([ALLOCATED]).values():
            nid = by_cloud.get(inst.cloud_instance_id)
            if nid is None and len(running_nodes) > 0 \
                    and inst.cloud_instance_id in running_nodes:
                nid = inst.cloud_instance_id
            if nid is not None:
                self.im.update_status(inst.instance_id, RAY_RUNNING,
                                      node_id=nid)

    # -- declaration --------------------------------------------------------

    def _declare_target(self, snapshot: Dict[str, Any]):
        """Compute instances to add from unmet demand (the declarative
        step: we only *enqueue* here; launching happens in stepping).

        Draining/quarantined nodes are dropped from the capacity view:
        the control plane is steering work away from them, so demand
        that only "fits" there must still drive a launch.  When a
        goodput policy is set and a RUNNING trial's goodput sags below
        its threshold while demand queues, spare instances are enqueued
        beyond strict bin-packing need (capped by max_extra)."""
        pending_like = self.im.storage.get_instances(
            [QUEUED, REQUESTED, ALLOCATED])
        # feed the scheduler a view that includes instances on the way up
        # so demand isn't double-counted into duplicate launches
        snap = dict(snapshot)
        extra_nodes = []
        for inst in pending_like.values():
            res = self.scheduler.node_types.get(
                inst.instance_type, {}).get("resources", {})
            extra_nodes.append({"node_id": inst.instance_id,
                                "available": dict(res),
                                "total": dict(res)})
        snap["nodes"] = _untainted(
            list(snapshot.get("nodes", []))) + extra_nodes
        to_launch = self.scheduler.get_nodes_to_launch(
            snap, self._counts_by_type())
        for type_name, count in to_launch.items():
            if count > 0:
                self.im.add_instances(type_name, count)
        self._declare_goodput_spares(snapshot, to_launch)
        self._declare_serve_spares(snapshot, to_launch)

    def _declare_serve_spares(self, snapshot: Dict[str, Any],
                              demand_launch: Dict[str, int]):
        pol = self.serve_policy
        if pol is None:
            return
        reason = _serve_pressure(snapshot, pol)
        if reason is None:
            return
        on_the_way = len(self.im.storage.get_instances(
            [QUEUED, REQUESTED, ALLOCATED])) + sum(demand_launch.values())
        budget = pol.max_extra - on_the_way
        if budget <= 0:
            return
        counts = self._counts_by_type()
        total = sum(counts.values())
        for tname, tcfg in self.scheduler.node_types.items():
            cap = tcfg.get("max_workers", self.scheduler.max_workers)
            room = min(cap - counts.get(tname, 0),
                       self.scheduler.max_workers - total, budget)
            if room <= 0:
                continue
            logger.info("serve SLO pressure (%s): launching %d spare %s",
                        reason, room, tname)
            self.im.add_instances(tname, room)
            self.num_serve_launches += room
            return

    def _declare_goodput_spares(self, snapshot: Dict[str, Any],
                                demand_launch: Dict[str, int]):
        pol = self.goodput_policy
        if pol is None:
            return
        gp = _min_goodput(snapshot)
        if gp is None or gp >= pol.scale_up_below:
            return
        if len(snapshot.get("demands", [])) < pol.min_queue:
            return
        on_the_way = len(self.im.storage.get_instances(
            [QUEUED, REQUESTED, ALLOCATED])) + sum(demand_launch.values())
        budget = pol.max_extra - on_the_way
        if budget <= 0:
            return
        # spares take the first type with headroom under its max_workers
        counts = self._counts_by_type()
        total = sum(counts.values())
        for tname, tcfg in self.scheduler.node_types.items():
            cap = tcfg.get("max_workers", self.scheduler.max_workers)
            room = min(cap - counts.get(tname, 0),
                       self.scheduler.max_workers - total, budget)
            if room <= 0:
                continue
            logger.info(
                "goodput %.2f < %.2f with %d queued demands: launching "
                "%d spare %s", gp, pol.scale_up_below,
                len(snapshot.get("demands", [])), room, tname)
            self.im.add_instances(tname, room)
            self.num_goodput_launches += room
            return

    def _counts_by_type(self) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for inst in self.im.storage.get_instances().values():
            if inst.status not in (TERMINATED,):
                counts[inst.instance_type] = \
                    counts.get(inst.instance_type, 0) + 1
        return counts

    # -- stepping -----------------------------------------------------------

    def _step_queued(self):
        for inst in self.im.storage.get_instances([QUEUED]).values():
            node_cfg = dict(self.scheduler.node_types.get(
                inst.instance_type, {}))
            try:
                cloud_ids = self.provider.create_node(
                    node_cfg, {TAG_NODE_KIND: "worker",
                               TAG_NODE_TYPE: inst.instance_type,
                               TAG_NODE_STATUS: "pending"}, 1)
            except Exception as e:
                logger.warning("launch of %s failed: %s",
                               inst.instance_type, e)
                continue
            self.num_launched += 1
            self.im.update_status(
                inst.instance_id, REQUESTED,
                cloud_instance_id=cloud_ids[0] if cloud_ids else None)

    def _step_idle_termination(self, snapshot: Dict[str, Any]):
        pol = self.goodput_policy
        if pol is not None:
            gp = _min_goodput(snapshot)
            if gp is not None and gp < pol.scale_down_above:
                # a run is below healthy goodput: keep every node — the
                # recovery may need exactly the capacity we'd shave
                self.num_goodput_holds += 1
                logger.debug(
                    "idle termination held: goodput %.2f < %.2f",
                    gp, pol.scale_down_above)
                return
        if self.serve_policy is not None:
            reason = _serve_pressure(snapshot, self.serve_policy)
            if reason is not None:
                # a deployment is under SLO pressure: shaving nodes now
                # would fight the replicas the controller wants to add
                self.num_serve_holds += 1
                logger.debug("idle termination held: %s", reason)
                return
        idle_s = snapshot.get("idle_s", {})
        min_workers = {
            t: cfg.get("min_workers", 0)
            for t, cfg in self.scheduler.node_types.items()}
        counts = self._counts_by_type()
        for inst in self.im.storage.get_instances([RAY_RUNNING]).values():
            node_idle = idle_s.get(inst.node_id, 0.0)
            if node_idle < self.idle_timeout_s:
                continue
            if counts.get(inst.instance_type, 0) \
                    <= min_workers.get(inst.instance_type, 0):
                continue
            counts[inst.instance_type] -= 1
            self.im.update_status(inst.instance_id, RAY_STOPPING)

    def _step_stopping(self):
        for inst in self.im.storage.get_instances(
                [RAY_STOPPING]).values():
            try:
                if inst.cloud_instance_id:
                    self.provider.terminate_node(inst.cloud_instance_id)
            except Exception as e:
                logger.warning("terminate of %s failed: %s",
                               inst.cloud_instance_id, e)
                continue
            self.im.update_status(inst.instance_id, TERMINATING)

    def _step_stuck_requests(self):
        """Requests that never allocated within the timeout are retried
        (requeued) — reference: reconciler's stuck-instance handling."""
        now = time.monotonic()
        for inst in self.im.storage.get_instances([REQUESTED]).values():
            if now - inst.status_since > self.request_timeout_s:
                logger.warning("instance %s stuck in REQUESTED; requeueing",
                               inst.instance_id)
                self.im.update_status(inst.instance_id, QUEUED,
                                      cloud_instance_id=None)

    def _gc_terminated(self):
        dead = list(self.im.storage.get_instances([TERMINATED]))
        if dead:
            self.im.storage.delete(dead)

    def reconcile(self) -> None:
        snapshot = self.load.snapshot()
        self._sync_cloud_state()
        self._sync_ray_state(snapshot)
        self._declare_target(snapshot)
        self._step_queued()
        self._step_idle_termination(snapshot)
        self._step_stopping()
        self._step_stuck_requests()
        self._gc_terminated()


class AutoscalerV2:
    """Facade wiring storage + manager + reconciler, mirroring
    autoscaler/v2/autoscaler.py's composition."""

    def __init__(self, config: Dict[str, Any], provider: NodeProvider,
                 control_client):
        node_types = config.get("available_node_types", {})
        self.scheduler = ResourceDemandScheduler(
            node_types, max_workers=config.get("max_workers", 8))
        self.manager = InstanceManager()
        gp_cfg = config.get("goodput")
        policy = None
        if gp_cfg is not None:
            policy = GoodputPolicy(**gp_cfg) if isinstance(gp_cfg, dict) \
                else GoodputPolicy()
        slo_cfg = config.get("serve_slo")
        serve_policy = None
        if slo_cfg is not None:
            serve_policy = ServeSLOPolicy(**slo_cfg) \
                if isinstance(slo_cfg, dict) else ServeSLOPolicy()
        self.reconciler = Reconciler(
            self.manager, provider, self.scheduler,
            LoadMetrics(control_client),
            idle_timeout_s=config.get("idle_timeout_minutes", 1.0) * 60.0,
            goodput_policy=policy, serve_policy=serve_policy)

    def update(self):
        self.reconciler.reconcile()

    @property
    def instances(self) -> Dict[str, Instance]:
        return self.manager.storage.get_instances()
