"""Node providers: pluggable machine lifecycle backends.

Analog of the reference's NodeProvider interface (reference:
python/ray/autoscaler/node_provider.py) with two implementations:

  * LocalNodeProvider — "launches" nodes as local raylet processes
    against the running control plane (the reference's
    FakeMultiNodeProvider pattern, autoscaler/_private/fake_multi_node/
    node_provider.py) — the workhorse for autoscaler tests.
  * GCPTpuNodeProvider — models GCE TPU pod-slice provisioning (the
    reference's gcp provider + TPU support, autoscaler/_private/gcp/
    config.py:42-216): one *slice* is the atomic unit, creating N host
    nodes with ICI-topology labels.  API calls are delegated to an
    injectable transport so it is testable offline (zero egress here).
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Any, Dict, List, Optional

TAG_NODE_KIND = "node-kind"        # head | worker
TAG_NODE_TYPE = "node-type"        # user node type name
TAG_NODE_STATUS = "node-status"    # pending | up-to-date | terminated


class NodeProvider:
    """Machine lifecycle interface (reference: node_provider.py)."""

    def __init__(self, provider_config: Dict[str, Any], cluster_name: str):
        self.provider_config = provider_config
        self.cluster_name = cluster_name

    def non_terminated_nodes(self, tag_filters: Dict[str, str]) -> List[str]:
        raise NotImplementedError

    def node_tags(self, node_id: str) -> Dict[str, str]:
        raise NotImplementedError

    def create_node(self, node_config: Dict[str, Any],
                    tags: Dict[str, str], count: int) -> List[str]:
        raise NotImplementedError

    def terminate_node(self, node_id: str):
        raise NotImplementedError

    def is_running(self, node_id: str) -> bool:
        raise NotImplementedError


class LocalNodeProvider(NodeProvider):
    """Launch raylets as local processes joined to a live control plane.

    provider_config: {"control_address": "host:port"}.
    node_config: {"resources": {...}, "labels": {...}}.
    """

    def __init__(self, provider_config, cluster_name):
        super().__init__(provider_config, cluster_name)
        from ray_tpu._private.bootstrap import Cluster

        addr = provider_config["control_address"].rsplit(":", 1)
        self._cluster = Cluster(
            session_name=f"autoscaler-{cluster_name}-{uuid.uuid4().hex[:6]}")
        self._cluster.control_addr = (addr[0], int(addr[1]))
        self._lock = threading.Lock()
        self._nodes: Dict[str, Dict[str, Any]] = {}  # id -> {handle, tags}

    def non_terminated_nodes(self, tag_filters):
        with self._lock:
            out = []
            for nid, rec in self._nodes.items():
                if rec["tags"].get(TAG_NODE_STATUS) == "terminated":
                    continue
                if all(rec["tags"].get(k) == v
                       for k, v in tag_filters.items()):
                    out.append(nid)
            return out

    def node_tags(self, node_id):
        with self._lock:
            return dict(self._nodes[node_id]["tags"])

    def create_node(self, node_config, tags, count):
        created = []
        for _ in range(count):
            handle = self._cluster.add_node(
                resources=node_config.get("resources"),
                labels=node_config.get("labels"), wait=True)
            nid = handle.node_id
            with self._lock:
                self._nodes[nid] = {
                    "handle": handle,
                    "tags": {**tags, TAG_NODE_STATUS: "up-to-date"},
                }
            created.append(nid)
        return created

    def terminate_node(self, node_id):
        with self._lock:
            rec = self._nodes.get(node_id)
        if rec is None:
            return
        rec["handle"].terminate()
        with self._lock:
            rec["tags"][TAG_NODE_STATUS] = "terminated"

    def is_running(self, node_id):
        with self._lock:
            rec = self._nodes.get(node_id)
        return rec is not None and rec["handle"].proc.poll() is None

    def shutdown(self):
        with self._lock:
            recs = list(self._nodes.values())
        for rec in recs:
            try:
                rec["handle"].terminate()
            except Exception:
                pass


class GCPTpuNodeProvider(NodeProvider):
    """TPU pod-slice provisioning model (offline transport-injected).

    The reference provisions TPU VMs through the GCE API with tpu.admin
    role and validates multi-host slices (reference:
    autoscaler/_private/gcp/config.py:42 `_get_num_tpu_chips`, multi-host
    validation :150-216; example configs autoscaler/gcp/tpu.yaml).  Here a
    node type describes a *slice* (accelerator_type like "v5e-16"); one
    create_node provisions every host of the slice with slice/worker
    topology labels so the scheduler can gang-place onto one ICI domain.

    provider_config["transport"]: object with create_tpu_slice(name, type,
    zone) / delete_tpu_slice(name) / list_slices() — a real GCE client in
    production, a fake in tests.  Without one, creation raises (zero
    egress).
    """

    #: chips per host for each generation (reference: tpu.py host bounds)
    CHIPS_PER_HOST = {"v4": 4, "v5e": 4, "v5p": 4, "v6e": 4}

    def __init__(self, provider_config, cluster_name):
        super().__init__(provider_config, cluster_name)
        self.transport = provider_config.get("transport")
        self.zone = provider_config.get("zone", "us-central2-b")
        self._lock = threading.Lock()
        self._nodes: Dict[str, Dict[str, Any]] = {}

    @classmethod
    def slice_hosts(cls, accelerator_type: str) -> int:
        """"v5e-16" -> 16 chips -> 4 hosts."""
        gen, chips = accelerator_type.rsplit("-", 1)
        per_host = cls.CHIPS_PER_HOST.get(gen, 4)
        return max(1, int(chips) // per_host)

    def create_node(self, node_config, tags, count):
        if self.transport is None:
            raise RuntimeError(
                "GCPTpuNodeProvider needs provider_config['transport'] "
                "(a GCE TPU API client); none configured")
        acc = node_config["accelerator_type"]
        created = []
        for _ in range(count):
            slice_name = f"{self.cluster_name}-{uuid.uuid4().hex[:8]}"
            self.transport.create_tpu_slice(slice_name, acc, self.zone)
            hosts = self.slice_hosts(acc)
            per_host = self.CHIPS_PER_HOST.get(acc.rsplit("-", 1)[0], 4)
            for w in range(hosts):
                nid = f"{slice_name}-w{w}"
                with self._lock:
                    self._nodes[nid] = {
                        "slice": slice_name,
                        "tags": {
                            **tags,
                            TAG_NODE_STATUS: "up-to-date",
                            "tpu-slice": slice_name,
                            "tpu-worker-id": str(w),
                            "tpu-accelerator-type": acc,
                        },
                        "resources": {"CPU": 96.0, "TPU": float(per_host)},
                        "created_at": time.time(),
                    }
                created.append(nid)
        return created

    def non_terminated_nodes(self, tag_filters):
        with self._lock:
            return [nid for nid, rec in self._nodes.items()
                    if rec["tags"].get(TAG_NODE_STATUS) != "terminated"
                    and all(rec["tags"].get(k) == v
                            for k, v in tag_filters.items())]

    def node_tags(self, node_id):
        with self._lock:
            return dict(self._nodes[node_id]["tags"])

    def terminate_node(self, node_id):
        """Terminating any host of a slice releases the whole slice (a
        partial TPU slice is unusable)."""
        with self._lock:
            rec = self._nodes.get(node_id)
            if rec is None:
                return
            slice_name = rec["slice"]
            peers = [n for n, r in self._nodes.items()
                     if r.get("slice") == slice_name]
        if self.transport is not None:
            self.transport.delete_tpu_slice(slice_name)
        with self._lock:
            for n in peers:
                self._nodes[n]["tags"][TAG_NODE_STATUS] = "terminated"

    def is_running(self, node_id):
        with self._lock:
            rec = self._nodes.get(node_id)
        return rec is not None and \
            rec["tags"].get(TAG_NODE_STATUS) == "up-to-date"


class KubernetesNodeProvider(NodeProvider):
    """KubeRay/GKE-shaped provider: one ray worker = one pod, managed
    through the Kubernetes API (reference:
    autoscaler/_private/kuberay/node_provider.py — pods carry ray.io/*
    labels; the autoscaler reconciles by creating/deleting pods, and
    the kubelet/scheduler does the rest).

    TPU pod slices follow the GKE recipe: a node type with
    `accelerator_type` (e.g. "v5e-16") + `topology` (e.g. "4x4")
    creates ONE POD PER SLICE HOST, each pinned to the slice's node
    pool via the cloud.google.com/gke-tpu-* selectors and requesting
    google.com/tpu chips — that is how real TPU pods are provisioned
    on GKE, and the slice/worker labels are what gang placement needs
    to land a whole slice on one ICI domain.

    provider_config:
      namespace: k8s namespace (default "default")
      api_client: duck-typed API server client —
          create_pod(namespace, manifest) -> manifest (server fills
              metadata.name if generateName was used)
          list_pods(namespace, label_selector) -> [pod dicts]
          delete_pod(namespace, name)
        a real kubernetes.client.CoreV1Api adapter in production, a
        fake in tests (zero egress here).
      pod_template: optional baseline pod manifest merged under ours.
    """

    RAY_CLUSTER_LABEL = "ray.io/cluster"
    RAY_TYPE_LABEL = "ray.io/node-type"
    RAY_KIND_LABEL = "ray.io/node-kind"
    #: GKE TPU node-pool selectors (the published GKE TPU recipe)
    GKE_ACCEL_SELECTOR = "cloud.google.com/gke-tpu-accelerator"
    GKE_TOPO_SELECTOR = "cloud.google.com/gke-tpu-topology"
    #: accelerator_type generation -> GKE accelerator selector value
    GKE_ACCEL_NAMES = {"v4": "tpu-v4-podslice",
                       "v5e": "tpu-v5-lite-podslice",
                       "v5p": "tpu-v5p-slice",
                       "v6e": "tpu-v6e-slice"}

    #: pod-list cache TTL: a reconcile tick calls node_tags once per
    #: node, and each would otherwise LIST every cluster pod — O(P^2)
    #: API-server requests per tick at scale
    LIST_CACHE_TTL_S = 2.0

    def __init__(self, provider_config, cluster_name):
        super().__init__(provider_config, cluster_name)
        self.api = provider_config.get("api_client")
        if self.api is None:
            self.api = _default_kubernetes_client()
        self.namespace = provider_config.get("namespace", "default")
        self.pod_template = provider_config.get("pod_template") or {}
        self._pods_cache: Optional[Dict[str, Dict]] = None
        self._pods_cache_at = 0.0

    # -- pod <-> node mapping ---------------------------------------------
    # (one TTL-cached LIST per tick; tag filters apply client-side on
    # the cached manifests rather than as server-side label selectors)

    def _cluster_pods(self) -> Dict[str, Dict]:
        now = time.monotonic()
        if self._pods_cache is None \
                or now - self._pods_cache_at > self.LIST_CACHE_TTL_S:
            pods = self.api.list_pods(
                self.namespace,
                f"{self.RAY_CLUSTER_LABEL}={self.cluster_name}")
            self._pods_cache = {p["metadata"]["name"]: p for p in pods}
            self._pods_cache_at = now
        return self._pods_cache

    def _invalidate(self):
        self._pods_cache = None

    def non_terminated_nodes(self, tag_filters):
        want = {_tag_to_label(k): v for k, v in tag_filters.items()}
        return [name for name, p in self._cluster_pods().items()
                if p.get("status", {}).get("phase")
                not in ("Succeeded", "Failed")
                and all(p["metadata"]["labels"].get(k) == v
                        for k, v in want.items())]

    def node_tags(self, node_id):
        p = self._cluster_pods().get(node_id)
        if p is None:
            # a pod deleted mid-reconcile (e.g. its slice peer was
            # terminated this tick) is just gone, not an error
            return {}
        return {_label_to_tag(k): v
                for k, v in p["metadata"]["labels"].items()}

    def is_running(self, node_id):
        p = self._cluster_pods().get(node_id)
        return p is not None and \
            p.get("status", {}).get("phase") == "Running"

    # -- create / delete ---------------------------------------------------

    def create_node(self, node_config, tags, count):
        acc = node_config.get("accelerator_type")
        created = []
        for _ in range(count):
            if acc:
                created += self._create_tpu_slice_pods(node_config, tags)
            else:
                created.append(self._create_pod(node_config, tags, {}))
        return created

    def _create_tpu_slice_pods(self, node_config, tags) -> List[str]:
        acc = node_config["accelerator_type"]
        gen = acc.rsplit("-", 1)[0]
        hosts = GCPTpuNodeProvider.slice_hosts(acc)
        per_host = GCPTpuNodeProvider.CHIPS_PER_HOST.get(gen, 4)
        topology = node_config.get("topology")
        slice_name = f"{self.cluster_name}-{uuid.uuid4().hex[:8]}"
        names = []
        for w in range(hosts):
            extra = {
                "labels": {"tpu-slice": slice_name,
                           "tpu-worker-id": str(w),
                           "tpu-accelerator-type": acc},
                "nodeSelector": {
                    self.GKE_ACCEL_SELECTOR:
                        self.GKE_ACCEL_NAMES.get(gen, gen),
                    **({self.GKE_TOPO_SELECTOR: topology}
                       if topology else {}),
                },
                "resources": {"google.com/tpu": per_host},
            }
            names.append(self._create_pod(node_config, tags, extra,
                                          name=f"{slice_name}-w{w}"))
        return names

    def _create_pod(self, node_config, tags, extra,
                    name: Optional[str] = None) -> str:
        labels = {self.RAY_CLUSTER_LABEL: self.cluster_name}
        for k, v in tags.items():
            labels[_tag_to_label(k)] = v
        labels.update(extra.get("labels", {}))
        spec = dict(self.pod_template.get("spec", {}))
        if extra.get("nodeSelector"):
            spec["nodeSelector"] = {**spec.get("nodeSelector", {}),
                                    **extra["nodeSelector"]}
        containers = spec.get("containers") or [{"name": "ray-worker"}]
        c0 = dict(containers[0])
        limits = dict(node_config.get("custom_resources", {}))
        limits.update(extra.get("resources", {}))
        if limits:
            # merge INTO the template's resources: clobbering the dict
            # would drop its requests and the kube scheduler would place
            # the pod as if it needed no cpu/memory
            res = dict(c0.get("resources", {}))
            res["limits"] = {**res.get("limits", {}), **limits}
            c0["resources"] = res
        # downward API: the raylet inside the pod registers with the
        # POD NAME as its control-plane node id (node.py honors
        # RAY_TPU_NODE_ID), which is what lets the autoscaler match
        # control-plane idleness back to a pod for scale-down
        env = [e for e in c0.get("env", [])
               if e.get("name") != "RAY_TPU_NODE_ID"]
        env.append({"name": "RAY_TPU_NODE_ID", "valueFrom": {
            "fieldRef": {"fieldPath": "metadata.name"}}})
        c0["env"] = env
        containers = [c0, *containers[1:]]
        spec["containers"] = containers
        tmeta = self.pod_template.get("metadata", {})
        manifest = {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                **({"name": name} if name
                   else {"generateName": f"{self.cluster_name}-worker-"}),
                "labels": {**tmeta.get("labels", {}), **labels},
                **({"annotations": tmeta["annotations"]}
                   if tmeta.get("annotations") else {}),
            },
            "spec": spec,
        }
        out = self.api.create_pod(self.namespace, manifest)
        self._invalidate()
        return out["metadata"]["name"]

    def terminate_node(self, node_id):
        """TPU slice pods release as a unit (a partial slice is
        unusable), matching GCPTpuNodeProvider semantics."""
        tags = self.node_tags(node_id)
        slice_name = tags.get("tpu-slice")
        if not tags:
            # the pod itself is gone (drained/evicted out-of-band) but
            # its slice peers may survive as an unusable partial slice:
            # slice pod names are <slice>-w<N>, so recover the slice
            # label and release the peers too
            base, sep, tail = node_id.rpartition("-w")
            if sep and tail.isdigit():
                slice_name = base
            else:
                return
        if slice_name:
            sel = (f"{self.RAY_CLUSTER_LABEL}={self.cluster_name},"
                   f"tpu-slice={slice_name}")
            for p in self.api.list_pods(self.namespace, sel):
                self.api.delete_pod(self.namespace,
                                    p["metadata"]["name"])
        else:
            self.api.delete_pod(self.namespace, node_id)
        self._invalidate()


def _tag_to_label(tag: str) -> str:
    # node-kind/node-type/node-status ride as ray.io/* labels (kuberay
    # convention); anything else passes through as-is
    if tag in (TAG_NODE_KIND, TAG_NODE_TYPE, TAG_NODE_STATUS):
        return f"ray.io/{tag}"
    return tag


def _label_to_tag(label: str) -> str:
    return label[len("ray.io/"):] if label.startswith("ray.io/") else label


def _default_kubernetes_client():
    """Adapt kubernetes.client.CoreV1Api to the duck surface (in-cluster
    config first, kubeconfig fallback) — only importable where the k8s
    client library exists."""
    try:
        from kubernetes import client, config  # type: ignore
    except ImportError as e:
        raise ImportError(
            "KubernetesNodeProvider needs the kubernetes client library "
            "(not available in this environment) — or pass "
            "provider_config['api_client'] with a compatible client") from e
    try:
        config.load_incluster_config()
    except Exception:
        config.load_kube_config()
    v1 = client.CoreV1Api()

    class _Adapter:
        def create_pod(self, namespace, manifest):
            out = v1.create_namespaced_pod(namespace, manifest)
            return client.ApiClient().sanitize_for_serialization(out)

        def list_pods(self, namespace, label_selector):
            out = v1.list_namespaced_pod(namespace,
                                         label_selector=label_selector)
            return client.ApiClient().sanitize_for_serialization(
                out)["items"]

        def delete_pod(self, namespace, name):
            v1.delete_namespaced_pod(name, namespace)

    return _Adapter()


def make_node_provider(provider_config: Dict[str, Any],
                       cluster_name: str) -> NodeProvider:
    """Provider factory keyed by provider.type (reference:
    autoscaler/_private/providers.py _get_node_provider)."""
    kind = (provider_config or {}).get("type", "local")
    if kind == "local":
        return LocalNodeProvider(provider_config, cluster_name)
    if kind in ("gcp_tpu", "gcp"):
        return GCPTpuNodeProvider(provider_config, cluster_name)
    if kind in ("kubernetes", "kuberay", "gke"):
        return KubernetesNodeProvider(provider_config, cluster_name)
    raise ValueError(f"unknown node provider type {kind!r}")
