"""Node providers: pluggable machine lifecycle backends.

Analog of the reference's NodeProvider interface (reference:
python/ray/autoscaler/node_provider.py) with two implementations:

  * LocalNodeProvider — "launches" nodes as local raylet processes
    against the running control plane (the reference's
    FakeMultiNodeProvider pattern, autoscaler/_private/fake_multi_node/
    node_provider.py) — the workhorse for autoscaler tests.
  * GCPTpuNodeProvider — models GCE TPU pod-slice provisioning (the
    reference's gcp provider + TPU support, autoscaler/_private/gcp/
    config.py:42-216): one *slice* is the atomic unit, creating N host
    nodes with ICI-topology labels.  API calls are delegated to an
    injectable transport so it is testable offline (zero egress here).
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Any, Dict, List, Optional

TAG_NODE_KIND = "node-kind"        # head | worker
TAG_NODE_TYPE = "node-type"        # user node type name
TAG_NODE_STATUS = "node-status"    # pending | up-to-date | terminated


class NodeProvider:
    """Machine lifecycle interface (reference: node_provider.py)."""

    def __init__(self, provider_config: Dict[str, Any], cluster_name: str):
        self.provider_config = provider_config
        self.cluster_name = cluster_name

    def non_terminated_nodes(self, tag_filters: Dict[str, str]) -> List[str]:
        raise NotImplementedError

    def node_tags(self, node_id: str) -> Dict[str, str]:
        raise NotImplementedError

    def create_node(self, node_config: Dict[str, Any],
                    tags: Dict[str, str], count: int) -> List[str]:
        raise NotImplementedError

    def terminate_node(self, node_id: str):
        raise NotImplementedError

    def is_running(self, node_id: str) -> bool:
        raise NotImplementedError


class LocalNodeProvider(NodeProvider):
    """Launch raylets as local processes joined to a live control plane.

    provider_config: {"control_address": "host:port"}.
    node_config: {"resources": {...}, "labels": {...}}.
    """

    def __init__(self, provider_config, cluster_name):
        super().__init__(provider_config, cluster_name)
        from ray_tpu._private.bootstrap import Cluster

        addr = provider_config["control_address"].rsplit(":", 1)
        self._cluster = Cluster(
            session_name=f"autoscaler-{cluster_name}-{uuid.uuid4().hex[:6]}")
        self._cluster.control_addr = (addr[0], int(addr[1]))
        self._lock = threading.Lock()
        self._nodes: Dict[str, Dict[str, Any]] = {}  # id -> {handle, tags}

    def non_terminated_nodes(self, tag_filters):
        with self._lock:
            out = []
            for nid, rec in self._nodes.items():
                if rec["tags"].get(TAG_NODE_STATUS) == "terminated":
                    continue
                if all(rec["tags"].get(k) == v
                       for k, v in tag_filters.items()):
                    out.append(nid)
            return out

    def node_tags(self, node_id):
        with self._lock:
            return dict(self._nodes[node_id]["tags"])

    def create_node(self, node_config, tags, count):
        created = []
        for _ in range(count):
            handle = self._cluster.add_node(
                resources=node_config.get("resources"),
                labels=node_config.get("labels"), wait=True)
            nid = handle.node_id
            with self._lock:
                self._nodes[nid] = {
                    "handle": handle,
                    "tags": {**tags, TAG_NODE_STATUS: "up-to-date"},
                }
            created.append(nid)
        return created

    def terminate_node(self, node_id):
        with self._lock:
            rec = self._nodes.get(node_id)
        if rec is None:
            return
        rec["handle"].terminate()
        with self._lock:
            rec["tags"][TAG_NODE_STATUS] = "terminated"

    def is_running(self, node_id):
        with self._lock:
            rec = self._nodes.get(node_id)
        return rec is not None and rec["handle"].proc.poll() is None

    def shutdown(self):
        with self._lock:
            recs = list(self._nodes.values())
        for rec in recs:
            try:
                rec["handle"].terminate()
            except Exception:
                pass


class GCPTpuNodeProvider(NodeProvider):
    """TPU pod-slice provisioning model (offline transport-injected).

    The reference provisions TPU VMs through the GCE API with tpu.admin
    role and validates multi-host slices (reference:
    autoscaler/_private/gcp/config.py:42 `_get_num_tpu_chips`, multi-host
    validation :150-216; example configs autoscaler/gcp/tpu.yaml).  Here a
    node type describes a *slice* (accelerator_type like "v5e-16"); one
    create_node provisions every host of the slice with slice/worker
    topology labels so the scheduler can gang-place onto one ICI domain.

    provider_config["transport"]: object with create_tpu_slice(name, type,
    zone) / delete_tpu_slice(name) / list_slices() — a real GCE client in
    production, a fake in tests.  Without one, creation raises (zero
    egress).
    """

    #: chips per host for each generation (reference: tpu.py host bounds)
    CHIPS_PER_HOST = {"v4": 4, "v5e": 4, "v5p": 4, "v6e": 4}

    def __init__(self, provider_config, cluster_name):
        super().__init__(provider_config, cluster_name)
        self.transport = provider_config.get("transport")
        self.zone = provider_config.get("zone", "us-central2-b")
        self._lock = threading.Lock()
        self._nodes: Dict[str, Dict[str, Any]] = {}

    @classmethod
    def slice_hosts(cls, accelerator_type: str) -> int:
        """"v5e-16" -> 16 chips -> 4 hosts."""
        gen, chips = accelerator_type.rsplit("-", 1)
        per_host = cls.CHIPS_PER_HOST.get(gen, 4)
        return max(1, int(chips) // per_host)

    def create_node(self, node_config, tags, count):
        if self.transport is None:
            raise RuntimeError(
                "GCPTpuNodeProvider needs provider_config['transport'] "
                "(a GCE TPU API client); none configured")
        acc = node_config["accelerator_type"]
        created = []
        for _ in range(count):
            slice_name = f"{self.cluster_name}-{uuid.uuid4().hex[:8]}"
            self.transport.create_tpu_slice(slice_name, acc, self.zone)
            hosts = self.slice_hosts(acc)
            per_host = self.CHIPS_PER_HOST.get(acc.rsplit("-", 1)[0], 4)
            for w in range(hosts):
                nid = f"{slice_name}-w{w}"
                with self._lock:
                    self._nodes[nid] = {
                        "slice": slice_name,
                        "tags": {
                            **tags,
                            TAG_NODE_STATUS: "up-to-date",
                            "tpu-slice": slice_name,
                            "tpu-worker-id": str(w),
                            "tpu-accelerator-type": acc,
                        },
                        "resources": {"CPU": 96.0, "TPU": float(per_host)},
                        "created_at": time.time(),
                    }
                created.append(nid)
        return created

    def non_terminated_nodes(self, tag_filters):
        with self._lock:
            return [nid for nid, rec in self._nodes.items()
                    if rec["tags"].get(TAG_NODE_STATUS) != "terminated"
                    and all(rec["tags"].get(k) == v
                            for k, v in tag_filters.items())]

    def node_tags(self, node_id):
        with self._lock:
            return dict(self._nodes[node_id]["tags"])

    def terminate_node(self, node_id):
        """Terminating any host of a slice releases the whole slice (a
        partial TPU slice is unusable)."""
        with self._lock:
            rec = self._nodes.get(node_id)
            if rec is None:
                return
            slice_name = rec["slice"]
            peers = [n for n, r in self._nodes.items()
                     if r.get("slice") == slice_name]
        if self.transport is not None:
            self.transport.delete_tpu_slice(slice_name)
        with self._lock:
            for n in peers:
                self._nodes[n]["tags"][TAG_NODE_STATUS] = "terminated"

    def is_running(self, node_id):
        with self._lock:
            rec = self._nodes.get(node_id)
        return rec is not None and \
            rec["tags"].get(TAG_NODE_STATUS) == "up-to-date"


def make_node_provider(provider_config: Dict[str, Any],
                       cluster_name: str) -> NodeProvider:
    """Provider factory keyed by provider.type (reference:
    autoscaler/_private/providers.py _get_node_provider)."""
    kind = (provider_config or {}).get("type", "local")
    if kind == "local":
        return LocalNodeProvider(provider_config, cluster_name)
    if kind in ("gcp_tpu", "gcp"):
        return GCPTpuNodeProvider(provider_config, cluster_name)
    raise ValueError(f"unknown node provider type {kind!r}")
