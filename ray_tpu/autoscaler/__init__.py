"""Autoscaler (reference: python/ray/autoscaler/)."""

from .autoscaler import (LoadMetrics, Monitor, ResourceDemandScheduler,
                         StandardAutoscaler)
from .node_provider import (GCPTpuNodeProvider, LocalNodeProvider,
                            NodeProvider)
from .v2 import (AutoscalerV2, Instance, InstanceManager, InstanceStorage,
                 Reconciler)

__all__ = [
    "StandardAutoscaler", "Monitor", "LoadMetrics",
    "ResourceDemandScheduler", "NodeProvider", "LocalNodeProvider",
    "GCPTpuNodeProvider",
    "AutoscalerV2", "Instance", "InstanceManager", "InstanceStorage",
    "Reconciler",
]
