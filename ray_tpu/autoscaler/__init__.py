"""Autoscaler (reference: python/ray/autoscaler/)."""

from .autoscaler import (LoadMetrics, Monitor, ResourceDemandScheduler,
                         StandardAutoscaler)
from .node_provider import (GCPTpuNodeProvider, LocalNodeProvider,
                            NodeProvider)

__all__ = [
    "StandardAutoscaler", "Monitor", "LoadMetrics",
    "ResourceDemandScheduler", "NodeProvider", "LocalNodeProvider",
    "GCPTpuNodeProvider",
]
