"""StandardAutoscaler: demand-driven scale-up, idle-timeout scale-down.

Analog of the reference's autoscaler v1 loop (reference:
autoscaler/_private/autoscaler.py:172 StandardAutoscaler.update, driven by
monitor.py; demand from load_metrics.py; node picking in
resource_demand_scheduler.py):

  update():
    1. LoadMetrics pulls cluster state: per-node utilization/idleness from
       the control plane, queued lease demands from each raylet, PENDING
       actors/placement groups.
    2. ResourceDemandScheduler bin-packs unmet demands onto node types to
       get "nodes to launch" (respecting min/max per type).
    3. Launch via the provider; terminate nodes idle past the timeout
       (never below min_workers; never the head).
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional, Tuple

from .node_provider import (TAG_NODE_KIND, TAG_NODE_STATUS, TAG_NODE_TYPE,
                            NodeProvider)

logger = logging.getLogger(__name__)


class LoadMetrics:
    """Cluster demand/usage snapshot (reference: load_metrics.py).

    Besides resource demand, the snapshot carries the train-side health
    signals the v2 goodput policy scales on: per-RUNNING-trial goodput
    fractions (from the run states the Trainer publishes into KV ns
    'train') under ``train_goodput``."""

    def __init__(self, control_client):
        self.control = control_client
        #: node_id -> monotonic ts when last seen busy
        self.last_busy: Dict[str, float] = {}

    def _train_goodput(self) -> Dict[str, float]:
        """trial -> goodput fraction for every RUNNING/RESTARTING run
        that publishes telemetry.  Advisory: any failure yields {}."""
        import json

        out: Dict[str, float] = {}
        try:
            keys = self.control.call(
                "kv_keys", {"ns": "train", "prefix": ""}, timeout=5.0) or []
            for key in keys:
                raw = self.control.call(
                    "kv_get", {"ns": "train", "key": key}, timeout=5.0)
                if not raw:
                    continue
                state = json.loads(
                    raw.decode() if isinstance(raw, bytes) else raw)
                if state.get("status") not in ("RUNNING", "RESTARTING"):
                    continue
                gp = ((state.get("telemetry") or {}).get("goodput")
                      or {}).get("goodput")
                if gp is not None:
                    out[key] = float(gp)
        except Exception:
            return {}
        return out

    def _serve_load(self) -> Dict[str, Any]:
        """'app:deployment' -> decode-engine load aggregates (queue_depth,
        ttft_p99_s, accepting, ...) from the Serve controller's status
        snapshot in KV ns 'serve'.  Advisory: any failure yields {}."""
        import json

        try:
            raw = self.control.call(
                "kv_get", {"ns": "serve", "key": "status"}, timeout=5.0)
            if not raw:
                return {}
            snap = json.loads(
                raw.decode() if isinstance(raw, bytes) else raw)
            load = snap.get("serve_load") or {}
            return load if isinstance(load, dict) else {}
        except Exception:
            return {}

    def snapshot(self) -> Dict[str, Any]:
        from ray_tpu._private.protocol import Client

        nodes = self.control.call("get_nodes", {}, timeout=10.0)
        demands: List[Dict[str, float]] = []
        now = time.monotonic()
        alive = []
        for n in nodes:
            if n["state"] != "ALIVE":
                continue
            alive.append(n)
            busy = n["available"] != n["total"]
            try:
                c = Client(tuple(n["addr"]), name="autoscaler-probe")
                try:
                    pending = c.call("pending_demands", {}, timeout=5.0)
                    demands.extend(pending)
                    busy = busy or bool(pending)
                finally:
                    c.close()
            except Exception:
                pass
            if busy or n["node_id"] not in self.last_busy:
                self.last_busy[n["node_id"]] = now
        # PENDING actors carry their resource demand
        dump = self.control.call("state_dump", {}, timeout=10.0)
        for a in dump["actors"]:
            if a["state"] == "PENDING" and a.get("resources"):
                demands.append(dict(a["resources"]))
        for pg in dump["pgs"]:
            if pg["state"] == "PENDING":
                demands.extend(dict(b) for b in pg["bundles"])
        return {"nodes": alive, "demands": demands,
                "idle_s": {nid: now - ts
                           for nid, ts in self.last_busy.items()},
                "train_goodput": self._train_goodput(),
                "serve_load": self._serve_load()}


class ResourceDemandScheduler:
    """First-fit-decreasing bin packing of unmet demands onto node types
    (reference: resource_demand_scheduler.py get_nodes_to_launch)."""

    def __init__(self, node_types: Dict[str, Dict[str, Any]],
                 max_workers: int):
        self.node_types = node_types
        self.max_workers = max_workers

    @staticmethod
    def _fits(demand: Dict[str, float], free: Dict[str, float]) -> bool:
        return all(free.get(k, 0.0) >= v for k, v in demand.items())

    @staticmethod
    def _consume(demand: Dict[str, float], free: Dict[str, float]):
        for k, v in demand.items():
            free[k] = free.get(k, 0.0) - v

    def get_nodes_to_launch(self, snapshot: Dict[str, Any],
                            current_by_type: Dict[str, int]
                            ) -> Dict[str, int]:
        # start from current free capacity
        free_pools = [dict(n["available"]) for n in snapshot["nodes"]]
        unmet: List[Dict[str, float]] = []
        for demand in sorted(snapshot["demands"],
                             key=lambda d: -sum(d.values())):
            for pool in free_pools:
                if self._fits(demand, pool):
                    self._consume(demand, pool)
                    break
            else:
                unmet.append(demand)
        if not unmet:
            return {}

        to_launch: Dict[str, int] = {}
        total_workers = sum(current_by_type.values())
        for demand in unmet:
            placed = False
            # try capacity of nodes we already decided to launch
            for pool in free_pools:
                if self._fits(demand, pool):
                    self._consume(demand, pool)
                    placed = True
                    break
            if placed:
                continue
            for tname, tcfg in self.node_types.items():
                res = tcfg.get("resources", {})
                launched = current_by_type.get(tname, 0) \
                    + to_launch.get(tname, 0)
                if launched >= tcfg.get("max_workers", self.max_workers):
                    continue
                if total_workers + sum(to_launch.values()) \
                        >= self.max_workers:
                    break
                if self._fits(demand, dict(res)):
                    to_launch[tname] = to_launch.get(tname, 0) + 1
                    pool = dict(res)
                    self._consume(demand, pool)
                    free_pools.append(pool)
                    placed = True
                    break
            if not placed:
                logger.warning("demand %s does not fit any node type",
                               demand)
        return to_launch


class StandardAutoscaler:
    def __init__(self, config: Dict[str, Any], provider: NodeProvider,
                 control_client):
        """config (reference: cluster YAML schema subset):
        {"max_workers": int, "idle_timeout_minutes": float,
         "available_node_types": {name: {"resources": {...},
                                         "node_config": {...},
                                         "min_workers": int,
                                         "max_workers": int}}}
        """
        self.config = config
        self.provider = provider
        self.load_metrics = LoadMetrics(control_client)
        self.scheduler = ResourceDemandScheduler(
            config["available_node_types"],
            config.get("max_workers", 8))
        self.idle_timeout_s = config.get("idle_timeout_minutes", 5) * 60
        #: provider node id -> control-plane node id (filled as they join)
        self.num_launches = 0
        self.num_terminations = 0

    def _workers_by_type(self) -> Dict[str, List[str]]:
        """One entry per SCHEDULABLE UNIT: a TPU slice's host nodes
        collapse to one representative (the type's resources describe
        the whole slice, terminate_node releases the whole slice) — so
        max_workers/min_workers count slices, not hosts, and idle
        scale-down can't shave a slice below usability."""
        out: Dict[str, List[str]] = {}
        seen_units = set()
        for nid in self.provider.non_terminated_nodes(
                {TAG_NODE_KIND: "worker"}):
            tags = self.provider.node_tags(nid)
            unit = tags.get("tpu-slice", nid)
            if unit in seen_units:
                continue
            seen_units.add(unit)
            out.setdefault(tags.get(TAG_NODE_TYPE, "?"), []).append(nid)
        return out

    def update(self):
        """One reconcile tick (reference: StandardAutoscaler.update)."""
        snapshot = self.load_metrics.snapshot()
        by_type = self._workers_by_type()
        current_counts = {t: len(v) for t, v in by_type.items()}

        # 1. enforce min_workers
        for tname, tcfg in self.config["available_node_types"].items():
            deficit = tcfg.get("min_workers", 0) \
                - current_counts.get(tname, 0)
            if deficit > 0:
                self._launch(tname, deficit)
                current_counts[tname] = current_counts.get(tname, 0) \
                    + deficit

        # 2. demand-driven scale up
        to_launch = self.scheduler.get_nodes_to_launch(
            snapshot, current_counts)
        for tname, count in to_launch.items():
            self._launch(tname, count)

        # 3. idle scale down (never below min_workers)
        if not snapshot["demands"]:
            idle_s = snapshot["idle_s"]
            # provider ids whose control node ids are idle: match by the
            # provider-visible control node id tag when available
            for tname, nodes in self._workers_by_type().items():
                tcfg = self.config["available_node_types"][tname]
                removable = len(nodes) - tcfg.get("min_workers", 0)
                if removable <= 0:
                    continue
                for pid in nodes:
                    if removable <= 0:
                        break
                    if self._unit_idle_s(pid, idle_s) > self.idle_timeout_s:
                        logger.info("terminating idle node %s", pid)
                        self.provider.terminate_node(pid)
                        self.num_terminations += 1
                        removable -= 1

    def _unit_idle_s(self, pid: str, idle_s: Dict[str, float]) -> float:
        """Idle seconds of the SCHEDULABLE UNIT pid represents.  For a
        TPU slice that is the LEAST idle of all its host nodes —
        terminate_node releases the whole slice, so judging it by one
        representative would kill work running on a peer host."""
        tags = self.provider.node_tags(pid)
        slice_name = tags.get("tpu-slice")
        if not slice_name:
            ctrl = tags.get("control-node-id", pid)
            return idle_s.get(ctrl, 0.0)
        vals = []
        for peer in self.provider.non_terminated_nodes(
                {"tpu-slice": slice_name}):
            ctrl = self.provider.node_tags(peer).get(
                "control-node-id", peer)
            vals.append(idle_s.get(ctrl, 0.0))
        return min(vals) if vals else 0.0

    def _launch(self, type_name: str, count: int):
        tcfg = self.config["available_node_types"][type_name]
        logger.info("launching %d x %s", count, type_name)
        node_config = dict(tcfg.get("node_config", {}))
        node_config.setdefault("resources", tcfg.get("resources", {}))
        created = self.provider.create_node(
            node_config,
            {TAG_NODE_KIND: "worker", TAG_NODE_TYPE: type_name,
             TAG_NODE_STATUS: "pending"},
            count)
        self.num_launches += len(created)


class Monitor:
    """The autoscaler driver loop (reference: monitor.py)."""

    def __init__(self, autoscaler: StandardAutoscaler,
                 interval_s: float = 5.0):
        self.autoscaler = autoscaler
        self.interval_s = interval_s
        self._stop = False

    def run(self, max_ticks: Optional[int] = None):
        ticks = 0
        while not self._stop:
            try:
                self.autoscaler.update()
            except Exception:
                logger.exception("autoscaler tick failed")
            ticks += 1
            if max_ticks is not None and ticks >= max_ticks:
                return
            time.sleep(self.interval_s)

    def stop(self):
        self._stop = True
