"""``python -m ray_tpu`` -> the CLI (reference: the `ray` console script)."""

from ray_tpu.scripts.cli import main

main()
