"""ray_tpu: a TPU-native distributed compute framework.

Capability-parity redesign of the reference (Ray v2.38-class: tasks, actors,
objects, placement groups, collectives, Data/Train/Tune/Serve) built
TPU-first: device objects are jax.Arrays, collectives compile to XLA ICI
operations via shard_map/pjit, the scheduler is TPU-pod-topology aware, and
DP/FSDP/TP/PP/EP/SP parallelism is first-class.

Public core API mirrors the reference's (reference:
python/ray/_private/worker.py — init :1260, get :2617, put :2785,
wait :2850, remote :3239).
"""

from __future__ import annotations

import atexit
import logging
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ._private import common as _common
from ._private.api import (ActorClass, ActorHandle, RemoteFunction, get_actor,
                           kill, remote)
from ._private.common import (ActorDiedError, GetTimeoutError, ObjectLostError,
                              RayTpuError, TaskCancelledError, TaskError,
                              WorkerCrashedError)
from ._private.core import CoreWorker, ObjectRef, ObjectRefGenerator

__version__ = "0.1.0"

logger = logging.getLogger(__name__)

_lock = threading.RLock()
_core: Optional[CoreWorker] = None
_owned_cluster = None


def is_initialized() -> bool:
    return _core is not None


def init(address: Optional[str] = None, *, num_cpus: Optional[float] = None,
         num_tpus: Optional[float] = None,
         resources: Optional[Dict[str, float]] = None,
         namespace: Optional[str] = None,
         ignore_reinit_error: bool = False,
         log_to_driver: bool = True,
         _tracing_startup_hook: Optional[str] = None,
         _tracing_config: Optional[Dict[str, Any]] = None,
         _system_config: Optional[Dict[str, Any]] = None,
         logging_level: int = logging.INFO) -> Dict[str, Any]:
    """Start (or connect to) a ray_tpu cluster and connect this driver.

    With no address, boots a local single-node cluster: a control-plane
    process and one raylet (reference: ray.init starting a head node,
    worker.py:1260).
    """
    global _core, _owned_cluster
    with _lock:
        if _core is not None:
            if ignore_reinit_error:
                return connection_info()
            raise RuntimeError("ray_tpu.init() called twice; use "
                               "ignore_reinit_error=True to allow")
        if _system_config:
            # typed flag overrides, inherited by every daemon this init
            # spawns (reference: _system_config through ray.init)
            from ._private.config import set_system_config

            set_system_config(_system_config)
        if address is None and os.environ.get("RAY_TPU_ADDRESS"):
            address = os.environ["RAY_TPU_ADDRESS"]
        if address and address.startswith("ray-tpu://"):
            # remote-driver client mode (reference: ray.init("ray://...")
            # through python/ray/util/client/)
            from ._private import core as core_mod
            from .util.client import ClientCore

            cc = ClientCore(address)
            _core = cc
            core_mod._current_core = cc
            atexit.register(shutdown)
            return {"control_address": "%s:%s" % cc._server_control_addr,
                    "job_id": cc.job_id, "client": True}
        if address == "auto":
            # connect to the CLI-started cluster (reference: address="auto"
            # reading /tmp/ray/ray_current_cluster)
            from .scripts.cli import read_cluster_file

            info = read_cluster_file()
            if info is None:
                raise ConnectionError(
                    "address='auto' but no running cluster found "
                    "(start one with `python -m ray_tpu start --head`)")
            address = info["control_address"]
        if address is None:
            from ._private import bootstrap

            cluster, node = bootstrap.start_local(num_cpus=num_cpus,
                                                  num_tpus=num_tpus,
                                                  resources=resources)
            _owned_cluster = cluster
            control_addr = cluster.control_addr
            raylet_addr = node.addr
        else:
            host, port = address.rsplit(":", 1)
            control_addr = (host, int(port))
            raylet_addr = None
        # find the local raylet & its store
        from ._private.protocol import Client

        node_id = None
        store_root = None
        if raylet_addr is None:
            probe = Client(control_addr, name="init-probe")
            nodes = probe.call("get_nodes", timeout=30.0)
            probe.close()
            alive = [n for n in nodes if n["state"] == "ALIVE"]
            if alive:
                raylet_addr = tuple(alive[0]["addr"])
        if raylet_addr is not None:
            probe = Client(raylet_addr, name="init-probe-raylet")
            info = probe.call("node_info", timeout=30.0)
            probe.close()
            node_id = info["node_id"]
            if os.path.isdir(info["store_root"]):
                store_root = info["store_root"]
        _core = CoreWorker(control_addr, raylet_addr, mode="driver",
                           namespace=namespace, log_to_driver=log_to_driver,
                           node_id=node_id, store_root=store_root)
        atexit.register(shutdown)
        # metrics created before a previous shutdown() flush again
        _metrics = sys.modules.get("ray_tpu.util.metrics")
        if _metrics is not None:
            _metrics._registry.restart_if_needed()
        if _tracing_startup_hook:
            # run locally + register in KV so every worker applies it
            # (reference: ray.init(_tracing_startup_hook=...))
            from .util import tracing as _tracing

            _tracing.run_hook(_tracing_startup_hook, _tracing_config)
            _tracing.register_hook(_core.control, _tracing_startup_hook,
                                   _tracing_config)
            # the hook may have just enabled tracing — attach the span
            # collector the CoreWorker init skipped while it was off
            _tracing.ensure_collector(_core.control, proc="driver",
                                      worker_id=_core.worker_id,
                                      node_id=_core.node_id or "",
                                      job_id=_core.job_id)
        return connection_info()


def connection_info() -> Dict[str, Any]:
    core = _require()
    return {
        "control_address": f"{core.control.addr[0]}:{core.control.addr[1]}",
        "node_id": core.node_id,
        "job_id": core.job_id,
    }


def shutdown() -> None:
    global _core, _owned_cluster
    with _lock:
        core, _core = _core, None
        cluster, _owned_cluster = _owned_cluster, None
    if core is not None:
        core.shutdown()
        from ._private import core as core_mod

        if core_mod._current_core is core:
            core_mod._current_core = None
    # the metrics flusher must stop AT shutdown, not race it (it would
    # otherwise wake after teardown and trip on the dead core)
    _metrics = sys.modules.get("ray_tpu.util.metrics")
    if _metrics is not None:
        _metrics._registry.stop()
    if cluster is not None:
        cluster.shutdown()


def _require() -> CoreWorker:
    if _core is not None:
        return _core
    # inside a worker process the CoreWorker registers itself globally
    from ._private.core import current_core

    return current_core()


def put(value: Any) -> ObjectRef:
    return _require().put(value)


def get(refs, timeout: Optional[float] = None):
    return _require().get(refs, timeout=timeout)


def wait(refs: Sequence[ObjectRef], *, num_returns: int = 1,
         timeout: Optional[float] = None):
    return _require().wait(refs, num_returns=num_returns, timeout=timeout)


def cancel(ref: "ObjectRef", *, force: bool = False,
           recursive: bool = True) -> bool:
    """Cancel the task that produces `ref` (reference: ray.cancel —
    recursive defaults to True there too).  Works for normal AND actor
    tasks: queued tasks are dropped; running ones get TaskCancelledError
    injected (async actor methods get their coroutine cancelled).
    force=True kills the worker process (normal tasks only).
    recursive=True also cancels the tasks the cancelled task submitted.
    Getting the ref afterwards raises TaskCancelledError.  Cancelled
    tasks never retry."""
    return _require().cancel(ref, force=force, recursive=recursive)


def cluster_resources() -> Dict[str, float]:
    return _require().control.call("cluster_resources", {})["total"]


def available_resources() -> Dict[str, float]:
    return _require().control.call("cluster_resources", {})["available"]


def nodes() -> List[Dict[str, Any]]:
    return _require().control.call("get_nodes", {})


def timeline(filename: Optional[str] = None) -> Optional[str]:
    """Export the task timeline as Chrome trace JSON (reference:
    ray.timeline, python/ray/_private/worker.py)."""
    from .util.state import timeline as _timeline

    _require().task_events.flush()
    return _timeline(filename)


class profile:
    """Span context manager feeding the timeline (reference:
    ray._private.profiling / TaskEventBuffer profile events)."""

    def __init__(self, event_name: str, task_id: str = ""):
        self._name = event_name
        self._task_id = task_id

    def __enter__(self):
        self._t0 = time.time()
        return self

    def __exit__(self, *exc):
        core = _require()
        if core is not None:
            core.task_events.record_profile(
                self._task_id, self._name, self._t0, time.time())
        return False


__all__ = [
    "init", "shutdown", "is_initialized", "put", "get", "wait", "remote",
    "kill", "cancel", "get_actor", "cluster_resources",
    "available_resources", "nodes", "timeline", "profile",
    "ObjectRef", "ObjectRefGenerator", "ActorHandle", "ActorClass",
    "RemoteFunction",
    "RayTpuError", "TaskError", "ActorDiedError", "WorkerCrashedError",
    "ObjectLostError", "GetTimeoutError", "TaskCancelledError",
]
