"""Scheme-dispatched file IO shared by data datasources and train storage.

The reference resolves every dataset/checkpoint path through pyarrow.fs so
s3://, gs://, hdfs:// work anywhere a worker runs (reference:
python/ray/data/datasource/file_based_datasource.py:65,
python/ray/train/_internal/storage.py:358).  Here the abstraction is
fsspec: a path either has a URI scheme (routed through the fsspec
filesystem for that scheme) or is a plain local path (plain os fast path).

This matters doubly on TPU pods: pod hosts share NO local disk, so the
remote filesystem is the only path training data and checkpoints can
actually travel through.

A `mock-remote://` scheme is registered for tests: it exercises the full
remote code path (every byte moves through the fsspec AbstractFileSystem
API — no os.path shortcuts) while persisting under a plain directory the
test can inspect out-of-band.  Code proven against it holds for any real
scheme (s3/gs via their fsspec drivers).
"""

from __future__ import annotations

import glob as _glob
import os
import threading
from typing import List, Optional

__all__ = [
    "is_uri", "fs_for", "open_file", "filesize", "exists", "makedirs",
    "expand_paths", "register_mock_remote",
]


def is_uri(path: str) -> bool:
    return "://" in (path or "")


_mock_registered = False
_reg_lock = threading.Lock()


def register_mock_remote() -> None:
    """Register the test/dev `mock-remote://` scheme (idempotent)."""
    global _mock_registered
    with _reg_lock:
        if _mock_registered:
            return
        import fsspec
        from fsspec.implementations.local import LocalFileSystem

        class MockRemoteFileSystem(LocalFileSystem):
            protocol = "mock-remote"

            def __init__(self, **kw):
                kw.pop("auto_mkdir", None)
                super().__init__(auto_mkdir=True, **kw)

            @classmethod
            def _strip_protocol(cls, path):
                path = str(path)
                if path.startswith("mock-remote://"):
                    path = path[len("mock-remote://"):]
                return LocalFileSystem._strip_protocol(path)

            def unstrip_protocol(self, name):
                return "mock-remote://" + str(name)

        try:
            fsspec.register_implementation("mock-remote",
                                           MockRemoteFileSystem,
                                           clobber=True)
        except Exception:
            pass
        _mock_registered = True


def fs_for(uri: str):
    """fsspec filesystem + in-fs path for a URI."""
    import fsspec

    register_mock_remote()
    return fsspec.core.url_to_fs(uri)


def _unstrip(fs, path: str) -> str:
    """Reattach the scheme so worker tasks re-resolve the same fs."""
    return fs.unstrip_protocol(path)


def open_file(path: str, mode: str = "rb"):
    """Open a local path or URI; returns a context-manager file object.

    Worker tasks call this inside read/write thunks: the fs is resolved
    on the worker from the scheme, so no filesystem object travels in the
    pickled closure.
    """
    if is_uri(path):
        fs, p = fs_for(path)
        if "w" in mode or "a" in mode:
            parent = p.rsplit("/", 1)[0]
            if parent:
                fs.makedirs(parent, exist_ok=True)
        return fs.open(p, mode)
    return open(path, mode)


def filesize(path: str) -> Optional[int]:
    try:
        if is_uri(path):
            fs, p = fs_for(path)
            return int(fs.size(p))
        return os.path.getsize(path)
    except Exception:
        return None


def exists(path: str) -> bool:
    if is_uri(path):
        fs, p = fs_for(path)
        return fs.exists(p)
    return os.path.exists(path)


def makedirs(path: str) -> None:
    if is_uri(path):
        fs, p = fs_for(path)
        fs.makedirs(p, exist_ok=True)
    else:
        os.makedirs(path, exist_ok=True)


def expand_paths(paths, suffixes: Optional[List[str]] = None) -> List[str]:
    """Expand dirs (recursive) and globs into concrete file paths, local
    or remote (reference: file_based_datasource.py path resolution —
    dirs list recursively, `*?[` trigger glob, plain paths pass through).
    Remote results keep their scheme so read tasks re-resolve on workers.
    """
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if is_uri(p):
            fs, fp = fs_for(p)
            if any(ch in fp for ch in "*?["):
                found = sorted(fs.glob(fp))
            elif fs.isdir(fp):
                found = sorted(fs.find(fp))
            else:
                found = [fp]
            out.extend(_unstrip(fs, f) for f in found)
        elif os.path.isdir(p):
            for root, _, files in os.walk(p):
                for f in sorted(files):
                    out.append(os.path.join(root, f))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(_glob.glob(p)))
        else:
            out.append(p)
    if suffixes:
        out = [p for p in out
               if any(p.endswith(s) for s in suffixes)] or out
    if not out:
        raise FileNotFoundError(f"no input files found for {paths!r}")
    return out
