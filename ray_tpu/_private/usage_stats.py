"""Usage stats: opt-out feature-usage telemetry, local-only.

Reference parity: python/ray/_private/usage/usage_lib.py — Ray records
which libraries/features a cluster used and (unless opted out) reports
them.  Here collection is the same shape — feature tags + library usage
counters in the control-plane KV — but nothing ever leaves the cluster:
the "report" is a JSON blob readable via the dashboard
(``/api/usage_stats``) or :func:`usage_report`.  Opt out entirely with
``RAY_TPU_USAGE_STATS_ENABLED=0`` (reference env:
RAY_USAGE_STATS_ENABLED).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict

USAGE_NS = "__usage_stats__"


def enabled() -> bool:
    from .config import cfg

    return cfg().usage_stats_enabled


def _core():
    from .core import current_core

    return current_core()


def record_library_usage(library: str) -> None:
    """Tag a library as used (reference: record_library_usage) — called
    from library entry points (serve.start, Tuner.fit, ...)."""
    record_extra_usage_tag(f"library_{library}", "1")


def record_extra_usage_tag(key: str, value: str) -> None:
    """Best-effort write-through of a usage tag to the control KV
    (reference: TagKey + record_extra_usage_tag)."""
    if not enabled():
        return
    try:
        core = _core()
        core.control.call("kv_put", {
            "ns": USAGE_NS, "key": key,
            "val": json.dumps({"value": value, "ts": time.time()}).encode(),
            "overwrite": True,
        }, timeout=5.0)
    except Exception:
        pass  # telemetry must never break the caller


def usage_report(control_client=None) -> Dict[str, Any]:
    """Aggregate recorded tags into one report blob."""
    try:
        cli = control_client or _core().control
        keys = cli.call("kv_keys", {"ns": USAGE_NS, "prefix": ""},
                        timeout=5.0) or []
        tags = {}
        for k in keys:
            raw = cli.call("kv_get", {"ns": USAGE_NS, "key": k},
                           timeout=5.0)
            if raw:
                tags[k] = json.loads(raw)
        return {"usage_stats_enabled": enabled(), "tags": tags,
                "collected_at": time.time()}
    except Exception as e:
        return {"usage_stats_enabled": enabled(), "error": str(e)}
