"""Common types: IDs, task specs, resource math, serialization helpers.

TPU-native re-design of the reference's `src/ray/common/` (id.h,
task/task_spec.h, scheduling/).  IDs are random 16-byte values rendered as
hex; object ids are derived from (owner task id, return index) the same way
the reference derives ObjectIDs from TaskIDs
(reference: src/ray/design_docs/id_specification.md).
"""

from __future__ import annotations

import os
import pickle
import threading
from dataclasses import dataclass, field, fields as dataclass_fields
from typing import Any, Dict, List, Optional, Tuple

import cloudpickle

_pid_rand = None


def _rand_bytes(n: int) -> bytes:
    # os.urandom is fork-safe and fast enough for id generation.
    return os.urandom(n)


# ids only need cross-process uniqueness, not cryptographic strength: an
# 8-byte urandom prefix drawn once per process + a 16-hex-digit counter is
# collision-safe and ~50x cheaper than os.urandom per id (the task-submit
# hot path mints 2 ids per task).  Fork safety comes from an at-fork hook
# rather than a getpid() check per id — getpid is a real syscall on
# sandboxed kernels and was the single hottest line of task submission.
_id_state = None


def _reset_id_state():
    global _id_state
    _id_state = None


if hasattr(os, "register_at_fork"):
    os.register_at_fork(after_in_child=_reset_id_state)


def new_id(prefix: str = "") -> str:
    global _id_state
    st = _id_state
    if st is None:
        import itertools

        _id_state = st = (os.urandom(8).hex(), itertools.count(1))
    # itertools.count.__next__ is atomic in CPython: thread-safe ids
    return f"{prefix}{st[0]}{next(st[1]):016x}"


def job_id() -> str:
    return new_id("job-")


def node_id() -> str:
    return new_id("node-")


def worker_id() -> str:
    return new_id("wkr-")


def actor_id() -> str:
    return new_id("act-")


def task_id() -> str:
    return new_id("tsk-")


def placement_group_id() -> str:
    return new_id("pg-")


def object_id_for_return(tid: str, index: int) -> str:
    """Derive object id from creating task id + return index (lineage key)."""
    return f"obj-{tid[4:]}-{index}"


def put_object_id(owner_worker_id: str, seq: int) -> str:
    return f"obj-put-{owner_worker_id[4:]}-{seq}"


# ---------------------------------------------------------------------------
# Resources
# ---------------------------------------------------------------------------

CPU = "CPU"
TPU = "TPU"
MEM = "memory"
# Granularity for fractional resources (reference uses 1e-4 fixed point).
_GRAN = 10000


def normalize_resources(res: Optional[Dict[str, float]]) -> Dict[str, int]:
    """To fixed-point ints to avoid float drift in accounting."""
    out: Dict[str, int] = {}
    for k, v in (res or {}).items():
        iv = int(round(float(v) * _GRAN))
        if iv < 0:
            raise ValueError(f"resource {k} negative: {v}")
        if iv > 0:
            out[k] = iv
    return out


def denormalize_resources(res: Dict[str, int]) -> Dict[str, float]:
    return {k: v / _GRAN for k, v in res.items()}


def fits(avail: Dict[str, int], demand: Dict[str, int]) -> bool:
    return all(avail.get(k, 0) >= v for k, v in demand.items())


def subtract(avail: Dict[str, int], demand: Dict[str, int]) -> None:
    for k, v in demand.items():
        avail[k] = avail.get(k, 0) - v


def add(avail: Dict[str, int], demand: Dict[str, int]) -> None:
    for k, v in demand.items():
        avail[k] = avail.get(k, 0) + v


# ---------------------------------------------------------------------------
# Task / actor specs
# ---------------------------------------------------------------------------

# Objects smaller than this are owner-held / inlined in messages; larger go to
# the node shared-memory store (reference: max_direct_call_object_size,
# ray_config_def.h).
INLINE_OBJECT_LIMIT = 100 * 1024


@dataclass
class FunctionDescriptor:
    function_id: str          # content hash of the pickled callable
    name: str                 # qualname, for errors/observability
    blob: Optional[bytes]     # pickled callable; None once registered


@dataclass
class TaskSpec:
    task_id: str
    function_id: str
    function_name: str
    # args/kwargs with ObjectRefs replaced by ("__ref__", object_id) markers;
    # pickled by cloudpickle.  Inline values embedded directly.
    args_blob: bytes
    num_returns: int = 1
    resources: Dict[str, int] = field(default_factory=dict)
    max_retries: int = 3
    retry_exceptions: bool = False
    # actor task fields
    actor_id: Optional[str] = None
    seq_no: int = -1
    # actor creation fields
    is_actor_creation: bool = False
    max_restarts: int = 0
    max_concurrency: int = 1
    # placement
    placement_group_id: Optional[str] = None
    placement_bundle_index: int = -1
    scheduling_strategy: Optional[Any] = None
    owner_id: str = ""
    owner_addr: Optional[Tuple[str, int]] = None
    # task that submitted this one (same owner process), for
    # ray.cancel(recursive=True) child propagation
    parent_task_id: Optional[str] = None
    # owning driver job — workers emit it as a log marker so worker
    # stdout can be routed to the right driver (log_monitor.py)
    job_id: str = ""
    # OTel span context carrier (util/tracing.py; reference
    # tracing_helper.py propagates the submit span to the executor)
    trace_ctx: Optional[Dict[str, str]] = None
    # runtime env (env vars, working dir); materialized by the worker
    runtime_env: Optional[Dict[str, Any]] = None
    name: str = ""
    # streaming generators: max unconsumed items before the producer
    # pauses (0 = unbounded; reference _generator_backpressure_num_objects)
    generator_backpressure: int = 0

    def return_ids(self) -> List[str]:
        if self.num_returns == STREAMING_RETURNS:
            return []
        return [object_id_for_return(self.task_id, i) for i in range(self.num_returns)]

    def __reduce__(self):
        # positional-tuple pickling: specs cross the wire once per task,
        # and the default dataclass reduce re-pickles all 20+ field-name
        # strings in every frame
        return (TaskSpec, tuple(getattr(self, n) for n in _SPEC_FIELDS))


# num_returns sentinel for streaming-generator tasks (reference:
# num_returns="streaming" -> ObjectRefGenerator, _raylet.pyx:281)
STREAMING_RETURNS = -1

_SPEC_FIELDS = tuple(f.name for f in dataclass_fields(TaskSpec))


class SerializedRef:
    """Marker for an ObjectRef inside pickled task args / objects.

    Carries enough to reconstruct a borrower-side ObjectRef: id, owner
    address (to fetch / send ref-count messages) and the node hint.
    """

    __slots__ = ("object_id", "owner_addr", "owner_id")

    def __init__(self, object_id: str, owner_addr, owner_id: str):
        self.object_id = object_id
        self.owner_addr = owner_addr
        self.owner_id = owner_id

    def __reduce__(self):
        return (SerializedRef, (self.object_id, self.owner_addr, self.owner_id))


_by_value_registered: set = set()


def _ensure_picklable_by_value(obj: Any) -> None:
    """User-code modules (anything outside the interpreter installation) are
    pickled by value so workers don't need the driver's sys.path — the
    equivalent of the reference exporting functions through the GCS function
    table regardless of importability."""
    import sys

    mod_name = getattr(obj, "__module__", None)
    if not mod_name or mod_name in _by_value_registered:
        return
    if mod_name == "ray_tpu" or mod_name.startswith("ray_tpu."):
        return  # framework code is importable everywhere
    mod = sys.modules.get(mod_name)
    if mod is None or mod_name == "__main__":
        return  # cloudpickle already handles __main__ by value
    mod_file = getattr(mod, "__file__", None)
    if mod_file is None:
        return
    prefix_paths = (sys.prefix, sys.base_prefix)
    if any(mod_file.startswith(p) for p in prefix_paths):
        return  # installed library: importable on workers, keep by-reference
    try:
        cloudpickle.register_pickle_by_value(mod)
        _by_value_registered.add(mod_name)
    except Exception:
        pass


def hash_function(fn: Any) -> Tuple[str, bytes]:
    _ensure_picklable_by_value(fn)
    blob = cloudpickle.dumps(fn)
    import hashlib

    return "fn-" + hashlib.sha1(blob).hexdigest(), blob


class RayTpuError(Exception):
    pass


class TaskError(RayTpuError):
    """Wraps an exception raised inside a remote task (cause + traceback)."""

    def __init__(self, cause: BaseException, tb: str, task_name: str = ""):
        self.cause = cause
        self.tb = tb
        self.task_name = task_name
        super().__init__(f"task {task_name!r} failed: {cause!r}\n{tb}")

    def __reduce__(self):
        return (TaskError, (self.cause, self.tb, self.task_name))


class WorkerCrashedError(RayTpuError):
    pass


class ActorDiedError(RayTpuError):
    pass


class ObjectLostError(RayTpuError):
    pass


class TaskCancelledError(RayTpuError):
    """The task was cancelled via ray_tpu.cancel() (reference:
    ray.exceptions.TaskCancelledError)."""


class GetTimeoutError(RayTpuError, TimeoutError):
    pass


# -- control-plane rendezvous file (failover re-homing) ----------------------
# One format, one reader, one writer: control.py publishes, raylets /
# workers / drivers re-resolve.  rsplit tolerates IPv6-ish hosts.

def write_addr_file(path: str, addr: Tuple[str, int]) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(f"{addr[0]}:{addr[1]}")
    os.replace(tmp, path)    # atomic: readers see old or new, never half


def read_addr_file(path: Optional[str]) -> Optional[Tuple[str, int]]:
    if not path:
        return None
    try:
        with open(path) as f:
            host, port = f.read().strip().rsplit(":", 1)
        return (host, int(port))
    except Exception:
        return None
