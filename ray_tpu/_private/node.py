"""Raylet: the per-node daemon.

TPU-native analog of the reference's NodeManager
(reference: src/ray/raylet/node_manager.cc:101): owns the worker pool
(worker_pool.h:366 PopWorker + startup-token protocol), lease-based local
scheduling (local_task_manager.h:58), placement-group bundle 2-phase commit
(placement_group_resource_manager.h:54-61), the node object store (shm_store),
and node-to-node object transfer (object_manager.proto:61 Push/Pull).

Deadlock avoidance for nested tasks: a worker blocked in `get` notifies the
raylet (task_blocked), which releases its CPUs so queued leases can be granted
— possibly by spawning extra workers (the reference does the same when
workers block in ray.get).
"""

from __future__ import annotations

import argparse
import contextlib
import logging
import os
import shutil
import subprocess
import sys
import threading
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Set, Tuple

from . import accelerators, common
from .common import add, fits, normalize_resources, subtract
from .protocol import (Backoff, Client, ConnectionLost, Deferred, Server,
                       ServerConn)
from .shm_store import ShmObjectStore

logger = logging.getLogger(__name__)

LEASE_GRANT_TICK_S = 0.01
WORKER_SPAWN_HARD_CAP_FACTOR = 10
# submit multiplexer: how recently a client must have submitted to count
# as a concurrent submitter, and how long a relay worker may sit idle
# before it returns to the shared pool
MUX_WINDOW_S = 10.0
MUX_IDLE_RELEASE_S = 1.0
MUX_CLIENT_ID = "__mux__"


class WorkerRecord:
    def __init__(self, worker_id: str, proc: Optional[subprocess.Popen], token: int):
        self.worker_id = worker_id
        self.proc = proc
        self.token = token
        self.addr: Optional[Tuple[str, int]] = None
        self.conn: Optional[ServerConn] = None
        self.state = "starting"  # starting | idle | leased | actor | dead
        self.leased_at = 0.0
        self.actor_id: Optional[str] = None
        self.lease_id: Optional[str] = None
        self.blocked = False
        self.lease_resources: Dict[str, int] = {}
        self.lease_retriable = True  # OOM-victim hint from the owner
        self.lease_client_id: Optional[str] = None  # whose core holds us
        self.bundle_key: Optional[Tuple[str, int]] = None
        self.bundle_demand: Dict[str, int] = {}  # PG actors: placed demand
        self.lent: Dict[str, int] = {}  # CPUs lent to the pool while blocked
        self.tpu = False  # spawned with TPU device visibility
        self.incarnation = 0  # actor incarnation this worker hosts


class PendingLease:
    def __init__(self, demand: Dict[str, int], deferred: Deferred, client_id: str,
                 bundle: Optional[Tuple[str, int]] = None,
                 retriable: bool = True, count: int = 1,
                 vector: bool = False):
        self.demand = demand
        self.deferred = deferred
        self.client_id = client_id
        self.bundle = bundle
        self.retriable = retriable
        self.count = count    # copies of `demand` wanted in one grant
        self.vector = vector  # reply shape: {"grants": [...]} vs single
        self.ts = time.monotonic()


class Raylet:
    def __init__(self, control_addr: Tuple[str, int], host: str = "127.0.0.1",
                 port: int = 0, resources: Optional[Dict[str, float]] = None,
                 session_dir: Optional[str] = None, labels: Optional[Dict[str, str]] = None,
                 node_id: Optional[str] = None,
                 control_addr_file: Optional[str] = None):
        self.node_id = node_id or common.node_id()
        self.control_addr = tuple(control_addr)
        self.control_addr_file = control_addr_file \
            or os.environ.get("RAY_TPU_CONTROL_ADDR_FILE")
        self.server = Server(host, port, name="raylet")
        self.session_dir = session_dir or f"/dev/shm/ray_tpu/{self.node_id}"
        self.store = ShmObjectStore(os.path.join(self.session_dir, "objects"))
        res = resources if resources is not None else accelerators.default_resources()
        self.total = normalize_resources(res)
        self.available = dict(self.total)
        self.labels = {**accelerators.tpu_labels(), **(labels or {})}
        self.lock = threading.RLock()
        self.workers: Dict[str, WorkerRecord] = {}
        self.workers_by_token: Dict[int, WorkerRecord] = {}
        self.idle: Deque[WorkerRecord] = deque()
        self.pending_leases: Deque[PendingLease] = deque()
        # lessee core conns, for on-demand idle-lease reclaim pushes
        self.client_conns: Dict[str, Any] = {}
        self._last_reclaim_push = 0.0
        # multi-client submit multiplexer (relay): once >=2 distinct
        # external clients submit within MUX_WINDOW_S, eligible plain
        # tasks arrive as framed mux_push_tasks notifies and are
        # scheduled HERE against the shared worker pool — N drivers stop
        # holding N separate pick_nodes/request_leases conversations.
        from .config import cfg as _mcfg

        self.mux_enabled = bool(_mcfg().submit_mux)
        self.mux_on = False                        # guarded-by: lock
        # FIFO of (client_id, spec) awaiting a worker slot
        self.mux_queue: Deque[Tuple[str, Any]] = deque()  # guarded-by: lock
        # wid -> {"rec", "inflight": {tid: (cid, spec)}, "idle_since"}
        self.mux_workers: Dict[str, Dict[str, Any]] = {}  # guarded-by: lock
        self.mux_seen: Dict[str, float] = {}       # guarded-by: lock
        self.mux_avg_ms: Optional[float] = None    # guarded-by: lock
        self.mux_stats = {"submitted": 0, "completed": 0,  # guarded-by: lock
                          "failed": 0, "released": 0}
        self.bundles: Dict[Tuple[str, int], Dict[str, Any]] = {}  # (pg,idx)->{resources,state}
        self._next_token = 0
        self._stop = threading.Event()
        self._reconnecting = threading.Semaphore(1)
        self._resurrect_lock = threading.Lock()
        self._registered_at = 0.0
        self.control: Optional[Client] = None
        self.peer_clients: Dict[Tuple[str, int], Client] = {}
        self.max_workers = max(
            1, int(sum(v for k, v in self.total.items() if k == common.CPU) / common._GRAN)
        ) * WORKER_SPAWN_HARD_CAP_FACTOR

        s = self.server
        s.handle("ping", lambda c, p: "pong")
        s.handle("register_worker", self.h_register_worker)
        s.handle("request_lease", self.h_request_lease, deferred=True)
        s.handle("request_leases", self.h_request_leases, deferred=True)
        s.handle("return_lease", self.h_return_lease)
        s.handle("cancel_lease_requests", self.h_cancel_lease_requests)
        s.handle("task_blocked", self.h_task_blocked)
        s.handle("task_unblocked", self.h_task_unblocked)
        s.handle("start_actor_worker", self.h_start_actor_worker, deferred=True)
        s.handle("kill_actor_worker", self.h_kill_actor_worker)
        s.handle("prepare_bundle", self.h_prepare_bundle)
        s.handle("commit_bundle", self.h_commit_bundle)
        s.handle("release_bundle", self.h_release_bundle)
        s.handle("fetch_object", self.h_fetch_object)
        s.handle("pull_object", self.h_pull_object, deferred=True)
        s.handle("delete_objects", self.h_delete_objects)
        s.handle("store_stats", self.h_store_stats)
        s.handle("node_info", self.h_node_info)
        s.handle("list_leases", self.h_list_leases)
        s.handle("list_workers", self.h_list_workers)
        s.handle("list_logs", self.h_list_logs)
        s.handle("read_log", self.h_read_log)
        s.handle("pending_demands", self.h_pending_demands)
        s.handle("report_task_events", self.h_report_task_events)
        s.handle("mux_push_tasks", self.h_mux_push_tasks)
        s.handle("mux_tasks_done", self.h_mux_tasks_done)
        s.handle("mux_cancel", self.h_mux_cancel)
        s.on_disconnect(self.h_disconnect)

        # node-local task-event relay (ROADMAP item 5 "per-node batching
        # of task events"): workers flush their task-event batches to
        # THIS raylet over their existing socket; a relay loop coalesces
        # every batch from the flush window into ONE framed pipe write
        # to the control.  N workers/node no longer means N control
        # writes per flush interval.  Bounded with drop-oldest
        # accounting — never silent loss.
        self._ev_relay: Deque[Dict[str, Any]] = deque()
        self._ev_relay_lock = threading.Lock()
        self._ev_relay_buffered = 0  # events currently buffered
        self._ev_relay_cap = 20_000  # events; overflow drops oldest batch
        self._ev_relay_pending_dropped = 0  # dropped, not yet reported
        self._ev_relay_stats = {"batches_in": 0, "events_in": 0,
                                "sends": 0, "coalesced": 0, "dropped": 0}
        self._ev_relay_thread = threading.Thread(
            target=self._task_event_relay_loop, name="raylet-task-events",
            daemon=True)

        # prestarted warm workers (reference: worker_pool.h prestart):
        # interpreter + framework import is paid once off the critical path;
        # leases and actor creations pop a warm worker
        cpu_slots = max(1, int(sum(
            v for k, v in self.total.items() if k == common.CPU)
            / common._GRAN))
        from .config import cfg as _pcfg

        self.prestart_target = min(cpu_slots, _pcfg().worker_prestart)
        self._prestart_thread = threading.Thread(
            target=self._prestart_loop, name="raylet-prestart", daemon=True)
        self._grant_thread = threading.Thread(target=self._grant_loop,
                                              name="raylet-grant", daemon=True)
        self._hb_thread = threading.Thread(target=self._heartbeat_loop,
                                           name="raylet-heartbeat", daemon=True)
        self._reap_thread = threading.Thread(target=self._reap_loop,
                                             name="raylet-reap", daemon=True)
        self._pull_pool: Dict[str, threading.Event] = {}
        #: a preemption notice was observed for THIS host: stop warming
        #: new workers; the control plane broadcasts the drain advisory
        self._draining = False
        self.preemption_watcher = None

        # object spilling + memory watchdog (reference:
        # local_object_manager.h:110, memory_monitor.h:52)
        from . import spilling

        from .config import cfg as _ncfg

        self.spill: Optional[spilling.SpillManager] = None
        if _ncfg().object_spilling:
            # spill to real disk — the session dir lives on /dev/shm, and
            # spilling tmpfs→tmpfs would free no memory.  Always suffix
            # with the node id: co-hosted raylets must not share (and on
            # shutdown rmtree) one directory.
            spill_base = os.environ.get("RAY_TPU_SPILL_DIR",
                                        "/tmp/ray_tpu_spill")
            self.spill = spilling.SpillManager(
                self.store, os.path.join(spill_base, self.node_id))
        self.oom_killer: Optional[spilling.OomKiller] = None
        if _ncfg().is_set("memory_monitor_refresh_ms"):
            refresh_ms = _ncfg().memory_monitor_refresh_ms
        else:
            # default on only inside a memory-limited cgroup, where the
            # limit is real and ours; on a shared host a high ambient
            # usage would make kills spurious
            refresh_ms = 250 if spilling._cgroup_usage() else 0
        self._mem_refresh_s = max(int(refresh_ms), 0) / 1000.0
        if self._mem_refresh_s > 0:
            self.oom_killer = spilling.OomKiller(
                self, spilling.MemoryMonitor())
        self._mem_thread = None
        if self.spill is not None or self.oom_killer is not None:
            self._mem_thread = threading.Thread(
                target=self._memory_loop, name="raylet-memory", daemon=True)

    # -- lifecycle ---------------------------------------------------------

    def start(self, block: bool = False):
        self.server.start()
        # the rendezvous file outranks the bootstrap --control address: a
        # node added AFTER a failover must join the promoted controller,
        # not the dead primary it was configured with
        file_addr = self._read_addr_file()
        if file_addr and file_addr != self.control_addr:
            logger.info("control addr-file overrides bootstrap address: "
                        "%s -> %s", self.control_addr, file_addr)
            self.control_addr = file_addr
        self.control = Client(self.control_addr, name="raylet->control",
                              on_disconnect=self._on_control_lost)
        self.control.call("register_node", {
            "node_id": self.node_id,
            "addr": self.server.addr,
            "resources": common.denormalize_resources(self.total),
            "labels": self.labels,
        }, timeout=30.0)
        self._registered_at = time.monotonic()
        # span collector: the raylet reports its relay/mux phase spans to
        # the control plane like every other traced process
        from ray_tpu.util import tracing as _tracing

        _tracing.ensure_collector(self.control,
                                  proc=f"raylet:{self.node_id[:8]}",
                                  node_id=self.node_id)
        self._grant_thread.start()
        self._hb_thread.start()
        self._reap_thread.start()
        self._prestart_thread.start()
        self._ev_relay_thread.start()
        if self._mem_thread is not None:
            self._mem_thread.start()
        # worker-log tailer -> control pubsub -> driver stderr
        # (reference: python/ray/_private/log_monitor.py)
        from .log_monitor import LogMonitor

        def _publish_logs(payload):
            cli = self.control
            if cli is not None and not cli.closed:
                try:
                    cli.notify("publish", {"topic": "worker_logs",
                                           "payload": payload})
                except Exception:
                    pass

        self.log_monitor = LogMonitor(
            os.path.join(self.session_dir, "logs"), self.node_id,
            _publish_logs)
        self.log_monitor.start()
        # preemption watcher: poll the maintenance-event source (env-
        # selected; None on hosts without one) and report a drain notice
        # to the control plane before the heartbeat timeout would fire
        from ray_tpu.elastic.preemption import (PreemptionWatcher,
                                                source_from_env)

        src = source_from_env()
        if src is not None:
            from .config import cfg as _wcfg

            self.preemption_watcher = PreemptionWatcher(
                src, self._on_preemption_notice,
                poll_interval_s=_wcfg().preemption_poll_s,
                debounce_s=_wcfg().preemption_debounce_s)
            self.preemption_watcher.start()
            logger.info("preemption watcher active (%s)",
                        type(src).__name__)
        logger.info("raylet %s up at %s resources=%s", self.node_id[:12],
                    self.server.addr, common.denormalize_resources(self.total))
        if block:
            try:
                while not self._stop.is_set():
                    time.sleep(0.5)
            except KeyboardInterrupt:
                pass
            self.shutdown()

    def _on_preemption_notice(self, notice):
        """The preemption source says this host is going away: report a
        drain notice to the control (which broadcasts the advisory) and
        stop warming new workers locally.  Best-effort — a raylet that
        can't reach the control still dies on schedule; the heartbeat
        timeout remains the backstop."""
        from .config import cfg as _wcfg

        grace = notice.grace_s if notice.grace_s is not None \
            else _wcfg().drain_grace_s
        logger.warning("preemption notice (%s): draining, grace %.1fs",
                       notice.reason, grace)
        self._draining = True
        cli = self.control
        if cli is None or cli.closed:
            return
        try:
            cli.call("report_draining", {
                "node_id": self.node_id, "grace_s": grace,
                "reason": notice.reason}, timeout=5.0)
        except Exception:
            logger.warning("could not report drain notice to control",
                           exc_info=True)

    def _on_control_lost(self):
        """Control connection dropped.  With a persistent control plane the
        daemon comes back at the same address (reference: GCS fault
        tolerance — raylets reconnect and re-sync rather than exiting);
        retry for a grace window before giving up."""
        if self._stop.is_set():
            return
        # closing a superseded client re-fires this callback: only react
        # when the *current* control client is actually down, one
        # reconnector at a time
        if self.control is not None and not self.control.closed:
            return
        if not self._reconnecting.acquire(blocking=False):
            return
        from .config import cfg

        grace = cfg().control_reconnect_s
        threading.Thread(target=self._reconnect_control, args=(grace,),
                         name="raylet-reconnect", daemon=True).start()

    def _read_addr_file(self):
        """Current control-plane address from the rendezvous file, or
        None.  A promoted standby rewrites the file (atomically) with
        its own address — re-reading it per retry is what re-homes this
        raylet across a failover."""
        return common.read_addr_file(self.control_addr_file)

    def _reconnect_control(self, grace: float):
        try:
            from .config import cfg

            deadline = time.monotonic() + grace
            # jittered exponential backoff: a cluster of raylets re-homing
            # after a control restart must not stampede it in lockstep
            bo = Backoff(cfg().rpc_backoff_base_s, cfg().rpc_backoff_cap_s)
            logger.warning("control connection lost; retrying for %.0fs",
                           grace)
            while not self._stop.is_set() and time.monotonic() < deadline:
                new_addr = self._read_addr_file()
                if new_addr and new_addr != self.control_addr:
                    logger.warning("control plane moved: %s -> %s",
                                   self.control_addr, new_addr)
                    self.control_addr = new_addr
                try:
                    cli = Client(self.control_addr, name="raylet->control",
                                 on_disconnect=self._on_control_lost,
                                 connect_timeout=2.0)
                    cli.call("ping", timeout=5.0)
                except Exception:
                    bo.sleep(max_s=max(0.0, deadline - time.monotonic()))
                    continue
                connected_at = time.monotonic()
                old, self.control = self.control, cli
                if old is not None:
                    old.close()
                # the restarted/promoted control has no node entry for
                # us: re-register, REPORTING live actor workers so the
                # control adopts them in place (state preserved) instead
                # of rescheduling; it replies with any it refuses
                self._rehome(if_stale_since=connected_at)
                logger.info("reconnected to control plane at %s",
                            self.control_addr)
                return
            if not self._stop.is_set():
                logger.warning("control did not come back within %.0fs; "
                               "shutting down raylet", grace)
                self.shutdown()
        finally:
            self._reconnecting.release()

    def _rehome(self, if_stale_since: Optional[float] = None):
        """Re-register after a control disconnect / restart / failover.

        Registration happens FIRST, reporting EVERY live actor worker —
        PG-placed ones included, tagged with their bundle.  The control's
        reply says whether it still held our node record (``resumed``):

        * resumed — transient disconnect (or failover to a standby that
          restored us): NOTHING is torn down.  PG workers, self.bundles
          and the availability books all survive; the only reconciliation
          is releasing bundles the control no longer assigns here (a
          remove_pg whose release RPC the partition ate) and reaping
          workers of rejected actors.
        * cold — the control lost our record (restart without
          persistence) or declared us dead: the clean-slate semantics.
          Live non-PG actors were offered for adoption (same incarnation,
          state preserved — the warm-standby promise) and the control
          rejected any it already rescheduled; PG-placed actors take the
          reschedule path with their group (bundle reservations re-run
          2-phase commit), so their workers are reaped and the bundle
          books wiped.  Only bundle keys snapshotted BEFORE registration
          are wiped — a bundle the control prepares concurrently with
          the cleanup must survive it.

        if_stale_since: skip if a registration already landed at/after
        this time — a second rehome racing the first would find its
        just-adopted actors ALIVE (not adoptable), get them rejected,
        and kill the workers the first rehome saved.  Checked UNDER the
        serializing lock (the check-outside variant was exactly that
        race)."""
        with self._resurrect_lock:
            if if_stale_since is not None \
                    and self._registered_at >= if_stale_since:
                return
            with self.lock:
                live = [{"actor_id": r.actor_id,
                         "incarnation": r.incarnation,
                         "worker_addr": r.addr,
                         "worker_id": r.worker_id,
                         "bundle": r.bundle_key}
                        for r in self.workers.values()
                        if r.actor_id is not None and r.state != "dead"
                        and r.addr is not None]
                bundles_before = list(self.bundles.keys())
            try:
                resp = self.control.call("register_node", {
                    "node_id": self.node_id,
                    "addr": self.server.addr,
                    "resources": common.denormalize_resources(self.total),
                    "labels": self.labels,
                    "live_actors": live,
                    "bundles": bundles_before,
                }, timeout=30.0) or {}
                self._registered_at = time.monotonic()
            except Exception:
                logger.warning("re-registration failed; will retry on "
                               "next heartbeat")
                return
            resumed = bool(resp.get("resumed"))
            rejected = set(resp.get("rejected_actors") or ())
            if resumed:
                assigned = {tuple(k) for k in
                            (resp.get("assigned_bundles") or ())}
                stale = [k for k in bundles_before if k not in assigned]
                if stale:
                    logger.warning("releasing %d bundle(s) the control "
                                   "dropped while we were disconnected: "
                                   "%s", len(stale), stale)
                for key in stale:
                    self._release_bundle_local(key)
                logger.info("re-registered with control (resumed): "
                            "%d live actor(s) kept, %d rejected",
                            len(live) - len(rejected), len(rejected))
            else:
                # clean slate: PG-placed workers reschedule with their
                # group; their bundles re-run the 2-phase reservation
                with self.lock:
                    pg_actor_workers = [
                        r for r in self.workers.values()
                        if r.actor_id is not None and r.state != "dead"
                        and r.bundle_key is not None]
                for rec in pg_actor_workers:
                    try:
                        if rec.conn is not None:
                            rec.conn.push("shutdown", {})
                        self._kill_worker(rec)
                    except Exception:
                        pass
                with self.lock:
                    for key in bundles_before:
                        self.bundles.pop(key, None)
                    self.available = dict(self.total)
                    for rec in self.workers.values():
                        if rec.state != "dead" and rec.lease_resources:
                            subtract(self.available, rec.lease_resources)
                            if rec.blocked and rec.lent:
                                add(self.available, rec.lent)
                    # reservations prepared after the snapshot survive
                    for b in self.bundles.values():
                        subtract(self.available, b["resources"])
            if rejected:
                with self.lock:
                    victims = [r for r in self.workers.values()
                               if r.actor_id in rejected
                               and r.state != "dead"]
                for rec in victims:
                    logger.warning("control rejected adoption of actor "
                                   "%s; reaping its worker",
                                   rec.actor_id[:12])
                    try:
                        if rec.conn is not None:
                            rec.conn.push("shutdown", {})
                        self._kill_worker(rec)
                    except Exception:
                        pass

    def _release_bundle_local(self, key: Tuple[str, int]):
        """Release one PG bundle and reap workers placed on it — rehome
        reconciliation for groups the control removed mid-partition."""
        with self.lock:
            victims = [r for r in self.workers.values()
                       if r.bundle_key == key and r.state != "dead"]
        for rec in victims:
            try:
                if rec.conn is not None:
                    rec.conn.push("shutdown", {})
                self._kill_worker(rec)
            except Exception:
                pass
        with self.lock:
            b = self.bundles.pop(key, None)
            if b is not None:
                add(self.available, b["resources"])

    def shutdown(self):
        if self._stop.is_set():
            return
        self._stop.set()
        # graceful exit: tell the control immediately.  Death is otherwise
        # only declared after the heartbeat timeout now that transient
        # disconnects are tolerated — a deliberate exit must not leave its
        # actors in limbo for that window.
        cli = self.control
        if cli is not None and not cli.closed:
            try:
                cli.call("unregister_node", {"node_id": self.node_id},
                         timeout=2.0)
            except Exception:
                pass
        if getattr(self, "log_monitor", None) is not None:
            self.log_monitor.stop()
        if self.preemption_watcher is not None:
            self.preemption_watcher.stop()
        with self.lock:
            workers = list(self.workers.values())
        for w in workers:
            self._kill_worker(w)
        self.server.stop()
        if self.spill is not None:
            self.spill.destroy()
        self.store.destroy()
        try:
            shutil.rmtree(self.session_dir, ignore_errors=True)
        except OSError:
            pass

    # -- worker pool -------------------------------------------------------

    def _spawn_worker(self, actor_id: Optional[str] = None,
                      env_extra: Optional[Dict[str, str]] = None,
                      tpu: bool = False,
                      container: Optional[Dict] = None) -> WorkerRecord:
        with self.lock:
            self._next_token += 1
            token = self._next_token
        wid = common.worker_id()
        rec = WorkerRecord(wid, None, token)
        rec.actor_id = actor_id
        rec.tpu = tpu
        with self.lock:
            self.workers[wid] = rec
            self.workers_by_token[token] = rec
        env = dict(os.environ)
        if not tpu and "PALLAS_AXON_POOL_IPS" in env:
            # CPU-only worker: skip the TPU-plugin sitecustomize (it
            # imports jax at interpreter start, ~2.4s of CPU per process,
            # and contends for the single chip).  Only workers granted a
            # TPU resource get device access — on a TPU host the chip
            # belongs to whichever process holds the TPU resource, exactly
            # like the reference's TPU_VISIBLE_CHIPS visibility scoping
            # (reference: _private/accelerators/tpu.py:155-195).
            env.pop("PALLAS_AXON_POOL_IPS")
            env["JAX_PLATFORMS"] = "cpu"
        from .bootstrap import _package_pythonpath

        # ONE dict of worker-specific vars: the same set is applied to
        # the host env AND forwarded into containers as -e flags (a
        # second hand-written list would silently drift)
        worker_vars = {
            "PYTHONPATH": _package_pythonpath(),
            "RAY_TPU_STARTUP_TOKEN": str(token),
            "RAY_TPU_WORKER_ID": wid,
            # line-buffered stdout so task prints reach the log tailer
            # (and the driver) promptly, not on buffer flushes
            "PYTHONUNBUFFERED": "1",
            "RAY_TPU_NODE_ID": self.node_id,
            "RAY_TPU_SESSION_DIR": self.session_dir,
        }
        if self.control_addr_file:
            # workers re-home to a promoted standby controller through
            # the same rendezvous file the raylet uses
            worker_vars["RAY_TPU_CONTROL_ADDR_FILE"] = self.control_addr_file
        if "JAX_PLATFORMS" in env and env.get("JAX_PLATFORMS") == "cpu":
            worker_vars["JAX_PLATFORMS"] = "cpu"
        if actor_id:
            worker_vars["RAY_TPU_ACTOR_ID"] = actor_id
        if env_extra:
            worker_vars.update(env_extra)
        env.update(worker_vars)
        cmd = [sys.executable, "-m", "ray_tpu._private.worker_proc",
               "--raylet", f"{self.server.addr[0]}:{self.server.addr[1]}",
               "--control", f"{self.control_addr[0]}:{self.control_addr[1]}"]
        try:
            if container:
                # containerized actor worker (reference: image_uri.py:106
                # ImageURIPlugin wrapping the worker command): the runtime
                # does not forward its client's env, so worker_vars ride
                # as -e flags; host network + /dev/shm + session dir
                # mounts keep the data/control planes reachable
                from . import runtime_env as _rtenv

                devices: list = []
                if tpu:
                    # TPU actors get the host's device nodes granted
                    # into the container + the chip-visibility/topology
                    # env forwarded (reference: image_uri.py device
                    # propagation; TPU_VISIBLE_CHIPS scoping tpu.py:155).
                    # A tunnel-attached chip (axon) needs only the env —
                    # it is reached over TCP.  Rejection stays ONLY for
                    # hosts with genuinely no device path: JAX silently
                    # falling back to CPU while holding the TPU lease is
                    # the failure mode this guards.
                    devices = accelerators.tpu_device_paths()
                    tpu_env = accelerators.tpu_container_env()
                    if not devices and \
                            "PALLAS_AXON_POOL_IPS" not in tpu_env:
                        raise RuntimeError(
                            "containerized TPU actor on a host with no "
                            "TPU device nodes (/dev/accel*, vfio) and "
                            "no tunnel endpoint — the container would "
                            "silently run on CPU while holding the TPU "
                            "lease")
                    worker_vars = {**worker_vars, **tpu_env}
                cmd = _rtenv.wrap_container_cmd(
                    cmd, worker_vars, container, self.session_dir,
                    env["PYTHONPATH"], devices=devices)
            log_dir = os.path.join(self.session_dir, "logs")
            os.makedirs(log_dir, exist_ok=True)
            out = open(os.path.join(log_dir, f"worker-{wid[:12]}.log"), "ab")
            rec.proc = subprocess.Popen(cmd, env=env, stdout=out, stderr=out,
                                        start_new_session=True)
            out.close()
        except Exception:
            # never leak the pre-registered record of a worker that was
            # never born (the reap loop skips proc=None records)
            with self.lock:
                self.workers.pop(wid, None)
                self.workers_by_token.pop(token, None)
            rec.state = "dead"
            raise
        return rec

    def h_register_worker(self, conn: ServerConn, p):
        token = p["token"]
        with self.lock:
            rec = self.workers_by_token.get(token)
            if rec is None:
                return {"ok": False, "error": "unknown startup token"}
            rec.addr = tuple(p["addr"])
            rec.conn = conn
            conn.meta["worker_id"] = rec.worker_id
            if rec.actor_id is None:
                rec.state = "idle"
                self.idle.append(rec)
            else:
                rec.state = "actor"
        return {"ok": True, "worker_id": rec.worker_id, "node_id": self.node_id,
                "actor_id": rec.actor_id}

    def _kill_worker(self, rec: WorkerRecord):
        rec.state = "dead"
        if rec.proc is not None and rec.proc.poll() is None:
            try:
                rec.proc.terminate()
            except OSError:
                pass

    def kill_worker_for_oom(self, rec: WorkerRecord) -> bool:
        """OOM-policy kill: release the lease's resources and retire the
        record up front — marking it dead suppresses the disconnect
        handler, which must not see this as an implicit lease return."""
        with self.lock:
            if rec.state != "leased":
                return False
            self._free_lease_resources(rec)
            rec.blocked = False
            rec.lease_id = None
            self.workers.pop(rec.worker_id, None)
            self.workers_by_token.pop(rec.token, None)
        self._kill_worker(rec)
        # its core may have held leases on other workers for nested tasks
        self._reclaim_leases_of_dead_client(rec.worker_id)
        self._mux_on_worker_gone(rec.worker_id)
        self._try_grant()
        return True

    def h_disconnect(self, conn: ServerConn):
        # drop reclaim-push registrations bound to this conn (drivers
        # and worker cores alike), or dead ServerConns accumulate —
        # and reclaim the departed client's leases: a DRIVER exiting
        # mid-lease never registers as a worker, so without this its
        # task leases leak until the whole node starves (each departed
        # driver once pinned its leased CPUs forever)
        gone_clients = []
        with self.lock:
            for cid, c in list(self.client_conns.items()):
                if c is conn:
                    self.client_conns.pop(cid, None)
                    # a worker core's own id is handled by the worker
                    # tail below (which also kills the proc) — don't
                    # run the reclaim scan twice for it
                    if cid != conn.meta.get("worker_id"):
                        gone_clients.append(cid)
        for cid in gone_clients:
            # purge the departed client's QUEUED lease requests too:
            # granting one to a ghost books resources nobody will ever
            # use or return (the leak that starved a node after a burst
            # of short-lived drivers)
            self._purge_pending_of_client(cid)
            self._mux_purge_client(cid)
            self._reclaim_leases_of_dead_client(cid)
        if gone_clients:
            self._try_grant()
        wid = conn.meta.get("worker_id")
        if not wid:
            return
        with self.lock:
            rec = self.workers.get(wid)
            if rec is None:
                return
            # single critical section (the lock is re-entrant, so the
            # reclaim below may re-acquire it): no TOCTOU window between
            # classifying the record and retiring it
            if rec.state == "dead":
                # killed via a kill path that already handled resources —
                # the record must still leave the table, or it counts
                # against max_workers forever and eventually starves all
                # worker spawning.  Leases ITS core held on other workers
                # still need reclaiming (below).
                self.workers.pop(wid, None)
                self.workers_by_token.pop(rec.token, None)
                was = actor_id = None
                killed_path = True
            else:
                killed_path = False
                was = rec.state
                actor_id = rec.actor_id
                if rec.lease_resources or rec.bundle_demand or rec.lent:
                    self._free_lease_resources(rec)
                if rec in self.idle:
                    try:
                        self.idle.remove(rec)
                    except ValueError:
                        pass
                rec.state = "dead"
                self.workers.pop(wid, None)
                self.workers_by_token.pop(rec.token, None)
        self._mux_on_worker_gone(wid)
        if killed_path:
            self._reclaim_leases_of_dead_client(wid)
            return
        if actor_id and self.control is not None and not self._stop.is_set():
            try:
                self.control.notify("actor_failed", {
                    "actor_id": actor_id,
                    "error": f"actor worker process exited (state={was})",
                })
            except OSError:
                pass
        self._reclaim_leases_of_dead_client(wid)

    def _reclaim_leases_of_dead_client(self, dead_worker_id: str):
        """A local worker (whose core may have leased OTHER workers for
        nested tasks — e.g. an actor running data tasks) died: free the
        leases it held, or they stay 'leased' forever and the node starves
        (reference: raylet lease cleanup on client disconnect).  The
        leased workers are KILLED, not recycled — they may still be
        executing the dead client's task, and a stale task queued ahead
        would stall the next lessee's work indefinitely."""
        reclaimed = []
        with self.lock:
            for rec in list(self.workers.values()):
                if rec.state == "leased" \
                        and rec.lease_client_id == dead_worker_id:
                    self._free_lease_resources(rec)
                    rec.blocked = False
                    rec.lease_id = None
                    rec.lease_client_id = None
                    self.workers.pop(rec.worker_id, None)
                    self.workers_by_token.pop(rec.token, None)
                    reclaimed.append(rec)
        for rec in reclaimed:
            self._kill_worker(rec)
        if reclaimed:
            logger.info("reclaimed %d lease(s) of dead client %s",
                        len(reclaimed), dead_worker_id[:12])
            # a reclaimed worker's own core may have leased further
            # workers (depth-2 nesting); its disconnect handler will
            # no-op (record already popped), so recurse here
            for rec in reclaimed:
                self._reclaim_leases_of_dead_client(rec.worker_id)
            self._try_grant()

    def _reap_loop(self):
        while not self._stop.is_set():
            time.sleep(1.0)
            with self.lock:
                for rec in list(self.workers.values()):
                    if rec.proc is None or rec.proc.poll() is None:
                        continue
                    if rec.state == "starting":
                        # died before registering
                        logger.warning("worker %s died during startup",
                                       rec.worker_id[:12])
                        self.workers.pop(rec.worker_id, None)
                        self.workers_by_token.pop(rec.token, None)
                    elif rec.state == "dead":
                        # kill paths own the resource bookkeeping; the
                        # reaper only retires the record (backstop for
                        # workers whose conn never fires h_disconnect)
                        self.workers.pop(rec.worker_id, None)
                        self.workers_by_token.pop(rec.token, None)

    # -- leases ------------------------------------------------------------

    def h_request_lease(self, conn, p, d: Deferred):
        self._enqueue_lease(conn, p, d, count=1, vector=False)

    def h_request_leases(self, conn, p, d: Deferred):
        """Vectorized lease request: up to p['count'] copies of one demand
        granted in a single reply ({"ok": True, "grants": [...]}).  Grants
        may be fewer than requested — whatever one grant pass can serve —
        and never zero with ok=True (zero keeps the request pending)."""
        self._enqueue_lease(conn, p, d,
                            count=max(1, int(p.get("count", 1))),
                            vector=True)

    def _enqueue_lease(self, conn, p, d: Deferred, count: int, vector: bool):
        res = p.get("resources")
        demand = normalize_resources({common.CPU: 1} if res is None else res)
        bundle = p.get("bundle")  # (pg_id, index) -> draw from bundle reservation
        if bundle is not None:
            bundle = (bundle[0], bundle[1])
            with self.lock:
                if bundle[1] == -1:
                    # "any bundle of this group" (reference:
                    # placement_group_bundle_index=-1): accept if the pg
                    # has any committed bundle here; resolved at grant
                    if not self._pg_bundles_locked(bundle[0]):
                        d.reject(f"no committed bundle of {bundle[0]} "
                                 f"on this node")
                        return
                else:
                    b = self.bundles.get(bundle)
                    if b is None or b["state"] != "committed":
                        d.reject(f"bundle {bundle} not committed on this node")
                        return
        cid = p.get("client_id", "")
        with self.lock:
            if cid:
                self.client_conns[cid] = conn
                activated = self._mux_note_client(cid)
            else:
                activated = False
            self.pending_leases.append(
                PendingLease(demand, d, cid, bundle,
                             retriable=p.get("retriable", True),
                             count=count, vector=vector))
        if activated:
            self._mux_announce()
        self._try_grant()

    def _pg_bundles_locked(self, pg_id: str):
        return [k for k, b in self.bundles.items()
                if k[0] == pg_id and b["state"] == "committed"]

    def _bundle_free_fits_locked(self, key, demand) -> bool:
        b = self.bundles.get(key)
        if b is None or b["state"] != "committed":
            return False
        free = dict(b["resources"])
        subtract(free, b.setdefault("used", {}))
        return fits(free, demand)

    def _resolve_bundle_locked(self, bundle, demand):
        """Concrete committed bundle key for a lease (index -1 = any bundle
        of the pg with room)."""
        if bundle[1] != -1:
            return bundle if self._bundle_free_fits_locked(bundle, demand) \
                else None
        for key in self._pg_bundles_locked(bundle[0]):
            if self._bundle_free_fits_locked(key, demand):
                return key
        return None

    def _lease_fits(self, pl: PendingLease) -> bool:
        """Bundle leases draw from the bundle's reservation, not general
        availability (the reservation was subtracted at PREPARE)."""
        if pl.bundle is not None:
            if pl.bundle[1] == -1:
                if not self._pg_bundles_locked(pl.bundle[0]):
                    return True  # grant path rejects; don't wedge the queue
                return self._resolve_bundle_locked(pl.bundle,
                                                   pl.demand) is not None
            b = self.bundles.get(pl.bundle)
            if b is None or b["state"] != "committed":
                return True  # grant path will reject; don't wedge the queue
            free = dict(b["resources"])
            subtract(free, b.setdefault("used", {}))
            return fits(free, pl.demand)
        return fits(self.available, pl.demand)

    def _grant_loop(self):
        while not self._stop.is_set():
            time.sleep(LEASE_GRANT_TICK_S)
            self._try_grant()
            try:
                self._mux_tick()
            except Exception:
                logger.exception("mux tick failed")

    def _prestart_loop(self):
        while not self._stop.is_set():
            try:
                with self.lock:
                    warm = sum(1 for r in self.workers.values()
                               if r.actor_id is None
                               and r.state in ("starting", "idle"))
                    deficit = self.prestart_target - warm
                    room = self.max_workers - len(self.workers)
                # spawn at most one per tick: on small hosts concurrent
                # interpreter+jax imports thrash the CPU.  A draining
                # host stops warming — its pool only shrinks from here.
                if deficit > 0 and room > 0 and not self._draining:
                    self._spawn_worker()
            except Exception:
                logger.exception("prestart failed")
            time.sleep(0.25)

    def _try_grant(self):
        grants: List[Tuple[PendingLease, List[WorkerRecord]]] = []
        rejects: List[Tuple[PendingLease, str]] = []
        spawn = 0
        spawn_tpu = False
        starved = False
        with self.lock:
            mux_flag = self.mux_on
            while self.pending_leases:
                pl = self.pending_leases[0]
                wants_tpu = any(k.startswith(common.TPU)
                                for k in pl.demand)
                # grant up to pl.count copies in this one pass; the fits
                # check re-runs per copy because each charge shrinks the
                # pool (vector requests stop at whatever actually fits)
                granted: List[WorkerRecord] = []
                reject_msg = None
                while len(granted) < pl.count:
                    if not self._lease_fits(pl):
                        break
                    w = None
                    skipped: List[WorkerRecord] = []
                    while self.idle:
                        cand = self.idle.popleft()
                        if cand.state != "idle":
                            continue
                        if wants_tpu and not cand.tpu:
                            skipped.append(cand)  # CPU-only worker: no device
                            continue
                        w = cand
                        break
                    self.idle.extend(skipped)
                    if w is None:
                        break
                    if pl.bundle is not None:
                        key = self._resolve_bundle_locked(pl.bundle, pl.demand)
                        b = self.bundles.get(key) if key else None
                        if b is None:
                            reject_msg = f"bundle {pl.bundle} no longer committed"
                            self.idle.append(w)
                            break
                        add(b.setdefault("used", {}), pl.demand)
                        w.bundle_key = key
                    else:
                        subtract(self.available, pl.demand)
                    w.state = "leased"
                    w.leased_at = time.monotonic()
                    w.lease_id = common.new_id("lease-")
                    w.lease_resources = pl.demand
                    w.lease_retriable = pl.retriable
                    w.lease_client_id = pl.client_id
                    granted.append(w)
                if granted:
                    # partial vector grants resolve immediately with what
                    # this pass could serve — never park granted workers
                    # behind the remainder (the owner re-requests)
                    self.pending_leases.popleft()
                    grants.append((pl, granted))
                    continue
                if reject_msg is not None:
                    self.pending_leases.popleft()
                    rejects.append((pl, reject_msg))
                    continue
                if not self._lease_fits(pl):
                    starved = True
                    break
                # fits but no idle worker: spawn toward the remaining
                # demand (a vector request warms several at once instead
                # of the old one-per-grant-tick trickle)
                n_starting = sum(
                    1 for r in self.workers.values()
                    if r.state == "starting" and r.actor_id is None
                    and r.tpu == wants_tpu)
                room = self.max_workers - len(self.workers)
                spawn = max(0, min(pl.count - n_starting, room))
                spawn_tpu = wants_tpu
                break
        for _ in range(spawn):
            self._spawn_worker(tpu=spawn_tpu)
        for pl, msg in rejects:
            pl.deferred.reject(msg)
        for pl, ws in grants:
            logger.debug("grant %s lease=%s client=%s avail=%s",
                         [w.worker_id for w in ws], pl.demand,
                         pl.client_id, self.available)
            if pl.vector:
                pl.deferred.resolve({
                    "ok": True, "node_id": self.node_id,
                    # relay advisory: late-joining drivers learn the mux
                    # is open without waiting for a submit_mux push
                    "mux": mux_flag,
                    "grants": [{"lease_id": w.lease_id,
                                "worker_id": w.worker_id,
                                "worker_addr": w.addr} for w in ws],
                })
            else:
                w = ws[0]
                pl.deferred.resolve({
                    "ok": True, "lease_id": w.lease_id,
                    "worker_id": w.worker_id,
                    "worker_addr": w.addr, "node_id": self.node_id,
                })
        if starved:
            self._request_idle_reclaim()

    def _request_idle_reclaim(self):
        """A queued lease can't be served: ask every known lessee core to
        return its IDLE leases now instead of at the TTL reaper
        (reference: raylet ReleaseUnusedWorkers).  Without this, each
        new scheduling key's pool hoards leases and serialized one-shot
        workloads degrade to one reap-quantum per step."""
        now = time.monotonic()
        with self.lock:
            if now - self._last_reclaim_push < 0.5:
                return
            self._last_reclaim_push = now
            conns = list(self.client_conns.items())
        dead = []
        for cid, conn in conns:
            try:
                if not conn.push("reclaim_idle_leases", {}):
                    raise OSError("push failed")
            except Exception:
                with self.lock:
                    # identity guard: a failed push to a STALE conn must
                    # not reclaim a client that reconnected since
                    if self.client_conns.get(cid) is conn:
                        self.client_conns.pop(cid, None)
                        dead.append(cid)
        for cid in dead:
            # a push to a dead conn may race ahead of its h_disconnect;
            # having popped the registration (the disconnect handler's
            # only cue), run the same reclaim here or the dead client's
            # leases/queued requests leak
            self._purge_pending_of_client(cid)
            self._reclaim_leases_of_dead_client(cid)

    def _free_lease_resources(self, rec: WorkerRecord):
        """Return a worker's held resources to the right pool (general
        availability or its PG bundle's reservation).  Caller holds lock."""
        logger.info("free_lease %s lease=%s blocked=%s bundle=%s avail=%s",
                    rec.worker_id[:12], rec.lease_resources, rec.blocked,
                    rec.bundle_key, self.available)
        if rec.bundle_key is not None or rec.bundle_demand:
            # bundle 'used' is charged only for TASK leases; a blocked
            # task already released its CPU slot at block time, so only
            # the non-lent remainder comes back here
            if rec.lease_resources:
                b = self.bundles.get(rec.bundle_key) \
                    if rec.bundle_key is not None else None
                if b is not None:
                    rest = ({k: v for k, v in rec.lease_resources.items()
                             if k not in rec.lent}
                            if rec.blocked else rec.lease_resources)
                    subtract(b.setdefault("used", {}), rest)
            if rec.blocked and rec.lent:
                # bundle-backed: the general-pool loan was an EXTRA credit
                # on top of the PG's reservation; dying without unblocking
                # means it must be revoked (non-bundle loans simply stay —
                # the dead worker's CPU is genuinely free)
                subtract(self.available, rec.lent)
            rec.bundle_key = None
            rec.bundle_demand = {}
        elif not rec.blocked:
            add(self.available, rec.lease_resources)
        else:
            # blocked non-bundle lease: the CPU portion (rec.lent) already
            # went back at block time, but non-CPU resources (devices)
            # stayed booked — return them now or they leak forever
            rest = {k: v for k, v in rec.lease_resources.items()
                    if k not in rec.lent}
            add(self.available, rest)
        rec.lent = {}
        rec.lease_resources = {}

    def h_return_lease(self, conn, p):
        wid = p.get("worker_id")
        with self.lock:
            rec = self.workers.get(wid)
            if rec is None or rec.state != "leased":
                return False
            self._free_lease_resources(rec)
            rec.blocked = False
            rec.state = "idle"
            rec.lease_id = None
            self.idle.append(rec)
        self._try_grant()
        return True

    # -- submit multiplexer (relay) ---------------------------------------
    # Reference shape: the reference raylet's lease-less actor submission
    # path — here generalized so N concurrent drivers' plain tasks share
    # ONE framed stream per driver into this raylet, which schedules them
    # against the pool and fans coalesced acks back out.  rpc_stats
    # before/after shows request_leases/return_lease traffic collapsing.

    def _mux_note_client(self, cid: str) -> bool:  # holds: lock
        """Track distinct concurrent external submitters; True when this
        observation just flipped the mux on (caller announces, outside
        the lock).  Caller holds lock.  Worker cores doing nested
        submits don't count — they ride their host driver's workload."""
        if not self.mux_enabled or not cid or cid in self.workers:
            return False
        now = time.monotonic()
        self.mux_seen[cid] = now
        if self.mux_on:
            return False
        live = sum(1 for ts in self.mux_seen.values()
                   if now - ts < MUX_WINDOW_S)
        if live >= 2:
            self.mux_on = True   # sticky for the session
            return True
        return False

    def _mux_announce(self):
        """Tell every known lessee core the relay is open (late joiners
        learn via the mux flag on request_leases replies)."""
        with self.lock:
            conns = list(self.client_conns.values())
        for conn in conns:
            try:
                conn.push("submit_mux", {"on": True})
            except Exception:
                pass

    def _mux_depth_locked(self) -> int:  # holds: lock
        """Pushes in flight per relay worker before it stops getting
        more (same EWMA-driven pipelining rule as SchedPool.depth)."""
        if self.mux_avg_ms is None:
            return 1
        if self.mux_avg_ms < 2.0:
            return 16
        if self.mux_avg_ms < 20.0:
            return 4
        return 1

    def h_mux_push_tasks(self, conn: ServerConn, p):
        """A driver's flusher ships a framed batch of relay tasks."""
        cid = p.get("client_id", "")
        specs = p.get("specs") or []
        self._trace_stamp_relay(specs)
        activated = False
        with self.lock:
            if cid:
                self.client_conns[cid] = conn
                activated = self._mux_note_client(cid)
            for spec in specs:
                self.mux_queue.append((cid, spec))
            self.mux_stats["submitted"] += len(specs)
        if activated:
            self._mux_announce()
        self._mux_pump()
        return True

    def _mux_pump(self):
        """Dispatch queued relay tasks to workers with pipeline room,
        claiming idle workers (or spawning) toward the backlog.  All
        socket sends happen outside the lock."""
        to_push: List[Tuple[Any, List[Any]]] = []
        spawn = 0
        starved = False
        with self.lock:
            if not self.mux_queue:
                return
            demand = normalize_resources({common.CPU: 1})
            per_worker: Dict[str, Tuple[Any, List[Any]]] = {}
            while self.mux_queue:
                depth = self._mux_depth_locked()
                best = None
                for mw in self.mux_workers.values():
                    rec = mw["rec"]
                    if rec.state != "leased" or rec.conn is None \
                            or rec.blocked:
                        continue
                    if len(mw["inflight"]) >= depth:
                        continue
                    if best is None \
                            or len(mw["inflight"]) < len(best["inflight"]):
                        best = mw
                if best is None:
                    if self._mux_claim_worker_locked(demand):
                        continue
                    if fits(self.available, demand):
                        # fits but no idle worker: spawn toward the
                        # backlog (mirrors _try_grant's vector warmup)
                        n_starting = sum(
                            1 for r in self.workers.values()
                            if r.state == "starting"
                            and r.actor_id is None and not r.tpu)
                        room = self.max_workers - len(self.workers)
                        spawn = max(0, min(
                            len(self.mux_queue) - n_starting, room))
                    else:
                        starved = True
                    break
                cid, spec = self.mux_queue.popleft()
                best["inflight"][spec.task_id] = (cid, spec)
                rec = best["rec"]
                ent = per_worker.get(rec.worker_id)
                if ent is None:
                    ent = per_worker[rec.worker_id] = (rec.conn, [])
                ent[1].append(spec)
            to_push = list(per_worker.values())
        for _ in range(spawn):
            try:
                self._spawn_worker()
            except Exception:
                logger.exception("mux worker spawn failed")
        for wconn, specs in to_push:
            try:
                with self._trace_relay_cm(specs):
                    if not wconn.push("mux_push_tasks", specs):
                        raise OSError("push failed")
            except Exception:
                # dead worker conn: its h_disconnect sweep fails these
                # back to their owners via _mux_on_worker_gone
                pass
        if starved:
            self._request_idle_reclaim()

    @staticmethod
    def _trace_stamp_relay(specs) -> None:
        """Stamp relay-queue entry clocks on sampled specs (local-only
        attr — TaskSpec.__reduce__ keeps it off the wire)."""
        from ray_tpu.util import tracing

        if not tracing.is_enabled():
            return
        now = time.time_ns()
        for spec in specs:
            if tracing.carrier_sampled(getattr(spec, "trace_ctx", None)):
                spec._relay_ns = now

    @staticmethod
    def _trace_relay_cm(specs):
        """Retro ``raylet.relay`` spans (relay-queue dwell) for each
        sampled spec in the outgoing batch, plus a ``raylet.mux_push``
        phase span around the worker push itself."""
        from ray_tpu.util import tracing

        if not tracing.is_enabled():
            return contextlib.nullcontext()
        now_ns = time.time_ns()
        carrier = None
        for spec in specs:
            relay_ns = getattr(spec, "_relay_ns", None)
            if relay_ns is None:
                continue
            spec._relay_ns = None
            tracing.record_span("raylet.relay", "INTERNAL", relay_ns,
                                now_ns, tracing._extract(spec.trace_ctx),
                                batch=len(specs))
            if carrier is None:
                carrier = spec.trace_ctx
        if carrier is None:
            return contextlib.nullcontext()
        payload_bytes = sum(len(s.args_blob or b"") for s in specs)
        return tracing.phase_span("raylet.mux_push", carrier,
                                  batch=len(specs),
                                  payload_bytes=payload_bytes)

    def _mux_claim_worker_locked(self, demand) -> bool:  # holds: lock
        """Claim one idle CPU worker for the relay (caller holds lock).
        The claim books a full lease record — blocked-task lending, OOM
        policy and disconnect reclaim all see a normal leased worker."""
        if not fits(self.available, demand):
            return False
        w = None
        skipped: List[WorkerRecord] = []
        while self.idle:
            cand = self.idle.popleft()
            if cand.state != "idle":
                continue
            if cand.tpu:
                skipped.append(cand)  # keep device workers for leases
                continue
            w = cand
            break
        self.idle.extend(skipped)
        if w is None:
            return False
        subtract(self.available, demand)
        w.state = "leased"
        w.leased_at = time.monotonic()
        w.lease_id = common.new_id("lease-")
        w.lease_resources = demand
        w.lease_retriable = True
        w.lease_client_id = MUX_CLIENT_ID
        self.mux_workers[w.worker_id] = {
            "rec": w, "inflight": {}, "idle_since": time.monotonic()}
        return True

    def h_mux_tasks_done(self, conn: ServerConn, batch):
        """A relay worker's coalesced completions: fan them back out to
        the owning drivers, one framed push per driver."""
        wid = conn.meta.get("worker_id")
        per_client: Dict[str, List] = {}
        with self.lock:
            mw = self.mux_workers.get(wid)
            if mw is None:
                return True
            for task_id, reply in batch:
                ent = mw["inflight"].pop(task_id, None)
                if ent is None:
                    continue
                cid, _spec = ent
                ms = reply.get("exec_ms")
                if ms is not None:
                    self.mux_avg_ms = ms if self.mux_avg_ms is None \
                        else 0.8 * self.mux_avg_ms + 0.2 * ms
                per_client.setdefault(cid, []).append((task_id, reply))
                self.mux_stats["completed"] += 1
            if not mw["inflight"]:
                mw["idle_since"] = time.monotonic()
            conns = {cid: self.client_conns.get(cid) for cid in per_client}
        for cid, items in per_client.items():
            c = conns.get(cid)
            if c is None:
                continue   # owner gone; disconnect reclaim handles it
            try:
                c.push("mux_tasks_done", items)
            except Exception:
                pass
        self._mux_pump()
        return True

    def h_mux_cancel(self, conn: ServerConn, p):
        """Owner-requested cancel of a relay task: a still-queued task
        reports straight back through mux_task_failed (the owner maps it
        to TaskCancelledError — rec.canceled is already set there); a
        dispatched one is forwarded to its worker."""
        tid = p.get("task_id")
        cid = p.get("client_id", "")
        owner_conn = None
        worker_conn = None
        with self.lock:
            queued = next((i for i, (_c, s) in enumerate(self.mux_queue)
                           if s.task_id == tid), None)
            if queued is not None:
                del self.mux_queue[queued]
                owner_conn = self.client_conns.get(cid)
            else:
                for mw in self.mux_workers.values():
                    if tid in mw["inflight"]:
                        worker_conn = mw["rec"].conn
                        break
        if owner_conn is not None:
            try:
                owner_conn.push("mux_task_failed",
                                [(tid, "cancelled before start")])
            except Exception:
                pass
        elif worker_conn is not None:
            try:
                worker_conn.push("mux_cancel", p)
            except Exception:
                pass
        return True

    def _mux_on_worker_gone(self, wid: str):
        """A relay worker died: report its in-flight tasks to their
        owners (retry vs error is the owner's call — same policy as a
        lost lease conn)."""
        per_client: Dict[str, List] = {}
        with self.lock:
            mw = self.mux_workers.pop(wid, None)
            if mw is None:
                return
            for task_id, (cid, _spec) in mw["inflight"].items():
                per_client.setdefault(cid, []).append(
                    (task_id, f"worker {wid[:12]} died"))
                self.mux_stats["failed"] += 1
            conns = {cid: self.client_conns.get(cid) for cid in per_client}
        for cid, items in per_client.items():
            c = conns.get(cid)
            if c is None:
                continue
            try:
                c.push("mux_task_failed", items)
            except Exception:
                pass
        self._mux_pump()

    def _mux_purge_client(self, cid: str):
        """Drop a departed client's queued relay tasks (its in-flight
        ones finish and their acks fall on the floor)."""
        with self.lock:
            self.mux_seen.pop(cid, None)
            if self.mux_queue:
                self.mux_queue = deque(
                    (c, s) for c, s in self.mux_queue if c != cid)

    def _mux_tick(self):
        """Periodic relay maintenance (grant-loop tick): re-pump in case
        capacity freed, and hand relay workers back to the shared pool
        once idle past the TTL — immediately when classic lease requests
        are starving and the relay queue is empty."""
        released = False
        gone: List[str] = []
        with self.lock:
            if not self.mux_on:
                return
            now = time.monotonic()
            force = bool(self.pending_leases) and not self.mux_queue
            for wid, mw in list(self.mux_workers.items()):
                rec = mw["rec"]
                if rec.state != "leased":
                    # reclaimed/killed behind our back (e.g. reap loop):
                    # report its in-flight work, outside the lock
                    gone.append(wid)
                    continue
                if mw["inflight"]:
                    continue
                if not force and (self.mux_queue
                                  or now - mw["idle_since"]
                                  < MUX_IDLE_RELEASE_S):
                    continue
                self.mux_workers.pop(wid, None)
                self._free_lease_resources(rec)
                rec.blocked = False
                rec.state = "idle"
                rec.lease_id = None
                rec.lease_client_id = None
                self.idle.append(rec)
                self.mux_stats["released"] += 1
                released = True
        for wid in gone:
            self._mux_on_worker_gone(wid)
        if released:
            self._try_grant()
        self._mux_pump()

    def _purge_pending_of_client(self, cid: str) -> int:
        canceled = []
        with self.lock:
            keep = deque()
            for pl in self.pending_leases:
                if pl.client_id == cid:
                    canceled.append(pl)
                else:
                    keep.append(pl)
            self.pending_leases = keep
        for pl in canceled:
            try:
                pl.deferred.resolve({"ok": False, "canceled": True})
            except Exception:
                pass
        return len(canceled)

    def h_cancel_lease_requests(self, conn, p):
        return self._purge_pending_of_client(p.get("client_id"))

    def h_task_blocked(self, conn, p):
        """A worker blocked in get() lends its CPUs (CPU only — never a
        physical device its process still holds) to the GENERAL pool, and
        a bundle-backed worker also releases its PG slot for nested
        same-bundle leases.  Crediting only the bundle deadlocks the
        canonical Train shape: PG-bound train workers block on a
        streaming-data coordinator whose read tasks need general-pool
        CPUs (reference: blocked workers release CPUs for any work).  A
        bundle worker's slot is thus transiently usable from BOTH pools —
        bounded oversubscription, same as the unblock path's."""
        wid = p.get("worker_id")
        with self.lock:
            rec = self.workers.get(wid)
            if rec is not None and rec.state in ("leased", "actor") \
                    and not rec.blocked:
                rec.blocked = True
                base = rec.lease_resources or rec.bundle_demand
                rec.lent = {k: v for k, v in base.items() if k == common.CPU}
                if rec.bundle_key is not None and rec.lease_resources:
                    b = self.bundles.get(rec.bundle_key)
                    if b is not None:
                        # release only the CPU slot — the process still
                        # owns any device the lease carried
                        subtract(b.setdefault("used", {}), rec.lent)
                add(self.available, rec.lent)
        self._try_grant()
        return True

    def h_task_unblocked(self, conn, p):
        wid = p.get("worker_id")
        with self.lock:
            rec = self.workers.get(wid)
            if rec is not None and rec.blocked:
                rec.blocked = False
                if rec.bundle_key is not None and rec.lease_resources:
                    b = self.bundles.get(rec.bundle_key)
                    if b is not None:
                        add(b.setdefault("used", {}), rec.lent)
                # may go negative transiently: oversubscription by design
                subtract(self.available, rec.lent)
                rec.lent = {}
        return True

    # -- actors ------------------------------------------------------------

    def h_start_actor_worker(self, conn, p, d: Deferred):
        demand = normalize_resources(p.get("resources"))
        with self.lock:
            bundle_key = (p.get("pg_id"), p.get("bundle_index", -1))
            if p.get("pg_id") and bundle_key[1] == -1:
                # "any bundle of this group": resolve to a committed one —
                # otherwise the actor wrongly competes for general-pool
                # CPUs its own PG already reserved (admission inside a
                # bundle is not re-gated: the PG reserved the capacity and
                # the control plane assigns actors to bundles)
                for k, b in self.bundles.items():
                    if k[0] == p["pg_id"] and b.get("state") == "committed":
                        bundle_key = k
                        break
            from_bundle = p.get("pg_id") and self.bundles.get(
                bundle_key, {}).get("state") == "committed"
            if not from_bundle:
                if not fits(self.available, demand):
                    d.resolve({"ok": False, "error": "insufficient resources"})
                    return
                subtract(self.available, demand)
        # prefer a prestarted idle worker: assign_actor turns it into the
        # actor's dedicated process with zero spawn latency (reference:
        # WorkerPool::PopWorker worker_pool.h:366).  TPU actors need a
        # device-visible process — the warm pool is CPU-only, so they spawn.
        wants_tpu = any(k.startswith(common.TPU) for k in demand)
        container = p.get("container")
        w = None
        with self.lock:
            # containerized actors never reuse the warm pool: those
            # processes run on the host, not in the requested image
            while not wants_tpu and not container and self.idle:
                cand = self.idle.popleft()
                if cand.state == "idle" and cand.conn is not None:
                    w = cand
                    break
            if w is not None:
                w.state = "actor"
                w.actor_id = p["actor_id"]
                w.incarnation = p.get("incarnation", 0)
                w.lease_resources = demand if not from_bundle else {}
                w.bundle_demand = demand if from_bundle else {}
                if from_bundle:
                    w.bundle_key = bundle_key
        if w is not None:
            ok = w.conn.push("assign_actor", {
                "actor_id": p["actor_id"],
                "incarnation": p.get("incarnation", 0)})
            if ok:
                d.resolve({"ok": True, "worker_addr": w.addr,
                           "worker_id": w.worker_id})
                return
            with self.lock:  # conn raced shut: fall through to fresh spawn
                w.state = "dead"
                if not from_bundle:
                    add(self.available, w.lease_resources)
                w.lease_resources = {}
                w.bundle_demand = {}
                w.bundle_key = None
        env = {}
        if p.get("incarnation") is not None:
            env["RAY_TPU_ACTOR_INCARNATION"] = str(p["incarnation"])
        try:
            rec = self._spawn_worker(actor_id=p["actor_id"], env_extra=env,
                                     tpu=wants_tpu, container=container)
        except Exception as e:
            # release the admission and surface the reason instead of a
            # silent spawn.  Only CONTAINER failures are permanent
            # (missing runtime / unsupported combination — retrying on
            # this node can't help); a transient host error on a plain
            # spawn (ENOMEM, disk blip) keeps the pre-container retry
            # semantics
            with self.lock:
                if not from_bundle:
                    add(self.available, demand)
            d.resolve({"ok": False, "permanent": bool(container),
                       "error": f"worker spawn failed: {e}"})
            return
        rec.lease_resources = demand if not from_bundle else {}
        rec.bundle_demand = demand if from_bundle else {}
        rec.incarnation = p.get("incarnation", 0)
        if from_bundle:
            rec.bundle_key = bundle_key

        def waiter():
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline and not self._stop.is_set():
                with self.lock:
                    if rec.addr is not None:
                        d.resolve({"ok": True, "worker_addr": rec.addr,
                                   "worker_id": rec.worker_id})
                        return
                    if rec.state == "dead" or rec.worker_id not in self.workers:
                        break
                time.sleep(0.02)
            with self.lock:
                if not from_bundle:
                    add(self.available, rec.lease_resources)
            reply = {"ok": False, "error": "actor worker failed to start"}
            rc = rec.proc.poll() if rec.proc is not None else None
            if container and rc not in (None, 0):
                # `podman run` exited before the worker registered: bad
                # image tag, failed pull, broken entrypoint — respawning
                # outside the actor's restart budget can't fix it (the
                # budget still applies via the control's failure path)
                reply["permanent"] = True
                reply["error"] = (f"container worker exited with code {rc} "
                                  f"before registering (image "
                                  f"{container.get('image')!r})")
            d.resolve(reply)

        threading.Thread(target=waiter, daemon=True).start()

    def h_kill_actor_worker(self, conn, p):
        aid = p["actor_id"]
        want_addr = tuple(p["worker_addr"]) if p.get("worker_addr") else None
        with self.lock:
            rec = next((r for r in self.workers.values()
                        if r.actor_id == aid
                        and (want_addr is None or r.addr == want_addr)), None)
        logger.info("kill_actor_worker %s -> rec=%s lease=%s", aid[:12],
                    rec.worker_id[:12] if rec else None,
                    rec.lease_resources if rec else None)
        if rec is None:
            return False

        def do_kill():
            # ask politely first so the worker can run atexit handlers
            if rec.conn is not None:
                rec.conn.push("shutdown", {})
            time.sleep(0.05)
            self._kill_worker(rec)
            with self.lock:
                if rec.lease_resources or rec.bundle_demand or rec.lent:
                    self._free_lease_resources(rec)

        threading.Thread(target=do_kill, daemon=True).start()
        return True

    # -- placement group bundles (2-phase commit) -------------------------

    def h_prepare_bundle(self, conn, p):
        key = (p["pg_id"], p["bundle_index"])
        demand = normalize_resources(p["resources"])
        with self.lock:
            if key in self.bundles:
                return {"ok": True}
            if not fits(self.available, demand):
                return {"ok": False, "error": "insufficient resources"}
            subtract(self.available, demand)
            self.bundles[key] = {"resources": demand, "state": "prepared",
                                 "ts": time.monotonic()}
        return {"ok": True}

    def h_commit_bundle(self, conn, p):
        key = (p["pg_id"], p["bundle_index"])
        with self.lock:
            b = self.bundles.get(key)
            if b is None:
                return {"ok": False}
            b["state"] = "committed"
        return {"ok": True}

    def h_release_bundle(self, conn, p):
        key = (p["pg_id"], p["bundle_index"])
        with self.lock:
            b = self.bundles.pop(key, None)
            if b is not None:
                add(self.available, b["resources"])
        return {"ok": True}

    # -- object plane ------------------------------------------------------

    def h_fetch_object(self, conn, p):
        """Serve raw object bytes to a remote raylet (chunking: the frame
        layer handles large payloads; reference streams 1MiB chunks,
        object_manager.proto:61)."""
        data = self.store.read_bytes(p["object_id"])
        if data is None and self.spill is not None:
            data = self.spill.read_spilled(p["object_id"])
        return data

    def h_pull_object(self, conn, p, d: Deferred):
        oid, from_addr = p["object_id"], tuple(p["from_addr"])

        def do():
            if self.store.contains(oid):
                d.resolve(True)
                return
            if self.spill is not None and self.spill.restore(oid):
                d.resolve(True)
                return
            try:
                cli = self._peer(from_addr)
                data = cli.call("fetch_object", {"object_id": oid}, timeout=120.0)
                if data is None:
                    d.resolve(False)
                    return
                self.store.write_bytes(oid, data)
                d.resolve(True)
            except Exception as e:
                d.reject(f"pull {oid} from {from_addr} failed: {e}")

        threading.Thread(target=do, daemon=True).start()

    def _peer(self, addr: Tuple[str, int]) -> Client:
        with self.lock:
            cli = self.peer_clients.get(addr)
            if cli is not None and not cli.closed:
                return cli
        cli = Client(addr, name="raylet-peer")
        with self.lock:
            self.peer_clients[addr] = cli
        return cli

    def h_delete_objects(self, conn, p):
        n = 0
        for oid in p["object_ids"]:
            dropped = self.store.delete(oid)
            if self.spill is not None:
                dropped = self.spill.delete(oid) or dropped
            if dropped:
                n += 1
        return n

    def h_store_stats(self, conn, p):
        objs = self.store.list_objects()
        out = {"num_objects": len(objs),
               "bytes": sum(self.store.size(o) or 0 for o in objs)}
        if self.spill is not None:
            out["spill"] = self.spill.stats()
        if self.oom_killer is not None:
            out["oom_killed"] = self.oom_killer.n_killed
        if p and p.get("detail"):
            out["objects"] = [{"object_id": o,
                               "size_bytes": self.store.size(o) or 0}
                              for o in objs]
        return out

    def h_pending_demands(self, conn, p):
        """Queued lease demands — autoscaler scale-up signal (reference:
        raylet resource_load in ray_syncer feeding load_metrics)."""
        with self.lock:
            return [common.denormalize_resources(pl.demand)
                    for pl in self.pending_leases]

    def h_list_workers(self, conn, p):
        """State-API source (reference: WorkerInfoGcsService + raylet state)."""
        with self.lock:
            return [{
                "worker_id": r.worker_id,
                "pid": r.proc.pid if r.proc else None,
                "state": r.state,
                "actor_id": r.actor_id,
                "node_id": self.node_id,
                "tpu": r.tpu,
                "addr": r.addr,  # core server: get_object + profiling RPCs
                "blocked": r.blocked,
                "lease_client_id": r.lease_client_id,
                "lease_resources": dict(r.lease_resources),
            } for r in self.workers.values()]

    def h_list_logs(self, conn, p):
        """Names + sizes of this node's log files (reference: dashboard
        modules/log + `ray logs` CLI listing)."""
        log_dir = os.path.join(self.session_dir, "logs")
        out = []
        try:
            for name in sorted(os.listdir(log_dir)):
                path = os.path.join(log_dir, name)
                if os.path.isfile(path):
                    out.append({"name": name,
                                "size_bytes": os.path.getsize(path)})
        except OSError:
            pass
        return {"node_id": self.node_id, "logs": out}

    def h_read_log(self, conn, p):
        """Tail of one log file by name (no path components allowed)."""
        name = p.get("name", "")
        if not name or "/" in name or name.startswith("."):
            return None
        path = os.path.join(self.session_dir, "logs", name)
        tail = int(p.get("tail_bytes", 64 * 1024))
        try:
            with open(path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - tail))
                return f.read().decode(errors="replace")
        except OSError:
            return None

    def h_list_leases(self, conn, p):
        """Debug introspection: every worker record's state + lease
        bookkeeping (who holds each CPU) — the first question when a
        node shows avail=0 with nothing visibly running."""
        with self.lock:
            return [{
                "worker_id": r.worker_id,
                "state": r.state,
                "actor_id": r.actor_id,
                "lease_resources": dict(r.lease_resources or {}),
                "lease_client_id": r.lease_client_id,
                "blocked": r.blocked,
                "lent": dict(r.lent or {}),
                "bundle_key": r.bundle_key,
            } for r in self.workers.values()]

    def h_node_info(self, conn, p):
        with self.lock:
            return {
                "node_id": self.node_id,
                "store_root": self.store.root,
                "control_addr": self.control_addr,
                "total": common.denormalize_resources(self.total),
                "available": common.denormalize_resources(self.available),
                "labels": self.labels,
                "num_workers": len(self.workers),
                "idle": len(self.idle),
                "pending_leases": len(self.pending_leases),
                "pid": os.getpid(),
                "bundles": [{"pg_id": k[0], "index": k[1],
                             "state": b["state"]}
                            for k, b in self.bundles.items()],
                "task_event_relay": self.task_event_relay_stats(),
                "submit_mux": {"on": self.mux_on,
                               "queued": len(self.mux_queue),
                               "workers": len(self.mux_workers),
                               **self.mux_stats},
            }

    # -- task-event relay --------------------------------------------------

    def task_event_relay_stats(self) -> Dict[str, Any]:
        with self._ev_relay_lock:
            return {**self._ev_relay_stats,
                    "buffered_events": self._ev_relay_buffered}

    def h_report_task_events(self, conn, p):
        """Workers flush task-event batches here (one-way notify on the
        socket they already hold) instead of each opening a control
        write; the relay loop forwards them coalesced."""
        nev = len(p.get("events", ()))
        with self._ev_relay_lock:
            self._ev_relay.append(p)
            self._ev_relay_buffered += nev
            rs = self._ev_relay_stats
            rs["batches_in"] += 1
            rs["events_in"] += nev
            while self._ev_relay_buffered > self._ev_relay_cap \
                    and len(self._ev_relay) > 1:
                old = self._ev_relay.popleft()
                n_old = len(old.get("events", ()))
                dropped = n_old + old.get("dropped", 0)
                self._ev_relay_buffered -= n_old
                self._ev_relay_pending_dropped += dropped
                rs["dropped"] += dropped
        return True

    def _task_event_relay_loop(self):
        from .task_events import FLUSH_INTERVAL_S

        while not self._stop.wait(FLUSH_INTERVAL_S):
            self._flush_task_event_relay()
        self._flush_task_event_relay()  # final drain on shutdown

    def _flush_task_event_relay(self):
        with self._ev_relay_lock:
            if not self._ev_relay and not self._ev_relay_pending_dropped:
                return
            batches = list(self._ev_relay)
            self._ev_relay.clear()
            self._ev_relay_buffered = 0
            dropped = self._ev_relay_pending_dropped
            self._ev_relay_pending_dropped = 0
        cli = self.control
        try:
            if cli is None or cli.closed:
                raise ConnectionLost("no control connection")
            # ONE framed write for the whole node-flush window
            cli.notify("report_task_events", {
                "batches": batches, "dropped": dropped,
                "node_id": self.node_id,
            })
            with self._ev_relay_lock:
                self._ev_relay_stats["sends"] += 1
                self._ev_relay_stats["coalesced"] += len(batches)
        except Exception:
            # control unreachable: requeue (bounded by the cap on the
            # next ingest) so a reconnect delivers rather than drops
            with self._ev_relay_lock:
                self._ev_relay.extendleft(reversed(batches))
                self._ev_relay_buffered += sum(
                    len(b.get("events", ())) for b in batches)
                self._ev_relay_pending_dropped += dropped

    # -- memory pressure ---------------------------------------------------

    def _memory_loop(self):
        """Spill under store pressure; kill workers under system memory
        pressure (reference: local_object_manager spilling loop +
        memory_monitor worker killing)."""
        spill_interval = 0.2
        next_mem = 0.0
        while not self._stop.is_set():
            try:
                if self.spill is not None and self.spill.over_high_water():
                    n = self.spill.maybe_spill()
                    if n:
                        logger.info("spilled %d objects to disk (%s)", n,
                                    self.spill.stats())
                now = time.monotonic()
                if self.oom_killer is not None and now >= next_mem:
                    self.oom_killer.step()
                    next_mem = now + self._mem_refresh_s
            except Exception:
                logger.exception("memory loop iteration failed")
            self._stop.wait(spill_interval)

    # -- heartbeats --------------------------------------------------------

    def _heartbeat_loop(self):
        """Liveness + resource sync (reference: ray_syncer.h:44-70 — a
        versioned RESOURCE_VIEW where only snapshots newer than the
        peer's last-seen version travel).  Heartbeats always carry
        liveness; the availability dict rides along ONLY when it changed
        since the last ACKED send, under a monotonically increasing
        version the control uses to drop stale/reordered updates.  At
        the reference's 2k-node envelope this is the difference between
        the control plane deserializing 2k resource dicts per beat and
        deserializing only what actually changed."""
        from .config import cfg as _hcfg
        from .control import HEARTBEAT_INTERVAL_S

        delta_sync = _hcfg().resource_sync_delta
        last_acked: Optional[Dict[str, float]] = None
        version = 0
        reg_seen = self._registered_at
        while not self._stop.is_set():
            try:
                if self._registered_at != reg_seen:
                    # re-registered (control restart / resurrect): the
                    # fresh NodeRecord assumed available == total, so
                    # force a full resync on the next beat
                    reg_seen = self._registered_at
                    last_acked = None
                with self.lock:
                    avail = common.denormalize_resources(
                        {k: max(v, 0) for k, v in self.available.items()})
                payload = {"node_id": self.node_id}
                send_avail = (not delta_sync) or avail != last_acked
                if send_avail:
                    version += 1
                    payload["available"] = avail
                    payload["avail_version"] = version
                sent = time.monotonic()
                r = self.control.call("heartbeat", payload, timeout=5.0)
                if r and r.get("ok") and send_avail:
                    last_acked = avail
                if r and r.get("resync"):
                    # the control's view diverged (optimistic pick_node
                    # reservation): resend ground truth next beat even
                    # if our own view hasn't changed
                    last_acked = None
                if r and not r.get("ok") and r.get("reregister"):
                    # not in the control's node table (restart/failover
                    # we haven't re-registered for, OR a false
                    # declared-dead while the control kept running).
                    # _rehome handles both: the control adopts live
                    # actors it restored, and rejects ones it already
                    # rescheduled elsewhere (those workers are reaped —
                    # the old clean-slate resurrect semantics).  The
                    # staleness guard skips if a racing reconnect-path
                    # rehome registered after this beat was sent.
                    last_acked = None   # new control: resend full view
                    self._rehome(if_stale_since=sent)
            except Exception:
                if not self._stop.is_set():
                    logger.warning("heartbeat to control failed")
            time.sleep(HEARTBEAT_INTERVAL_S)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--control", required=True)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--resources", default=None, help="JSON resource dict")
    ap.add_argument("--node-id", default=None)
    ap.add_argument("--session-dir", default=None)
    ap.add_argument("--addr-file", default=None,
                    help="control-plane rendezvous file; re-read on "
                         "reconnect so the raylet re-homes to a promoted "
                         "standby controller")
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s raylet %(levelname)s %(message)s")
    host, port = args.control.rsplit(":", 1)
    import json

    resources = json.loads(args.resources) if args.resources else None
    labels = None
    if os.environ.get("RAY_TPU_NODE_LABELS"):
        labels = json.loads(os.environ["RAY_TPU_NODE_LABELS"])
    # on Kubernetes the provider injects the pod name via the downward
    # API so control-plane node ids match pod names (idle scale-down
    # resolves idleness per control node id)
    node_id = args.node_id or os.environ.get("RAY_TPU_NODE_ID")
    r = Raylet((host, int(port)), host=args.host, port=args.port,
               resources=resources, session_dir=args.session_dir,
               node_id=node_id, labels=labels,
               control_addr_file=args.addr_file)

    # SIGTERM (bootstrap remove_node / scale-down) exits gracefully so the
    # control gets an immediate unregister_node instead of waiting out the
    # heartbeat-timeout death window
    import signal

    def _term(_sig, _frm):
        try:
            r.shutdown()
        finally:
            os._exit(0)

    try:
        signal.signal(signal.SIGTERM, _term)
    except (OSError, ValueError):
        pass
    r.start(block=True)


if __name__ == "__main__":
    main()
