"""Node-local shared-memory object store (plasma equivalent).

The reference's plasma store keeps immutable objects in a shared-memory arena
inside the raylet, with clients attaching over a unix socket + fd passing
(reference: src/ray/object_manager/plasma/store.h, fling.h).  The TPU-native
redesign uses one mmap-backed file per object under /dev/shm: *create* writes
into a private temp file and *seal* atomically renames it into place, so any
process on the node can open+mmap a sealed object lock-free and zero-copy —
no store round-trip on the read path at all.  The raylet owns lifetime
(delete/evict); see node.py.  A C++ arena allocator with LRU eviction backs
the same interface when built (ray_tpu/native/).

Object layout (64-byte aligned buffers so numpy views are aligned):

    magic u32 | ver u32 | meta_len u64 | nbuf u32 | pad u32 | buf_len u64[nbuf]
    | meta bytes | pad->64 | buf0 | pad->64 | buf1 | ...
"""

from __future__ import annotations

import mmap
import os
import struct
import time
from typing import List, Optional, Sequence, Tuple

_MAGIC = 0x52545055  # "RTPU"
_ALIGN = 64


def _pad(n: int) -> int:
    return (n + _ALIGN - 1) & ~(_ALIGN - 1)


def layout_size(meta_len: int, buf_lens: Sequence[int]) -> int:
    header = 4 + 4 + 8 + 4 + 4 + 8 * len(buf_lens)
    total = _pad(header + meta_len)
    for b in buf_lens:
        total = _pad(total + b)
    return total


def pack_header_into(buf: memoryview, meta: bytes,
                     lens: Sequence[int]) -> int:
    """Write the object header + meta; returns the (padded) offset where
    buffer 0 starts.  THE single owner of the on-disk layout's header —
    every writer (mmap pack, native-arena fast path) goes through it so a
    format change cannot silently fork."""
    off = 0
    struct.pack_into("<IIQII", buf, off, _MAGIC, 1, len(meta), len(lens), 0)
    off += 4 + 4 + 8 + 4 + 4
    for l in lens:
        struct.pack_into("<Q", buf, off, l)
        off += 8
    buf[off:off + len(meta)] = meta
    return _pad(off + len(meta))


def pack_into(buf: memoryview, meta: bytes, buffers: Sequence[memoryview]) -> None:
    off = pack_header_into(buf, meta, [len(b) for b in buffers])
    for b in buffers:
        n = len(b)
        buf[off:off + n] = b.cast("B") if isinstance(b, memoryview) else memoryview(b)
        off = _pad(off + n)


def unpack(buf: memoryview) -> Tuple[bytes, List[memoryview]]:
    magic, ver, meta_len, nbuf, _ = struct.unpack_from("<IIQII", buf, 0)
    if magic != _MAGIC:
        raise ValueError("bad object magic")
    off = 4 + 4 + 8 + 4 + 4
    lens = []
    for _ in range(nbuf):
        (l,) = struct.unpack_from("<Q", buf, off)
        lens.append(l)
        off += 8
    meta = bytes(buf[off:off + meta_len])
    off = _pad(off + meta_len)
    bufs = []
    for l in lens:
        bufs.append(buf[off:off + l])
        off = _pad(off + l)
    return meta, bufs


class FileObjectStore:
    """File-per-object store rooted at a /dev/shm session directory.

    Pure-Python fallback (and overflow tier) for the native arena store
    (ray_tpu/native/shm_arena.cc via _private/native_store.py)."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, object_id: str) -> str:
        return os.path.join(self.root, object_id)

    # -- write path --------------------------------------------------------

    def create(self, object_id: str, meta: bytes, buffers: Sequence[memoryview],
               primary: bool = True, allow_overflow: bool = True,
               warm_only: bool = False) -> int:
        """Write + seal an object; returns its byte size.

        Uses writev() rather than mmap: on tmpfs a streaming write avoids
        the per-page fault + TLB cost of populating a fresh mapping
        (~1.5-2x the bandwidth on the put path; reads stay mmap
        zero-copy)."""
        lens = [len(b) for b in buffers]
        size = layout_size(len(meta), lens)
        tmp = self._path(object_id) + ".tmp.%d" % os.getpid()
        fd = os.open(tmp, os.O_CREAT | os.O_RDWR | os.O_EXCL, 0o600)
        try:
            header = bytearray(4 + 4 + 8 + 4 + 4 + 8 * len(lens))
            struct.pack_into("<IIQII", header, 0, _MAGIC, 1, len(meta),
                             len(lens), 0)
            off = 4 + 4 + 8 + 4 + 4
            for l in lens:
                struct.pack_into("<Q", header, off, l)
                off += 8
            pad = b"\0" * _ALIGN
            iov: List = [bytes(header), meta]
            pos = len(header) + len(meta)
            for b in buffers:
                aligned = _pad(pos)
                if aligned != pos:
                    iov.append(pad[:aligned - pos])
                    pos = aligned
                mv = b.cast("B") if isinstance(b, memoryview) else memoryview(b)
                iov.append(mv)
                pos += len(mv)
            if _pad(pos) != pos:
                iov.append(pad[:_pad(pos) - pos])
            written = 0
            while iov:
                n = os.writev(fd, iov[:1024])
                written += n
                # drop fully-written iovecs; split a partial one
                while iov and n >= len(iov[0]):
                    n -= len(iov[0])
                    iov.pop(0)
                if n and iov:
                    iov[0] = memoryview(iov[0])[n:]
            if written < 1:
                os.ftruncate(fd, 1)
            os.rename(tmp, self._path(object_id))  # atomic seal
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        finally:
            os.close(fd)
        return size

    def put_raw(self, object_id: str, data: bytes) -> int:
        return self.create(object_id, b"", [memoryview(data)])

    # -- read path ---------------------------------------------------------

    def contains(self, object_id: str) -> bool:
        return os.path.exists(self._path(object_id))

    def get(self, object_id: str) -> Optional[Tuple[bytes, List[memoryview]]]:
        """Zero-copy read of a sealed object; None if absent.

        Lifetime: the returned memoryviews hold references to the mmap, and
        values deserialized over them (numpy arrays) hold the buffers — the
        mapping closes via GC when the last consumer drops it.  Unlinking
        the file (delete/evict) is safe while mapped (pages live until the
        mappings go away)."""
        try:
            fd = os.open(self._path(object_id), os.O_RDONLY)
        except FileNotFoundError:
            return None
        try:
            size = os.fstat(fd).st_size
            mm = mmap.mmap(fd, size, prot=mmap.PROT_READ)
        finally:
            os.close(fd)
        return unpack(memoryview(mm))

    def get_raw(self, object_id: str) -> Optional[memoryview]:
        r = self.get(object_id)
        if r is None:
            return None
        _, bufs = r
        return bufs[0] if bufs else memoryview(b"")

    def read_bytes(self, object_id: str) -> Optional[bytes]:
        """Copying read of the raw file (for network transfer)."""
        try:
            with open(self._path(object_id), "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    def write_bytes(self, object_id: str, data: bytes) -> None:
        """Install a raw object file fetched from another node."""
        tmp = self._path(object_id) + ".tmp.%d" % os.getpid()
        with open(tmp, "wb") as f:
            f.write(data)
        os.rename(tmp, self._path(object_id))

    def release(self, object_id: str) -> None:
        """No-op: mappings are GC-owned (see get)."""

    def delete(self, object_id: str) -> bool:
        try:
            os.unlink(self._path(object_id))
            return True
        except FileNotFoundError:
            return False

    def size(self, object_id: str) -> Optional[int]:
        try:
            return os.stat(self._path(object_id)).st_size
        except FileNotFoundError:
            return None

    def list_objects(self) -> List[str]:
        return [n for n in os.listdir(self.root) if not n.endswith(".tmp")
                and ".tmp." not in n and n != "arena.shm"]

    def wait_sealed(self, object_id: str, timeout: float) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.contains(object_id):
                return True
            time.sleep(0.002)
        return self.contains(object_id)

    def destroy(self) -> None:
        try:
            for n in os.listdir(self.root):
                try:
                    os.unlink(os.path.join(self.root, n))
                except OSError:
                    pass
            os.rmdir(self.root)
        except OSError:
            pass


def ShmObjectStore(root: str):
    """Store factory: native C++ arena when the toolchain is available
    (the default), file-per-object otherwise or when
    RAY_TPU_DISABLE_NATIVE_STORE=1."""
    if not os.environ.get("RAY_TPU_DISABLE_NATIVE_STORE"):
        try:
            from .native_store import NativeShmObjectStore

            return NativeShmObjectStore(root)
        except Exception as e:
            import logging

            logging.getLogger(__name__).warning(
                "native object store unavailable (%s); using file store", e)
    return FileObjectStore(root)
