"""On-demand per-process profiling.

Reference parity: the dashboard's ReporterAgent runs py-spy stack dumps /
CPU flamegraphs and memray memory profiles against worker PIDs
(reference: dashboard/modules/reporter/profile_manager.py:82,:189).
Those tools attach from outside via ptrace; here every worker is our own
Python process with an RPC server, so the equivalents are in-process and
dependency-free:

  * ``dump_stacks()`` — all-thread stack dump (py-spy dump analog)
  * ``cpu_profile(duration)`` — sampling profiler over
    ``sys._current_frames`` producing collapsed stacks in the flamegraph
    "folded" format (py-spy record analog)
  * ``memory_summary()`` — tracemalloc-based top allocations
    (memray analog; enable with RAY_TPU_TRACEMALLOC=1 at worker start)

If py-spy/memray ever are installed, they attach by pid exactly as in
the reference — these fallbacks keep the feature working without them.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback
from collections import Counter
from typing import Dict, Optional


def dump_stacks() -> str:
    """Formatted stacks of every thread (reference: py-spy dump)."""
    lines = []
    names = {t.ident: t.name for t in threading.enumerate()}
    for tid, frame in sorted(sys._current_frames().items()):
        lines.append(f"Thread {tid} ({names.get(tid, '?')}):")
        lines.extend(l.rstrip("\n")
                     for l in traceback.format_stack(frame))
        lines.append("")
    return "\n".join(lines)


def _folded_stack(frame) -> str:
    parts = []
    while frame is not None:
        code = frame.f_code
        parts.append(f"{os.path.basename(code.co_filename)}:"
                     f"{code.co_name}:{frame.f_lineno}")
        frame = frame.f_back
    return ";".join(reversed(parts))


def cpu_profile(duration_s: float = 2.0, interval_s: float = 0.01,
                thread_id: Optional[int] = None) -> str:
    """Sampling CPU profile in collapsed-stack ("folded") format, one
    line per unique stack: ``a;b;c <count>`` — feed to any flamegraph
    renderer (reference: py-spy record -f raw)."""
    counts: Counter = Counter()
    deadline = time.monotonic() + duration_s
    me = threading.get_ident()
    n = 0
    while time.monotonic() < deadline:
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue  # don't profile the profiler
            if thread_id is not None and tid != thread_id:
                continue
            counts[_folded_stack(frame)] += 1
        n += 1
        time.sleep(interval_s)
    header = f"# {n} samples over {duration_s}s at {interval_s*1000:.0f}ms\n"
    return header + "\n".join(
        f"{stack} {c}" for stack, c in counts.most_common())


def memory_summary(top: int = 20) -> str:
    """Top allocation sites via tracemalloc (memray analog).  Starts
    tracing on first call if RAY_TPU_TRACEMALLOC=1 wasn't set — later
    calls then see allocations made since."""
    import tracemalloc

    if not tracemalloc.is_tracing():
        tracemalloc.start()
        return ("tracemalloc was not tracing; started now — call again "
                "to see allocations made from this point")
    snap = tracemalloc.take_snapshot()
    stats = snap.statistics("lineno")[:top]
    total = sum(s.size for s in snap.statistics("filename"))
    lines = [f"# total traced: {total / 1e6:.1f} MB; top {top} sites:"]
    for s in stats:
        lines.append(f"{s.size / 1024:.0f} KiB  {s.count} blocks  "
                     f"{s.traceback.format()[-1].strip()}")
    return "\n".join(lines)


def maybe_start_tracemalloc() -> None:
    if os.environ.get("RAY_TPU_TRACEMALLOC") == "1":
        import tracemalloc

        tracemalloc.start()


def install_handlers(server) -> None:
    """Register the profiling RPCs on a worker/driver core server."""
    server.handle("dump_stacks", lambda c, p: dump_stacks())
    server.handle("memory_summary",
                  lambda c, p: memory_summary((p or {}).get("top", 20)))

    def h_profile(conn, p, d):
        def run():
            try:
                d.resolve(cpu_profile(
                    duration_s=float((p or {}).get("duration", 2.0)),
                    interval_s=float((p or {}).get("interval", 0.01))))
            except Exception as e:
                d.reject(f"cpu_profile failed: {e}")

        threading.Thread(target=run, daemon=True).start()

    server.handle("profile_cpu", h_profile, deferred=True)
