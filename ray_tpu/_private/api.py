"""Public API objects: @remote functions, actor classes, handles, options.

Mirrors the reference's decorator machinery (reference:
python/ray/remote_function.py:266 RemoteFunction._remote,
python/ray/actor.py ActorClass/ActorHandle) with TPU-native resource names
(num_tpus instead of num_gpus).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

from . import common
from .core import ObjectRef, current_core


def _build_resources(num_cpus=None, num_tpus=None, resources=None,
                     default_cpus: float = 1.0) -> Dict[str, float]:
    out: Dict[str, float] = dict(resources or {})
    out[common.CPU] = float(default_cpus if num_cpus is None else num_cpus)
    if num_tpus is not None and num_tpus > 0:
        out[common.TPU] = float(num_tpus)
    if out.get(common.CPU) == 0:
        out.pop(common.CPU, None)
    return out


def _strategy_to_wire(scheduling_strategy) -> tuple:
    """Returns (strategy_dict, pg_id, bundle_index)."""
    if scheduling_strategy is None or scheduling_strategy == "DEFAULT":
        return None, None, -1
    if scheduling_strategy == "SPREAD":
        return {"kind": "spread"}, None, -1
    kind = type(scheduling_strategy).__name__
    if kind == "PlacementGroupSchedulingStrategy":
        pg = scheduling_strategy.placement_group
        return None, pg.id, scheduling_strategy.placement_group_bundle_index
    if kind == "NodeAffinitySchedulingStrategy":
        return {"kind": "node_affinity",
                "node_id": scheduling_strategy.node_id,
                "soft": scheduling_strategy.soft}, None, -1
    if kind == "NodeLabelSchedulingStrategy":
        return scheduling_strategy.to_wire(), None, -1
    raise ValueError(f"unknown scheduling strategy: {scheduling_strategy!r}")


class RemoteFunction:
    def __init__(self, fn, **opts):
        self._fn = fn
        self._opts = opts
        functools.update_wrapper(self, fn)

    def options(self, **opts):
        merged = {**self._opts, **opts}
        return RemoteFunction(self._fn, **merged)

    def remote(self, *args, **kwargs):
        core = current_core()
        o = self._opts
        strategy, pg, bidx = _strategy_to_wire(o.get("scheduling_strategy"))
        if pg is None and o.get("placement_group") is not None:
            pg = o["placement_group"].id
            bidx = o.get("placement_group_bundle_index", -1)
        nr = o.get("num_returns", 1)
        refs = core.submit_task(
            self._fn, args, kwargs,
            num_returns=nr,
            resources=_build_resources(o.get("num_cpus"), o.get("num_tpus"),
                                       o.get("resources")),
            max_retries=o.get("max_retries", 3),
            strategy=strategy, pg=pg, bundle_index=bidx,
            name=o.get("name", ""),
            runtime_env=o.get("runtime_env"),
            generator_backpressure=o.get(
                "_generator_backpressure_num_objects", 0) or 0,
        )
        # streaming tasks return one ObjectRefGenerator
        return refs[0] if nr == 1 or nr == "streaming" else refs

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"remote function {self._fn.__name__} cannot be called directly; "
            f"use .remote()")

    def bind(self, *args, **kwargs):
        """Build a DAG node instead of executing (reference: python/ray/dag
        function_node.py) — used by interpreted DAGs and workflows."""
        from ray_tpu.dag.dag_node import FunctionNode

        return FunctionNode(self, args, kwargs)


class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str, num_returns: int = 1,
                 generator_backpressure: int = 0):
        self._handle = handle
        self._name = name
        self._num_returns = num_returns
        self._generator_backpressure = generator_backpressure

    def options(self, num_returns: int = 1,
                _generator_backpressure_num_objects: int = 0):
        return ActorMethod(self._handle, self._name, num_returns,
                           _generator_backpressure_num_objects or 0)

    def remote(self, *args, **kwargs):
        core = current_core()
        refs = core.submit_actor_task(
            self._handle._actor_id, self._name, args, kwargs,
            num_returns=self._num_returns,
            generator_backpressure=self._generator_backpressure)
        # streaming methods return one ObjectRefGenerator
        return refs[0] if self._num_returns in (1, "streaming") else refs

    def bind(self, *args, **kwargs):
        """Build a DAG node instead of executing (reference:
        python/ray/dag — actor.method.bind)."""
        from ray_tpu.dag.dag_node import ClassMethodNode

        return ClassMethodNode(self._handle, self._name, args, kwargs)


class ActorHandle:
    def __init__(self, actor_id: str, class_name: str = "Actor",
                 is_owner: bool = False, owner_addr=None,
                 _register_borrow: bool = False,
                 _transit_nonce: Optional[str] = None):
        self._actor_id = actor_id
        self._class_name = class_name
        self._is_owner = is_owner
        self._owner_addr = tuple(owner_addr) if owner_addr else None
        self._borrow_registered = False
        if _register_borrow and not is_owner:
            # deserialized handle: register as a borrower with the owner
            # so the actor outlives the owner's handles while we exist
            # (reference: distributed actor-handle reference counting);
            # the nonce retires the specific transit hold this pickle took
            try:
                core = current_core()
                if core is not None and not core._shutdown:
                    self._borrow_registered = core.on_actor_handle_borrowed(
                        actor_id, self._owner_addr, nonce=_transit_nonce)
            except Exception:
                pass

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return ActorMethod(self, name)

    def __repr__(self):
        return f"ActorHandle({self._class_name}, {self._actor_id})"

    def _actor_call(self, fn, *args, **kwargs):
        """Run `fn(actor_instance, *args)` inside the actor (reference:
        ActorHandle.__ray_call__) — returns an ObjectRef."""
        return ActorMethod(self, "__apply__").remote(fn, *args, **kwargs)

    def __reduce__(self):
        # deserialized handles are borrowed: they don't own the lifetime
        # but DO extend it (the serializing core takes a per-pickle
        # transit hold so the actor survives the pickling->registration
        # gap; the nonce rides the pickle so the receiver retires exactly
        # this hold)
        nonce = None
        try:
            core = current_core()
            if core is not None and not core._shutdown:
                nonce = core.on_actor_handle_serialized(self._actor_id,
                                                        self._owner_addr)
        except Exception:
            pass
        return (ActorHandle, (self._actor_id, self._class_name, False,
                              self._owner_addr, True, nonce))

    def __del__(self):
        # the last owner handle going out of scope terminates the actor
        # gracefully — queued behind in-flight calls, so
        # `Actor.remote().method.remote()` temporaries don't kill the
        # actor under their own call (reference semantics: actors are
        # GC'd when no handle remains, via a __ray_terminate__ marker
        # task); borrowed handles deregister with the owner instead
        if getattr(self, "_is_owner", False):
            try:
                core = current_core()
                if not core._shutdown:
                    core.release_actor(self._actor_id)
            except Exception:
                pass
        elif getattr(self, "_borrow_registered", False):
            try:
                core = current_core()
                if core is not None and not core._shutdown:
                    core.on_actor_handle_dropped(self._actor_id)
            except Exception:
                pass


class ActorClass:
    def __init__(self, cls, **opts):
        self._cls = cls
        self._opts = opts

    def options(self, **opts):
        return ActorClass(self._cls, **{**self._opts, **opts})

    def remote(self, *args, **kwargs) -> ActorHandle:
        core = current_core()
        o = self._opts
        if o.get("get_if_exists"):
            # idempotent get-or-create for named actors (reference:
            # actor options get_if_exists) — fetch first; creation races
            # fall through to the name-collision fetch below
            if not o.get("name"):
                raise ValueError("get_if_exists requires a name")
            view = core.get_actor_by_name(o["name"],
                                          namespace=o.get("namespace"))
            if view is not None and view["state"] != "DEAD":
                return ActorHandle(view["actor_id"], self._cls.__name__,
                                   is_owner=False)
            try:
                return self.options(get_if_exists=False).remote(
                    *args, **kwargs)
            except Exception as e:
                if "already taken" not in str(e):
                    raise
                view = core.get_actor_by_name(o["name"],
                                              namespace=o.get("namespace"))
                if view is None:
                    raise
                return ActorHandle(view["actor_id"], self._cls.__name__,
                                   is_owner=False)
        strategy, pg, bidx = _strategy_to_wire(o.get("scheduling_strategy"))
        if pg is None and o.get("placement_group") is not None:
            pg = o["placement_group"].id
            bidx = o.get("placement_group_bundle_index", -1)
        aid = core.create_actor(
            self._cls, args, kwargs,
            resources=_build_resources(o.get("num_cpus"), o.get("num_tpus"),
                                       o.get("resources")),
            name=o.get("name"),
            max_restarts=o.get("max_restarts", 0),
            max_task_retries=o.get("max_task_retries", 0),
            max_concurrency=o.get("max_concurrency", 1),
            pg=pg, bundle_index=bidx,
            detached=o.get("lifetime") == "detached",
            runtime_env=o.get("runtime_env"),
            namespace=o.get("namespace"),
            strategy=strategy,
        )
        return ActorHandle(aid, self._cls.__name__,
                           is_owner=o.get("lifetime") != "detached",
                           owner_addr=core.addr)

    def __call__(self, *a, **k):
        raise TypeError(f"actor class {self._cls.__name__} cannot be "
                        f"instantiated directly; use .remote()")


def remote(*args, **opts):
    """@ray_tpu.remote decorator for functions and classes."""

    def wrap(obj):
        if isinstance(obj, type):
            return ActorClass(obj, **opts)
        return RemoteFunction(obj, **opts)

    if len(args) == 1 and not opts and (callable(args[0]) or isinstance(args[0], type)):
        return wrap(args[0])
    if args:
        raise TypeError("@remote takes keyword options only")
    return wrap


def get_actor(name: str, namespace: str = None) -> ActorHandle:
    """Named-actor lookup.  The returned handle is WEAK (owner_addr-less):
    it neither owns nor extends the actor's lifetime, matching the
    reference — a named non-detached actor still dies when its creator's
    handles drop; use lifetime="detached" to outlive the creator."""
    core = current_core()
    view = core.get_actor_by_name(name, namespace=namespace)
    if view is None or view["state"] == "DEAD":
        raise ValueError(f"no alive actor named {name!r}")
    return ActorHandle(view["actor_id"], view.get("class_name") or "Actor")


def kill(handle: ActorHandle, no_restart: bool = True):
    current_core().kill_actor(handle._actor_id, no_restart=no_restart)
