"""CoreWorker: the in-process runtime linked into every driver and worker.

TPU-native analog of the reference's CoreWorker (reference:
src/ray/core_worker/core_worker.h:271): task submission with per-scheduling-
key lease pools (normal_task_submitter.h:75), ordered per-actor submission
queues (actor_task_submitter.h:75), ownership-based reference counting
(reference_count.h:64), in-process memory store for small/device objects
(store_provider/memory_store/), shared-memory store access for large host
objects, task retries + lineage-based object reconstruction
(task_manager.h:208, object_recovery_manager.h:41).

Design departures for TPU:
  * jax.Array values never leave the device on put(): they are held
    device-resident in the in-process store; host staging happens only if a
    borrower in another process fetches them.  Device-to-device movement
    belongs to the collective plane (compiled ICI collectives), not here.
  * Ownership is fully owner-based: the owner process serves `get_object`
    to borrowers and receives add_ref/del_ref notifications — there is no
    separate distributed directory service.
"""

from __future__ import annotations

import contextlib
import logging
import os
import threading
import time
import weakref
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Set, Tuple

import cloudpickle
import uuid

from . import common, serialization
from .common import (INLINE_OBJECT_LIMIT, STREAMING_RETURNS, ActorDiedError,
                     GetTimeoutError, ObjectLostError, RayTpuError,
                     SerializedRef, TaskCancelledError, TaskError, TaskSpec,
                     WorkerCrashedError, normalize_resources)
from .protocol import (IDEM_KEY, Backoff, Client, ConnectionLost,
                       DaemonPool, Deferred, RpcError, Server, ServerConn,
                       idem_token)
from .shm_store import ShmObjectStore

logger = logging.getLogger(__name__)

# typed flag table (reference: ray_config_def.h); RAY_TPU_* env or
# _system_config overrides
from .config import cfg as _cfg

PIPELINE_DEPTH = _cfg().pipeline_depth  # pushes per lease before waiting
DELETE_GRACE_S = _cfg().delete_grace_s
IDLE_LEASE_TTL_S = _cfg().idle_lease_ttl_s
# how long a DEAD-actor verdict from the control plane is trusted before
# submit_actor_task re-probes for a revived incarnation
DEAD_RECHECK_TTL_S = 1.0


# ---------------------------------------------------------------------------
# ObjectRef
# ---------------------------------------------------------------------------

_current_core: Optional["CoreWorker"] = None


def current_core() -> "CoreWorker":
    if _current_core is None:
        raise RuntimeError("ray_tpu is not initialized; call ray_tpu.init()")
    return _current_core


def adopt_task_context() -> None:
    """Module-level form of CoreWorker.adopt_task_context for helper
    threads spawned inside tasks (train session loops, data
    prefetchers): no-op outside a worker, never raises — THE one place
    library code should call so the blocked-CPU-lending contract stays
    in sync everywhere."""
    try:
        core = _current_core
        if core is not None and not core._shutdown:
            core.adopt_task_context()
    except Exception:
        pass


def raise_stored(err: BaseException) -> None:
    """Raise a stored (in-process-store) exception without mutating it.

    Raising the stored object directly would attach the caller's frames
    to its ``__traceback__``, creating an uncollectable cycle rooted in
    the owner's object table (entry → error → traceback → caller frame →
    ObjectRef → entry): the frame's ObjectRefs never hit refcount zero,
    so the entry — and anything else the frame holds, like actor handles
    — leaks for the life of the process."""
    import copy as _copy

    try:
        clone = _copy.copy(err)
        clone.__traceback__ = None
        clone.__cause__ = err.__cause__
        clone.__suppress_context__ = True
    except Exception:
        clone = err
    raise clone


class ObjectRef:
    """Handle to a (possibly pending) object.  Owner-based, like the
    reference's ObjectRef + ownership protocol."""

    __slots__ = ("id", "owner_addr", "owner_id", "__weakref__")

    def __init__(self, object_id: str, owner_addr, owner_id: str):
        self.id = object_id
        self.owner_addr = tuple(owner_addr) if owner_addr else None
        self.owner_id = owner_id

    def __repr__(self):
        return f"ObjectRef({self.id})"

    def __hash__(self):
        return hash(self.id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.id == self.id

    def __reduce__(self):
        raise TypeError(
            "ObjectRef can only be serialized by ray_tpu (inside task args or "
            "ray_tpu.put values), not by plain pickle."
        )

    def __del__(self):
        core = _current_core
        if core is not None and not core._shutdown:
            try:
                core._remove_local_ref(self)
            except Exception:
                pass

    def future(self):
        """concurrent.futures.Future resolving to the value."""
        core = current_core()
        return core.as_future(self)

    def __await__(self):
        """`await ref` / asyncio.gather(*refs) from async drivers and
        async actors (reference: ObjectRef.__await__, _raylet.pyx +
        async_compat.py)."""
        import asyncio

        return asyncio.wrap_future(current_core().as_future(self)).__await__()


def _marker_to_ref(marker: SerializedRef) -> ObjectRef:
    core = _current_core
    ref = ObjectRef(marker.object_id, marker.owner_addr, marker.owner_id)
    if core is not None:
        core._on_borrowed_ref(ref)
    return ref


def _ref_to_marker(ref: ObjectRef) -> SerializedRef:
    core = _current_core
    if core is not None:
        core._pin_for_serialization(ref)
    return SerializedRef(ref.id, ref.owner_addr, ref.owner_id)


serialization.install_ref_hooks(ObjectRef, _ref_to_marker, _marker_to_ref)

# Execution attribution for code running inside a task: which task is
# submitting (recursive-cancel parenting) and which driver job owns it
# (log routing for nested submissions).  contextvars, not thread-locals:
# async tasks/actor methods run as asyncio Tasks, each with its own
# context, so interleaved coroutines attribute correctly
# (worker_proc._execute / _finish set these).
import contextvars

EXECUTING_TASK_ID: contextvars.ContextVar = contextvars.ContextVar(
    "ray_tpu_executing_task_id", default=None)
EXECUTING_JOB_ID: contextvars.ContextVar = contextvars.ContextVar(
    "ray_tpu_executing_job_id", default=None)
# set while serializing a task's ARGS: actor-handle transit holds taken
# inside bind to this task and refresh while it is queued/running
TRANSIT_TASK_ID: contextvars.ContextVar = contextvars.ContextVar(
    "ray_tpu_transit_task_id", default=None)


class StreamState:
    """Owner-side bookkeeping for one streaming-generator task
    (reference: task_manager.h:355 HandleReportGeneratorItemReturns —
    per-item returns with backpressure + idempotent retries)."""

    __slots__ = ("spec", "cv", "ready", "produced", "consumed", "done",
                 "total", "error", "waiters", "closed")

    def __init__(self, spec: TaskSpec):
        self.spec = spec
        self.cv = threading.Condition()
        self.ready: deque = deque()   # indices stored, not yet handed out
        self.produced = 0             # next expected item index
        self.consumed = 0             # items handed to the user
        self.done = False
        self.total: Optional[int] = None
        self.error: Optional[BaseException] = None
        self.waiters: List = []       # deferred producer acks (backpressure)
        self.closed = False           # generator dropped by the user


class ObjectRefGenerator:
    """Iterator over the ObjectRefs of a streaming task's yields
    (reference: _raylet.pyx:281 ObjectRefGenerator).  Each __next__
    blocks until the worker reports the next item, then returns an
    ObjectRef that is immediately gettable."""

    def __init__(self, core: "CoreWorker", task_id: str):
        self._core = core
        self._task_id = task_id

    def __iter__(self):
        return self

    def __next__(self) -> ObjectRef:
        ref = self._core._next_stream_item(self._task_id, timeout=None)
        if ref is None:
            raise StopIteration
        return ref

    def next_ready(self, timeout: Optional[float] = None) -> ObjectRef:
        """Like __next__ but with a timeout (GetTimeoutError)."""
        ref = self._core._next_stream_item(self._task_id, timeout=timeout)
        if ref is None:
            raise StopIteration
        return ref

    def completed(self) -> bool:
        st = self._core.streams.get(self._task_id)
        return st is None or (st.done and not st.ready)

    def __aiter__(self):
        return self

    async def __anext__(self) -> ObjectRef:
        """Async iteration: blocks in an executor thread, not the loop."""
        import asyncio

        loop = asyncio.get_running_loop()
        ref = await loop.run_in_executor(
            None, self._core._next_stream_item, self._task_id, None)
        if ref is None:
            raise StopAsyncIteration
        return ref

    @property
    def task_id(self) -> str:
        return self._task_id

    def __del__(self):
        core = self._core
        if core is not None and not core._shutdown:
            try:
                core._release_stream(self._task_id)
            except Exception:
                pass


# ---------------------------------------------------------------------------
# In-process store entries
# ---------------------------------------------------------------------------


class ObjectEntry:
    __slots__ = ("value", "has_value", "error", "shm_node", "shm_addr", "event",
                 "pins", "lineage", "nbytes", "attempts")

    def __init__(self):
        self.value = None
        self.has_value = False
        self.error: Optional[BaseException] = None
        self.shm_node: Optional[str] = None          # node id holding shm copy
        self.shm_addr: Optional[Tuple[str, int]] = None  # that node's raylet
        self.event = threading.Event()
        self.pins = 0
        self.lineage: Optional[TaskSpec] = None
        self.nbytes = 0
        self.attempts = 0

    @property
    def ready(self) -> bool:
        return self.event.is_set()


class TaskRecord:
    __slots__ = ("spec", "pool_key", "deps", "pushed_to", "retries_left",
                 "done", "canceled", "mux", "staged_ns")

    def __init__(self, spec: TaskSpec, pool_key, retries_left: int):
        self.spec = spec
        self.pool_key = pool_key
        self.deps: Set[str] = set()
        self.pushed_to: Optional[str] = None
        self.retries_left = retries_left
        self.done = False
        self.canceled = False
        self.mux = False          # routed via the raylet submit relay
        self.staged_ns = None     # stage clock for sampled traces only


class LeasedWorker:
    def __init__(self, worker_id, addr, lease_id, node_id, raylet_addr, client):
        self.worker_id = worker_id
        self.addr = tuple(addr)
        self.lease_id = lease_id
        self.node_id = node_id
        self.raylet_addr = raylet_addr
        self.client: Client = client
        self.inflight: Set[str] = set()
        self.inflight_since: Dict[str, float] = {}  # task_id -> push ts
        self.idle_since = time.monotonic()


class SchedPool:
    """Per scheduling-key lease pool (reference: NormalTaskSubmitter's
    per-SchedulingKey worker lease pools, normal_task_submitter.h:75)."""

    def __init__(self, key):
        self.key = key
        self.queue: deque = deque()
        self.leases: Dict[str, LeasedWorker] = {}
        # rotation order for O(1) amortized lease picking: _pick_lease
        # inspects the front and rotates, so a 100k-task burst never
        # rebuilds/rescans the whole lease list per task.  Entries are
        # healed lazily — a lease removed from `leases` is dropped the
        # next time the rotation reaches it (identity check).
        self.rr: deque = deque()
        self.pending_requests = 0
        # EWMA of task execution time drives pipeline depth: tiny tasks are
        # pipelined deep (throughput), long tasks one-at-a-time so queued
        # work can land on other nodes (parallelism)
        self.avg_ms: Optional[float] = None

    def depth(self) -> int:
        if self.avg_ms is None:
            return 1
        if self.avg_ms < 2.0:
            return 16
        if self.avg_ms < 20.0:
            return 4
        return 1


# ---------------------------------------------------------------------------
# Actor bookkeeping (submitter side)
# ---------------------------------------------------------------------------


class ActorConn:
    def __init__(self, actor_id: str):
        self.actor_id = actor_id
        self.client: Optional[Client] = None   # guarded-by: lock
        self.addr = None
        self.incarnation = -1
        self.seq = 0                           # guarded-by: lock
        self.state = "PENDING"                 # guarded-by: lock
        # staging queue: specs not yet shipped.  In batched mode
        # (submit_batch > 1) submit_actor_task appends here and the
        # combining flusher drains it; in legacy mode it holds only
        # calls staged while the conn is PENDING/RECONNECTING.
        self.buffer: deque = deque()           # guarded-by: lock
        self.inflight: Dict[str, TaskSpec] = {}  # guarded-by: lock
        self.lock = threading.Lock()
        self.resolving = False
        self.dead_error: Optional[str] = None
        # monotonic deadline below which a DEAD verdict is trusted
        # without re-probing the control plane (revival-probe TTL)
        self.dead_recheck_at = 0.0             # guarded-by: lock
        self.max_task_retries = 0


class CoreWorker:
    def __init__(self, control_addr, raylet_addr=None, mode: str = "driver",
                 job: Optional[str] = None, worker_id: Optional[str] = None,
                 node_id: Optional[str] = None, store_root: Optional[str] = None,
                 namespace: Optional[str] = None, log_to_driver: bool = True):
        global _current_core
        self.mode = mode
        self.log_to_driver = log_to_driver and mode == "driver"
        self.namespace = (namespace
                          or os.environ.get("RAY_TPU_NAMESPACE")
                          or "default")
        self.worker_id = worker_id or common.worker_id()
        self.job_id = job or common.job_id()
        self.node_id = node_id
        self._shutdown = False
        self.lock = threading.RLock()

        # RPC
        self.server = Server(name=f"core-{mode}")
        self.server.handle("get_object", self.h_get_object, deferred=True)
        self.server.handle("add_ref", self.h_add_ref)
        self.server.handle("del_ref", self.h_del_ref)
        self.server.handle("actor_add_ref", self.h_actor_add_ref)
        self.server.handle("actor_del_ref", self.h_actor_del_ref)
        self.server.handle("actor_transit", self.h_actor_transit)
        self.server.handle("actor_borrow_check", self.h_actor_borrow_check)
        self.server.handle("generator_item", self.h_generator_item,
                           deferred=True)
        self.server.handle("ping", lambda c, p: "pong")
        # streaming-generator tasks owned by this process
        self.streams: Dict[str, StreamState] = {}
        # user-dropped stream ids whose producer task is still live —
        # tells a late report "stop" vs "lineage-recovery re-report,
        # accept"; entries clear when the producer's final reply lands
        self._released_streams: Set[str] = set()
        # on-demand profiling RPCs (reference: dashboard reporter agent's
        # py-spy/memray endpoints, profile_manager.py:82)
        from . import profiling

        profiling.install_handlers(self.server)
        profiling.maybe_start_tracemalloc()
        self.server.start()
        self.addr = self.server.addr

        self.control_addr = tuple(control_addr)
        # rendezvous file outranks the configured address (a driver
        # started after a failover must reach the promoted controller)
        file_addr = common.read_addr_file(
            os.environ.get("RAY_TPU_CONTROL_ADDR_FILE"))
        if file_addr and file_addr != self.control_addr:
            self.control_addr = file_addr
        self.control = Client(self.control_addr, name=f"{mode}->control",
                              on_push=self._on_control_push)
        self.raylet: Optional[Client] = None
        self.raylet_addr = None
        if raylet_addr is not None:
            self.raylet = Client(raylet_addr, name=f"{mode}->raylet",
                                 on_push=self._on_raylet_push,
                                 on_disconnect=self._on_raylet_lost)
            self.raylet_addr = tuple(raylet_addr)

        # local shm store access (same node as raylet)
        self.store: Optional[ShmObjectStore] = None
        if store_root:
            self.store = ShmObjectStore(store_root)

        # in-process object store
        self.objects: Dict[str, ObjectEntry] = {}
        self.local_ref_counts: Dict[str, int] = {}
        self.borrowed: Dict[str, SerializedRef] = {}

        # actor-handle borrow protocol (reference: distributed actor handle
        # reference counting — an actor lives while ANY handle exists, not
        # just the creator's):
        #   owner side: borrower worker-ids + in-transit serialization
        #   holds; release defers until both clear.
        #   borrower side: local handle counts per borrowed actor.
        # owner side: borrower worker-id -> [count, addr].  A COUNT, not
        # a set: add/del notifications ride the borrower's FIFO connection,
        # so counting makes a drop-to-zero racing a re-borrow on the same
        # worker net out correctly.  addr is probed for liveness while a
        # release is pending — a crashed borrower never sends actor_del_ref.
        self._actor_borrowers: Dict[str, Dict[str, list]] = {}
        # one hold deadline per in-flight serialized copy of a handle
        # aid -> {nonce: [expiry, bound_task_id|None]} (per-pickle holds)
        self._actor_transit: Dict[str, Dict[str, List]] = {}
        self._actor_pending_release: Set[str] = set()
        self._actor_probe_scheduled: Set[str] = set()
        self._borrowed_actors: Dict[str, list] = {}  # aid -> [count, owner]

        # task submission
        self.pools: Dict[Any, SchedPool] = {}
        self.task_records: Dict[str, TaskRecord] = {}  # live normal tasks
        self.functions: Dict[str, Any] = {}           # fid -> callable (exec side)
        self.registered_functions: Set[str] = set()   # fids pushed to control
        # fn object -> (fid, name); weak keys so task fns can be GC'd
        self._fn_registration_cache = weakref.WeakKeyDictionary()
        self._push_handlers: Dict[str, list] = {}
        self.actors: Dict[str, ActorConn] = {}
        self.owner_clients: Dict[Tuple[str, int], Client] = {}
        # negative cache of unreachable owner addrs (see _owner_client)
        self._owner_dead_until: Dict[Tuple[str, int], float] = {}
        # cached clients to remote raylets (see _remote_raylet_client)
        self._remote_raylets: Dict[Tuple[str, int], Client] = {}
        self.pool_executor = DaemonPool(max_workers=8, name="core")
        # object serving NEVER shares threads with scheduling: a
        # request_lease call can park its pool thread for up to 120 s on
        # the raylet's deferred grant, and with the shared pool full of
        # those, broadcast consumers waiting on h_get_object starved —
        # blocked workers lent CPU, the raylet granted MORE leases, the
        # driver parked MORE threads: a livelock (found via the 8 MiB
        # x200 broadcast-fanout envelope test)
        self.obj_serve_pool = DaemonPool(max_workers=4, name="core-obj")
        self._put_seq = 0
        self._blocked_depth = 0
        self._executing = threading.local()

        # submission batching (resolved once: these knobs sit on the
        # .remote() hot path, and cfg() rebuilds from the env per call)
        c = _cfg()
        self._submit_batch = max(1, int(c.submit_batch))
        self._lease_grant_batch = max(1, int(c.lease_grant_batch))
        self._pending_lease_cap = max(1, int(c.pending_lease_cap))
        self._small_arg_limit = int(c.small_arg_limit)
        self._small_arg_memo = int(c.small_arg_memo)
        # register_function identity fast path (cheaper than the weak-dict
        # hash when one fn is submitted in a tight loop — the common case)
        self._last_fn: Any = None
        self._last_fn_out: Optional[Tuple[str, str]] = None
        # combining submit flusher: .remote() appends to the pool queue
        # and marks the pool dirty; this thread ships whatever accumulated
        # since its last pass as framed push_tasks batches.  Batch size
        # adapts to the submission rate (busy flusher -> bigger batches).
        self._flush_cv = threading.Condition()
        self._flush_dirty: Set[SchedPool] = set()   # guarded-by: _flush_cv
        # actor conns with staged calls awaiting a flusher pass
        self._flush_dirty_actors: Set[ActorConn] = set()  # guarded-by: _flush_cv
        # multi-client submit multiplexer (raylet-side relay).  Eligible
        # plain tasks stage here instead of a SchedPool once the raylet
        # reports >=2 concurrent drivers; the flusher ships them as
        # framed mux_push_tasks envelopes and the raylet schedules them
        # without per-driver lease conversations.
        self._mux_enabled = bool(getattr(c, "submit_mux", True)) \
            and self._submit_batch > 1
        self._mux_on = False                        # guarded-by: lock
        self._mux_staged: deque = deque()           # guarded-by: lock
        self._mux_dirty = False                     # guarded-by: _flush_cv
        # telemetry: push_tasks batch-size histogram + flush-latency sums
        self._stats_lock = threading.Lock()
        self._submit_hist: Dict[int, int] = {}      # guarded-by: _stats_lock
        self._actor_hist: Dict[int, int] = {}       # guarded-by: _stats_lock
        self._actor_sends = 0                       # guarded-by: _stats_lock
        self._flush_stats = {"flushes": 0, "tasks": 0,  # guarded-by: _stats_lock
                             "latency_ms_total": 0.0, "latency_ms_max": 0.0}
        self._flush_thread = threading.Thread(
            target=self._submit_flush_loop, name="core-submit-flush",
            daemon=True)
        self._flush_thread.start()

        # task-event export (reference: task_event_buffer.h:220)
        from .task_events import NULL_BUFFER, TaskEventBuffer

        if _cfg().task_events:
            # workers relay batches through their raylet (one control
            # write per node per flush window instead of one per worker);
            # drivers and rayletless processes report directly
            transport = None
            if mode == "worker" and self.raylet is not None:
                raylet_cli = self.raylet
                transport = lambda payload: raylet_cli.notify(
                    "report_task_events", payload)
            self.task_events = TaskEventBuffer(
                self.control, worker_id=self.worker_id,
                node_id=self.node_id or "", job_id=self.job_id,
                transport=transport)
        else:
            self.task_events = NULL_BUFFER

        # distributed tracing: install this process's span collector
        # (SpanBuffer -> batched report_spans) — a no-op unless tracing
        # is already enabled or RAY_TPU_TRACE_SAMPLE asks for it
        from ray_tpu.util import tracing as _tracing

        _tracing.ensure_collector(
            self.control,
            proc=("driver" if mode == "driver"
                  else f"worker:{self.worker_id[:8]}"),
            worker_id=self.worker_id, node_id=self.node_id or "",
            job_id=self.job_id)

        if mode == "driver":
            self.control.call("register_job", {"job_id": self.job_id,
                                               "driver_pid": os.getpid()})
        self.control.call("subscribe", {"topics": self._sub_topics()})
        self._reaper = threading.Thread(target=self._lease_reaper_loop,
                                        name="core-lease-reaper", daemon=True)
        self._reaper.start()
        # as_future dispatcher (awaitable ObjectRefs)
        self._future_lock = threading.Lock()
        self._future_waiters: List[Tuple[ObjectEntry, Callable, Any]] = []
        self._future_event = threading.Event()
        self._future_thread = threading.Thread(
            target=self._future_dispatch_loop, name="core-future-dispatch",
            daemon=True)
        self._future_thread.start()
        # single delayed-deletion reaper (a Timer thread per released
        # object dominates the tiny-task hot path otherwise)
        self._delete_queue: deque = deque()
        self._delete_event = threading.Event()
        self._delete_thread = threading.Thread(
            target=self._delete_loop, name="core-object-reaper", daemon=True)
        self._delete_thread.start()
        # claim the process-global slot stack-wise: a scoped CoreWorker
        # (e.g. a test driver against its own cluster) restores the
        # previous live core on shutdown instead of stranding it
        self._prev_current_core = _current_core
        _current_core = self

    def _control_call(self, method, payload=None, timeout=30.0):
        """Control RPC with one reconnect-and-retry on connection loss.
        With a persistent control plane (reference: GCS fault tolerance)
        the daemon restarts at the same address and clients re-attach."""
        cli = self.control
        try:
            return cli.call(method, payload, timeout=timeout)
        except (ConnectionLost, OSError):
            if self._shutdown:
                raise
            self._rebuild_control(cli)
            return self.control.call(method, payload, timeout=timeout)

    def _rebuild_control(self, failed_client=None):
        with self.lock:
            # compare by identity, not by .closed: a send-path failure
            # (EPIPE in call) can precede the reader thread marking the
            # client closed — the caller's client is dead either way
            if failed_client is not None and self.control is not failed_client:
                return  # someone else already re-attached
        grace = _cfg().control_reconnect_s
        deadline = time.monotonic() + grace
        last: Optional[BaseException] = None
        bo = Backoff(_cfg().rpc_backoff_base_s, _cfg().rpc_backoff_cap_s)
        addr_file = os.environ.get("RAY_TPU_CONTROL_ADDR_FILE")
        while time.monotonic() < deadline and not self._shutdown:
            # failover re-homing: a promoted standby publishes its
            # address in the rendezvous file
            new_addr = common.read_addr_file(addr_file)
            if new_addr and new_addr != tuple(self.control_addr):
                logger.warning("control plane moved: %s -> %s",
                               self.control_addr, new_addr)
                self.control_addr = new_addr
            try:
                cli = Client(self.control_addr,
                             name=f"{self.mode}->control(re)",
                             on_push=self._on_control_push,
                             connect_timeout=2.0)
                if self.mode == "driver":
                    cli.call("register_job", {"job_id": self.job_id,
                                              "driver_pid": os.getpid()})
                cli.call("subscribe", {"topics": self._sub_topics()})
                with self.lock:
                    old, self.control = self.control, cli
                if hasattr(self.task_events, "_client"):
                    self.task_events._client = cli
                if old is not None:
                    old.close()
                logger.info("re-attached to control plane at %s",
                            self.control_addr)
                return
            except Exception as e:
                last = e
                # jittered exponential backoff: every driver and worker
                # re-attaches at once after a control restart
                bo.sleep(max_s=max(0.0, deadline - time.monotonic()))
        raise ConnectionLost(f"control plane unreachable: {last}")

    def _delete_loop(self):
        while not self._shutdown:
            if not self._delete_queue:
                self._delete_event.wait(0.5)
                self._delete_event.clear()
                continue
            try:
                # flush_pending_deletes drains concurrently: the peek and
                # the pop can both lose the race
                due, oid = self._delete_queue[0]
            except IndexError:
                continue
            now = time.monotonic()
            if due > now:
                time.sleep(min(due - now, 0.5))
                continue
            try:
                item = self._delete_queue.popleft()
            except IndexError:
                continue
            if item[0] > now:
                # raced a concurrent drain: the popped item is a FRESH
                # enqueue whose grace window has not elapsed — deleting
                # it now would shave DELETE_GRACE_S off in-flight gets
                self._delete_queue.append(item)
                continue
            try:
                self._maybe_delete(item[1])
            except Exception:
                pass

    def _submit_flush_loop(self):
        """Ship staged submissions.  One pass pumps every pool that went
        dirty since the previous pass — while this thread is busy doing
        socket sends, .remote() keeps staging, so the next pass naturally
        carries more tasks per frame (a combining flusher: batch size is
        adaptive, bounded by submit_batch, with no added latency when the
        submission rate is low)."""
        while not self._shutdown:
            with self._flush_cv:
                while (not self._flush_dirty and not self._flush_dirty_actors
                       and not self._mux_dirty and not self._shutdown):
                    self._flush_cv.wait(0.5)
                dirty, self._flush_dirty = self._flush_dirty, set()
                dirty_actors, self._flush_dirty_actors = \
                    self._flush_dirty_actors, set()
                mux_dirty, self._mux_dirty = self._mux_dirty, False
            if self._shutdown:
                return
            t0 = time.monotonic()
            for pool in dirty:
                try:
                    self._pump(pool)
                except Exception:
                    logger.exception("submit flush failed")
            for ac in dirty_actors:
                try:
                    self._flush_actor_conn(ac)
                except Exception:
                    logger.exception("actor submit flush failed")
            if mux_dirty:
                try:
                    self._flush_mux()
                except Exception:
                    logger.exception("mux submit flush failed")
            ms = (time.monotonic() - t0) * 1000.0
            with self._stats_lock:
                st = self._flush_stats
                st["flushes"] += 1
                st["latency_ms_total"] += ms
                if ms > st["latency_ms_max"]:
                    st["latency_ms_max"] = ms

    def submit_telemetry(self) -> Dict[str, Any]:
        """Snapshot of the submission-batching counters (bench/debug)."""
        with self._stats_lock:
            return {"batch_hist": dict(self._submit_hist),
                    "actor_batch_hist": dict(self._actor_hist),
                    "actor_sends": self._actor_sends,
                    "flush": dict(self._flush_stats)}

    def _lease_reaper_loop(self):
        """Return leases that have sat idle past the TTL so their resources
        free up for other clients (reference: worker lease idle timeout)."""
        while not self._shutdown:
            time.sleep(IDLE_LEASE_TTL_S / 2)
            with self.lock:
                pools = list(self.pools.values())
            for pool in pools:
                try:
                    self._maybe_return_idle_leases(pool)
                except Exception:
                    pass

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def shutdown(self):
        if self._shutdown:
            return
        # tell owners this core's borrowed actor handles are gone (a
        # crashed borrower instead leaks its registration until the owner
        # core exits; actors die with their job regardless)
        with self.lock:
            borrowed_actors = {aid: tuple(rec[1])
                               for aid, rec in self._borrowed_actors.items()}
            self._borrowed_actors.clear()
        for aid, owner_addr in borrowed_actors.items():
            if owner_addr == self.addr:
                continue
            try:
                # best-effort with a FAST connect bound: the 30s default
                # connect retry is for owners still booting; here a dead
                # owner (its actors die with it) must not stall shutdown
                # — N dead owners once cost N x 30s of teardown
                self._owner_client(owner_addr, connect_timeout=0.5).notify(
                    "actor_del_ref", {"actor_id": aid,
                                      "borrower": self.worker_id,
                                      "all": True})
            except Exception:
                pass
        self._shutdown = True
        with self._flush_cv:
            self._flush_cv.notify_all()  # wake the submit flusher to exit
        # fail pending awaited futures instead of hanging their loops
        with self._future_lock:
            waiters, self._future_waiters = self._future_waiters, []
        for _entry, _run, fut in waiters:
            if not fut.done():
                try:
                    fut.set_exception(
                        RayTpuError("ray_tpu shut down while awaiting"))
                except Exception:
                    pass
        self._future_event.set()
        global _current_core
        if _current_core is self:
            prev = self._prev_current_core
            _current_core = prev if (prev is not None
                                     and not prev._shutdown) else None
        with self.lock:
            pools = list(self.pools.values())
            actors = list(self.actors.values())
            owners = list(self.owner_clients.values())
        # withdraw our queued lease requests: granting one to a departing
        # client books resources nobody will use (conn-drop purging on
        # the raylet is the backstop for crashes)
        try:
            if self.raylet is not None:
                self.raylet.notify("cancel_lease_requests",
                                   {"client_id": self.worker_id})
        except Exception:
            pass
        # return IDLE leases explicitly, one client per granting raylet:
        # a departing driver's conn teardown also reclaims
        # (h_disconnect), but the polite return frees resources without
        # waiting for the socket.  An INFLIGHT lease is not returned —
        # recycling a worker mid-task would queue the next lessee behind
        # abandoned work; conn-drop reclaim kills those instead.
        by_raylet: Dict[Tuple, List] = {}
        for pool in pools:
            for lw in list(pool.leases.values()):
                if not lw.inflight:
                    by_raylet.setdefault(tuple(lw.raylet_addr),
                                         []).append(lw.worker_id)
        for addr, wids in by_raylet.items():
            try:
                if addr == self.raylet_addr and self.raylet is not None:
                    cli, transient = self.raylet, False
                else:
                    cli = Client(addr, name="core-return",
                                 connect_timeout=1.0)
                    transient = True
                for wid in wids:
                    cli.notify("return_lease", {"worker_id": wid})
                if transient:
                    cli.close()
            except Exception:
                pass
        for pool in pools:
            for lw in list(pool.leases.values()):
                try:
                    lw.client.close()
                except Exception:
                    pass
        for ac in actors:
            if ac.client:
                ac.client.close()
        for c in owners:
            c.close()
        try:
            self.task_events.stop()
        except Exception:
            pass
        # final span flush while the control client is still open
        from ray_tpu.util import tracing as _tracing

        _tracing.detach_collector()
        try:
            self.control.close()
        except Exception:
            pass
        if self.raylet:
            self.raylet.close()
        self.server.stop()
        self.pool_executor.shutdown(wait=False)

    # ------------------------------------------------------------------
    # put / get / wait
    # ------------------------------------------------------------------

    def _new_entry(self, oid: str) -> ObjectEntry:
        e = ObjectEntry()
        self.objects[oid] = e
        return e

    def _estimate_nbytes(self, value) -> Optional[int]:
        try:
            import sys

            jax = sys.modules.get("jax")
            if jax is not None and isinstance(value, jax.Array):
                return int(value.nbytes)
        except Exception:
            pass
        try:
            import numpy as np

            if isinstance(value, np.ndarray):
                return int(value.nbytes)
        except Exception:
            pass
        if isinstance(value, (bytes, bytearray, memoryview)):
            return len(value)
        return None

    def put(self, value) -> ObjectRef:
        with self.lock:
            self._put_seq += 1
            oid = common.put_object_id(self.worker_id, self._put_seq)
            e = self._new_entry(oid)
            e.pins = 1
            self.local_ref_counts[oid] = 1
        size = self._estimate_nbytes(value)
        is_device = False
        import sys

        jax = sys.modules.get("jax")
        if jax is not None and isinstance(value, jax.Array):
            is_device = True
        if is_device or (size is not None and size <= INLINE_OBJECT_LIMIT):
            e.value = value
            e.has_value = True
            e.nbytes = size or 0
            e.event.set()
        else:
            meta, bufs = serialization.dumps_oob(value)
            raw = [b.raw() for b in bufs]
            total = len(meta) + sum(len(b) for b in raw)
            if total <= INLINE_OBJECT_LIMIT or self.store is None:
                e.value = value
                e.has_value = True
                e.nbytes = total
                e.event.set()
            else:
                self._store_create(oid, meta, raw)
                e.shm_node = self.node_id
                e.shm_addr = self.raylet_addr
                e.nbytes = total
                e.event.set()
        return ObjectRef(oid, self.addr, self.worker_id)

    def _store_create(self, oid: str, meta: bytes, raw) -> None:
        """store.create with pressure relief: when the arena can't place
        the object in warm (already-touched) space, flush this core's
        grace-delayed deletes and retry before growing into cold pages or
        overflowing to disk files (memory pressure overrides the delete
        grace period, like the reference's eviction-under-pressure)."""
        st = self.store
        if st.create(oid, meta, raw, warm_only=True) is not None:
            return
        self.flush_pending_deletes()
        if st.create(oid, meta, raw, warm_only=True) is not None:
            return
        st.create(oid, meta, raw)

    def flush_pending_deletes(self) -> None:
        """Delete every grace-queued object NOW, and wait for the local
        raylet to drop them from the shm arena (the normal path only
        notifies) — the caller is about to retry an allocation."""
        local: List[str] = []
        while True:
            try:
                _, oid = self._delete_queue.popleft()
            except IndexError:
                break
            try:
                self._maybe_delete(oid, collect_local=local)
            except Exception:
                pass
        if local and self.raylet is not None:
            try:
                self.raylet.call("delete_objects", {"object_ids": local},
                                 timeout=10.0)
            except Exception:
                pass

    def get(self, refs, timeout: Optional[float] = None):
        single = isinstance(refs, ObjectRef)
        if single:
            refs = [refs]
        self._mark_blocked(True)
        try:
            deadline = None if timeout is None else time.monotonic() + timeout
            out = [self._get_one(r, deadline) for r in refs]
        finally:
            self._mark_blocked(False)
        return out[0] if single else out

    def _remaining(self, deadline) -> Optional[float]:
        if deadline is None:
            return None
        return max(0.0, deadline - time.monotonic())

    def _get_one(self, ref: ObjectRef, deadline):
        if not isinstance(ref, ObjectRef):
            raise TypeError(f"get() expects ObjectRef, got {type(ref)}")
        with self.lock:
            entry = self.objects.get(ref.id)
        if entry is not None:
            return self._materialize_local(ref, entry, deadline)
        return self._fetch_from_owner(ref, deadline)

    def _materialize_local(self, ref, entry: ObjectEntry, deadline):
        if not entry.event.wait(self._remaining(deadline)):
            raise GetTimeoutError(f"get() timed out waiting for {ref.id}")
        if entry.error is not None:
            raise_stored(entry.error)
        if entry.has_value:
            return entry.value
        if entry.shm_node is not None:
            value = self._read_shm_value(ref.id, entry, deadline)
            return value
        raise ObjectLostError(f"object {ref.id} has no value or location")

    def _read_shm_value(self, oid: str, entry: ObjectEntry, deadline):
        # local node?
        if self.store is not None and (entry.shm_node == self.node_id
                                       or self.store.contains(oid)):
            got = self.store.get(oid)
            if got is None and entry.shm_addr is not None:
                got = self._pull_then_get(oid, entry, deadline)
        elif entry.shm_addr is not None:
            got = self._pull_then_get(oid, entry, deadline)
        else:
            got = None
        if got is None:
            return self._recover_object(oid, entry, deadline)
        meta, bufs = got
        return serialization.loads_oob(meta, bufs)

    def _pull_then_get(self, oid, entry, deadline):
        if self.raylet is None or self.store is None:
            # no local store: fetch raw bytes via owner's raylet
            try:
                peer = Client(entry.shm_addr, name="core-pull")
                data = peer.call("fetch_object", {"object_id": oid},
                                 timeout=self._remaining(deadline) or 300.0)
                peer.close()
            except Exception:
                return None
            if data is None:
                return None
            from .shm_store import unpack

            return unpack(memoryview(data))
        try:
            ok = self.raylet.call("pull_object", {
                "object_id": oid, "from_addr": entry.shm_addr,
            }, timeout=self._remaining(deadline) or 300.0)
        except Exception:
            ok = False
        if not ok:
            return None
        return self.store.get(oid)

    def _recover_object(self, oid, entry: ObjectEntry, deadline):
        """Lineage reconstruction: resubmit the creating task
        (reference: object_recovery_manager.h:41)."""
        if entry.lineage is None:
            raise ObjectLostError(f"object {oid} lost and has no lineage")
        logger.warning("reconstructing lost object %s by resubmitting %s",
                       oid, entry.lineage.task_id)
        entry.event.clear()
        entry.shm_node = None
        entry.shm_addr = None
        self._submit_spec(entry.lineage, retries_left=1, recovery=True)
        if not entry.event.wait(self._remaining(deadline)):
            raise GetTimeoutError(f"timed out reconstructing {oid}")
        if entry.error is not None:
            raise_stored(entry.error)
        if entry.has_value:
            return entry.value
        return self._read_shm_value(oid, entry, deadline)

    def _fetch_from_owner(self, ref: ObjectRef, deadline):
        if ref.owner_addr is None:
            raise ObjectLostError(f"{ref.id}: no owner address")
        cli = self._owner_client(ref.owner_addr)
        try:
            r = cli.call("get_object", {"object_id": ref.id},
                         timeout=self._remaining(deadline))
        except ConnectionLost:
            raise ObjectLostError(f"owner of {ref.id} at {ref.owner_addr} died")
        except TimeoutError:
            raise GetTimeoutError(f"get() timed out waiting for {ref.id}")
        kind = r["kind"]
        if kind == "inline":
            meta, bufs = r["meta"], [memoryview(b) for b in r["bufs"]]
            return serialization.loads_oob(meta, bufs)
        if kind == "shm":
            entry = ObjectEntry()
            entry.shm_node = r["node_id"]
            entry.shm_addr = tuple(r["addr"]) if r["addr"] else None
            entry.event.set()
            return self._read_shm_value(ref.id, entry, deadline)
        if kind == "error":
            raise serialization.loads_inline(r["error"])
        raise ObjectLostError(f"{ref.id}: owner replied {kind}")

    def _owner_client(self, addr, connect_timeout: float = 30.0) -> Client:
        addr = tuple(addr)
        housekeeping = connect_timeout <= 5.0
        with self.lock:
            cli = self.owner_clients.get(addr)
            if cli is not None and not cli.closed:
                return cli
            dead_until = self._owner_dead_until.get(addr, 0.0)
        if housekeeping and dead_until > time.monotonic():
            # negative cache for NOTIFY flows only (short timeouts): a
            # churned-away owner (dead coordinator, exited driver) must
            # not cost every ref-release a fresh connect retry.  The
            # data path (long default timeout: get/add_ref — owners may
            # still be booting) always attempts, and success clears the
            # quarantine.
            raise ConnectionLost(f"owner {addr} recently unreachable")
        try:
            cli = Client(addr, name="core->owner",
                         connect_timeout=connect_timeout)
        except ConnectionLost:
            with self.lock:
                if len(self._owner_dead_until) > 64:
                    now = time.monotonic()
                    self._owner_dead_until = {
                        a: t for a, t in self._owner_dead_until.items()
                        if t > now}
                self._owner_dead_until[addr] = time.monotonic() + 60.0
            raise
        with self.lock:
            self._owner_dead_until.pop(addr, None)
            self.owner_clients[addr] = cli
        return cli

    def wait(self, refs: Sequence[ObjectRef], num_returns: int = 1,
             timeout: Optional[float] = None):
        if num_returns > len(refs):
            raise ValueError("num_returns > len(refs)")
        deadline = None if timeout is None else time.monotonic() + timeout
        pending = list(refs)
        ready: List[ObjectRef] = []
        self._mark_blocked(True)
        try:
            while len(ready) < num_returns:
                progressed = False
                still = []
                for r in pending:
                    with self.lock:
                        e = self.objects.get(r.id)
                    if e is not None and e.ready:
                        ready.append(r)
                        progressed = True
                    elif e is None:
                        # borrowed ref: poll owner cheaply
                        try:
                            cli = self._owner_client(r.owner_addr)
                            st = cli.call("get_object",
                                          {"object_id": r.id, "poll": True},
                                          timeout=5.0)
                            if st["kind"] != "pending":
                                ready.append(r)
                                progressed = True
                            else:
                                still.append(r)
                        except Exception:
                            ready.append(r)  # owner gone: surfaces on get
                            progressed = True
                    else:
                        still.append(r)
                pending = still
                if len(ready) >= num_returns:
                    break
                if deadline is not None and time.monotonic() >= deadline:
                    break
                if not progressed:
                    time.sleep(0.002)
        finally:
            self._mark_blocked(False)
        ready_set = {r.id for r in ready}
        returned = [r for r in refs if r.id in ready_set][:num_returns]
        returned_ids = {r.id for r in returned}
        # ready-but-not-returned refs stay in the second list (reference
        # semantics): dropping them loses objects for wait-loop consumers
        return (returned,
                [r for r in refs if r.id not in returned_ids])

    def as_future(self, ref: ObjectRef):
        """concurrent.futures.Future resolving to the ref's value.  Local
        refs park in a dispatcher (no thread held while pending — an
        async driver may gather thousands); only ready values pay a pool
        thread to materialize (shm reads can block)."""
        from concurrent.futures import Future

        fut: Future = Future()

        def run():
            try:
                res = self.get(ref)
                if not fut.cancelled():
                    fut.set_result(res)
            except BaseException as e:
                if not fut.cancelled():
                    fut.set_exception(e)

        with self.lock:
            entry = self.objects.get(ref.id)
        if entry is None:
            # borrowed ref: the owner fetch blocks start-to-finish
            self.pool_executor.submit(run)
            return fut
        with self._future_lock:
            self._future_waiters.append((entry, run, fut))
        self._future_event.set()
        return fut

    def _future_dispatch_loop(self):
        """Multiplexes pending as_future waiters over entry events."""
        while not self._shutdown:
            with self._future_lock:
                pending = list(self._future_waiters)
            if not pending:
                self._future_event.wait(0.5)
                self._future_event.clear()
                continue
            fired = [t for t in pending
                     if t[0].event.is_set() or t[2].cancelled()]
            if fired:
                with self._future_lock:
                    for t in fired:
                        try:
                            self._future_waiters.remove(t)
                        except ValueError:
                            pass
                for _entry, run, fut in fired:
                    if not fut.cancelled():
                        self.pool_executor.submit(run)
            else:
                time.sleep(0.005)

    # ------------------------------------------------------------------
    # ref counting
    # ------------------------------------------------------------------

    def _remove_local_ref(self, ref: ObjectRef):
        notify_owner = False
        with self.lock:
            if ref.id in self.objects:
                n = self.local_ref_counts.get(ref.id, 0) - 1
                self.local_ref_counts[ref.id] = n
                if n <= 0:
                    self._unpin(ref.id)
            elif ref.id in self.borrowed:
                self.borrowed.pop(ref.id, None)
                notify_owner = bool(ref.owner_addr)
        if notify_owner:
            # OUTSIDE the lock: connecting to a dead owner retries for
            # seconds, and holding the core lock through that froze the
            # entire core in 30s quanta whenever refs to a dead owner
            # (e.g. a finished split coordinator) were dropped
            try:
                self._owner_client(ref.owner_addr,
                                   connect_timeout=2.0).notify(
                    "del_ref", {"object_id": ref.id})
            except Exception:
                pass

    def _pin(self, oid: str, n: int = 1):
        with self.lock:
            e = self.objects.get(oid)
            if e is not None:
                e.pins += n

    def _unpin(self, oid: str):
        with self.lock:
            e = self.objects.get(oid)
            if e is None:
                return
            e.pins -= 1
            if e.pins <= 0:
                self._delete_queue.append(
                    (time.monotonic() + DELETE_GRACE_S, oid))
                self._delete_event.set()

    def _maybe_delete(self, oid: str, collect_local: Optional[list] = None):
        with self.lock:
            e = self.objects.get(oid)
            if e is None or e.pins > 0:
                return
            self.objects.pop(oid, None)
            self.local_ref_counts.pop(oid, None)
            shm_addr = e.shm_addr
        if shm_addr is not None:
            try:
                if shm_addr == self.raylet_addr and self.raylet is not None:
                    if collect_local is not None:
                        # pressure flush: caller batches one synchronous
                        # delete_objects call so the arena space is truly
                        # free before the allocation retry
                        collect_local.append(oid)
                    else:
                        self.raylet.notify("delete_objects",
                                           {"object_ids": [oid]})
                else:
                    Client(shm_addr, name="core-del").notify(
                        "delete_objects", {"object_ids": [oid]})
            except Exception:
                pass
        if self.store is not None:
            self.store.release(oid)

    def _on_borrowed_ref(self, ref: ObjectRef):
        if ref.id in self.objects:
            with self.lock:
                self.local_ref_counts[ref.id] = self.local_ref_counts.get(ref.id, 0) + 1
            return
        with self.lock:
            known = ref.id in self.borrowed
            self.borrowed[ref.id] = SerializedRef(ref.id, ref.owner_addr, ref.owner_id)
        if not known and ref.owner_addr:
            try:
                self._owner_client(ref.owner_addr).notify("add_ref",
                                                          {"object_id": ref.id})
            except Exception:
                pass

    def _pin_for_serialization(self, ref: ObjectRef):
        self._pin(ref.id)  # owner: pin while in flight; borrower pin is remote

    # owner-side handlers
    def h_add_ref(self, conn, p):
        self._pin(p["object_id"])
        return True

    def h_del_ref(self, conn, p):
        self._unpin(p["object_id"])
        return True

    def h_get_object(self, conn, p, d: Deferred):
        oid = p["object_id"]
        poll = p.get("poll", False)
        with self.lock:
            e = self.objects.get(oid)
        if e is None:
            d.resolve({"kind": "error", "error": serialization.dumps_inline(
                ObjectLostError(f"{oid}: unknown to owner"))})
            return
        if poll and not e.ready:
            d.resolve({"kind": "pending"})
            return
        if e.ready:
            if e.error is None and e.has_value is False \
                    and e.shm_node is not None:
                # shm redirect: a tiny dict, safe on the loop thread —
                # the hot broadcast path never waits on any pool
                self._reply_get_object(e, oid, d)
            else:
                self.obj_serve_pool.submit(self._reply_get_object, e, oid, d)
        else:
            # pending objects wait on a dedicated thread so they can never
            # starve the shared pool (lease requests, actor resolution)
            threading.Thread(target=self._wait_then_reply_get_object,
                             args=(e, oid, d), daemon=True).start()

    def _wait_then_reply_get_object(self, e: "ObjectEntry", oid: str, d: Deferred):
        while not e.event.wait(1.0):
            if self._shutdown:
                d.resolve({"kind": "error",
                           "error": serialization.dumps_inline(
                               ObjectLostError(f"{oid}: owner shut down"))})
                return
        self._reply_get_object(e, oid, d)

    def _reply_get_object(self, e: "ObjectEntry", oid: str, d: Deferred):
        try:
            if e.error is not None:
                d.resolve({"kind": "error",
                           "error": serialization.dumps_inline(e.error)})
            elif e.has_value:
                meta, bufs = serialization.dumps_oob(e.value)
                d.resolve({"kind": "inline", "meta": meta,
                           "bufs": [b.raw().tobytes() for b in bufs]})
            elif e.shm_node is not None:
                d.resolve({"kind": "shm", "node_id": e.shm_node,
                           "addr": e.shm_addr})
            else:
                d.resolve({"kind": "error", "error": serialization.dumps_inline(
                    ObjectLostError(f"{oid}: no value at owner"))})
        except Exception as ex:
            d.reject(f"get_object({oid}) failed at owner: {ex}")

    # ------------------------------------------------------------------
    # blocked notifications (nested-get deadlock avoidance)
    # ------------------------------------------------------------------

    def in_task_context(self) -> bool:
        """True on a thread currently executing (or adopted into) a task."""
        return bool(getattr(self._executing, "active", False))

    def adopt_task_context(self) -> None:
        """Mark THIS thread as part of the running task.  Helper threads a
        task spawns (e.g. data prefetchers) must call this, or their
        blocking get() never notifies the raylet and the worker's CPUs
        are not lent out while it waits (the Train+streaming deadlock).
        Library code should prefer the module-level
        `adopt_task_context()` (safe outside workers)."""
        self._executing.active = True

    def _mark_blocked(self, blocked: bool):
        if self.mode != "worker" or self.raylet is None:
            return
        if not getattr(self._executing, "active", False):
            return
        with self.lock:
            self._blocked_depth += 1 if blocked else -1
            fire = (self._blocked_depth == 1) if blocked else (self._blocked_depth == 0)
        if fire:
            try:
                self.raylet.notify("task_blocked" if blocked else "task_unblocked",
                                   {"worker_id": self.worker_id})
            except Exception:
                pass

    # ------------------------------------------------------------------
    # functions
    # ------------------------------------------------------------------

    def register_function(self, fn) -> Tuple[str, str]:
        # hot path: hashing cloudpickles the function, so memoize per
        # function object (the reference's function table is likewise
        # populated once per unique function, not per .remote() call).
        # Identity guard first: a tight .remote() loop over one function
        # skips even the weak-dict hash.
        if fn is self._last_fn:
            return self._last_fn_out
        try:
            cached = self._fn_registration_cache.get(fn)
        except TypeError:  # unhashable callables fall through
            cached = None
        if cached is not None:
            self._last_fn = fn
            self._last_fn_out = cached
            return cached
        fid, blob = common.hash_function(fn)
        with self.lock:
            new = fid not in self.registered_functions
            if new:
                self.registered_functions.add(fid)
                self.functions[fid] = fn
        if new:
            self._control_call("register_function", {"function_id": fid, "blob": blob})
        out = (fid, getattr(fn, "__qualname__", str(fn)))
        try:
            self._fn_registration_cache[fn] = out
        except TypeError:
            pass
        self._last_fn = fn
        self._last_fn_out = out
        return out

    def get_function(self, fid: str):
        with self.lock:
            fn = self.functions.get(fid)
        if fn is not None:
            return fn
        blob = self._control_call("get_function", {"function_id": fid}, timeout=30.0)
        if blob is None:
            raise RuntimeError(f"function {fid} not found in cluster function table")
        fn = cloudpickle.loads(blob)
        with self.lock:
            self.functions[fid] = fn
        return fn

    # ------------------------------------------------------------------
    # normal task submission
    # ------------------------------------------------------------------

    _EMPTY_ARGS_BLOB = serialization.dumps_inline(((), {}))
    _DEFAULT_RESOURCES = normalize_resources({common.CPU: 1})

    def serialize_args(self, args, kwargs,
                       task_id: Optional[str] = None) -> bytes:
        if not args and not kwargs:
            return self._EMPTY_ARGS_BLOB  # no-arg calls skip pickling
        if not kwargs and type(args) is tuple:
            # small-arg shortcut: plain scalars/bytes/ObjectRefs skip the
            # CloudPickler framing entirely (ref pin bookkeeping still
            # runs; actor handles are ineligible so no transit holds are
            # skipped).  None = ineligible, fall through to the full path.
            blob = serialization.dumps_args_small(
                args, limit=self._small_arg_limit,
                memo_cap=self._small_arg_memo)
            if blob is not None:
                return blob
        if task_id is None:
            return serialization.dumps_inline((args, kwargs))
        # actor handles pickled inside these args take transit holds
        # bound to this task: they refresh while the task is queued
        token = TRANSIT_TASK_ID.set(task_id)
        try:
            return serialization.dumps_inline((args, kwargs))
        finally:
            TRANSIT_TASK_ID.reset(token)

    def submit_task(self, fn, args, kwargs, *, num_returns=1, resources=None,
                    max_retries=3, strategy=None, pg=None, bundle_index=-1,
                    name="", runtime_env=None, generator_backpressure=0):
        if num_returns == "streaming":
            num_returns = STREAMING_RETURNS
        if runtime_env:
            from . import runtime_env as rtenv

            runtime_env = rtenv.prepare(runtime_env, self.control)
        fid, fname = self.register_function(fn)
        tid = common.task_id()
        spec = TaskSpec(
            task_id=tid,
            function_id=fid,
            function_name=name or fname,
            args_blob=self.serialize_args(args, kwargs, task_id=tid),
            num_returns=num_returns,
            # the default-resources dict is shared across specs (never
            # mutated downstream: _pool_key and the lease path only read
            # it, and the wire copy is a pickle)
            resources=(self._DEFAULT_RESOURCES if resources is None
                       else normalize_resources(resources)),
            max_retries=max_retries,
            scheduling_strategy=strategy,
            placement_group_id=pg,
            placement_bundle_index=bundle_index,
            owner_id=self.worker_id,
            owner_addr=self.addr,
            runtime_env=runtime_env,
            parent_task_id=EXECUTING_TASK_ID.get(),
            generator_backpressure=generator_backpressure,
            # nested tasks keep the ROOT driver's job so their logs
            # route to that driver (a worker core's own job_id is random)
            job_id=EXECUTING_JOB_ID.get() or self.job_id,
        )
        from ray_tpu.util import tracing

        if tracing.is_enabled():
            with tracing.submit_span("task", spec.function_name):
                spec.trace_ctx = tracing.inject_context()
        return self._submit_spec(spec, retries_left=max_retries)

    @staticmethod
    def _trace_stage_ns(carrier) -> Optional[int]:
        """Stage-clock read for the driver.stage_wait phase — taken only
        for specs riding a sampled trace, so the untraced hot path pays
        one None check."""
        if carrier is None:
            return None
        from ray_tpu.util import tracing

        if tracing.carrier_sampled(carrier):
            return time.time_ns()
        return None

    def _submit_spec(self, spec: TaskSpec, retries_left: int,
                     recovery: bool = False):
        # recovery resubmission of a streaming spec: the stream is long
        # consumed — re-executed items land straight into their awaited
        # object entries (h_generator_item fallback), no StreamState
        if spec.num_returns == STREAMING_RETURNS and not recovery \
                and spec.task_id not in self.streams:
            self.streams[spec.task_id] = StreamState(spec)
        refs = []
        key = self._pool_key(spec)
        rec = TaskRecord(spec, key, retries_left)
        rec.staged_ns = self._trace_stage_ns(spec.trace_ctx)
        # ONE lock acquisition for all submission bookkeeping: this path
        # runs once per .remote() and ping-pongs the core lock with the
        # reply thread during 100k-task bursts
        pool = None
        with self.lock:
            for oid in spec.return_ids():
                e = self.objects.get(oid)
                if e is None:
                    e = self._new_entry(oid)
                    self.local_ref_counts[oid] = 0
                # every ObjectRef we hand out counts, including the ones the
                # reconstruction path discards — their __del__ decrements
                self.local_ref_counts[oid] += 1
                e.pins = max(e.pins, 1)
                e.lineage = spec
                e.attempts += 1
                refs.append(ObjectRef(oid, self.addr, self.worker_id))
            if self._mux_on and self._mux_eligible(spec):
                # relay mode: the raylet schedules this task itself, no
                # per-driver lease conversation.  Staged specs still live
                # in task_records so cancel()/liveness checks see them.
                rec.mux = True
                self._mux_staged.append(rec)
            else:
                pool = self.pools.get(key)
                if pool is None:
                    pool = self.pools[key] = SchedPool(key)
                pool.queue.append(rec)
            self.task_records[spec.task_id] = rec  # cancel() lookup
        self.task_events.record_submit(
            spec.task_id, spec.function_name, "NORMAL_TASK")
        if pool is None:
            with self._flush_cv:
                self._mux_dirty = True
                self._flush_cv.notify()
        elif self._submit_batch <= 1:
            # escape hatch: bypass the combining flusher, ship inline
            # exactly like the pre-batching path
            self._pump(pool)
        else:
            # hand the pump to the combining flusher; by the time it runs,
            # a tight .remote() loop has queued more work and the whole
            # backlog ships as framed push_tasks batches
            with self._flush_cv:
                self._flush_dirty.add(pool)
                self._flush_cv.notify()
        if spec.num_returns == STREAMING_RETURNS and not recovery:
            return [ObjectRefGenerator(self, spec.task_id)]
        return refs

    def _pool_key(self, spec: TaskSpec):
        strat = spec.scheduling_strategy
        # retriability is part of the key so a lease's OOM-victim hint
        # (request_lease "retriable") holds for every task it ever serves
        return (tuple(sorted(spec.resources.items())),
                spec.placement_group_id, spec.placement_bundle_index,
                repr(strat) if strat else None,
                spec.max_retries > 0)

    def _pump(self, pool: SchedPool):
        to_push: List[Tuple[LeasedWorker, TaskRecord]] = []
        request_new = 0
        with self.lock:
            while pool.queue:
                lw = self._pick_lease(pool)
                if lw is None:
                    # every lease is saturated (or stalled on a slow task):
                    # aim for one outstanding lease request per queued task
                    # so queued work can run in parallel instead of
                    # stacking behind busy workers.  The whole shortfall is
                    # charged at once and served by ONE vectorized
                    # request_leases round-trip (capped per request).
                    cap = min(len(pool.queue), self._pending_lease_cap)
                    if pool.pending_requests < cap:
                        request_new = min(cap - pool.pending_requests,
                                          self._lease_grant_batch)
                        pool.pending_requests += request_new
                    break
                rec = pool.queue.popleft()
                rec.pushed_to = lw.worker_id
                lw.inflight.add(rec.spec.task_id)
                lw.inflight_since[rec.spec.task_id] = time.monotonic()
                to_push.append((lw, rec))
        if to_push:
            self._push_batched(pool, to_push)
        if request_new:
            self.pool_executor.submit(self._request_lease, pool, request_new)

    PIPELINE_STALL_S = 0.1

    def _pick_lease(self, pool: SchedPool) -> Optional[LeasedWorker]:
        """O(1) amortized pick over the rotation deque: inspect the front
        lease, rotate, return the first one with pipeline room.  The old
        per-task rebuild of list(pool.leases.values()) re-scanned every
        lease per submitted task — O(leases) per pick.  Rotation spreads
        work round-robin, which converges to the same balance the
        least-loaded scan produced (depth caps per-lease load either
        way).  Worst case (all saturated/stalled) is one full rotation,
        identical to the old scan."""
        depth = pool.depth()
        now = time.monotonic()
        # The EWMA depth is a *prediction*; a worker whose oldest
        # in-flight task has overrun the expected full-pipeline drain
        # time (2x slack) is evidence the prediction is stale — e.g. a
        # long task after a burst of tiny ones.  Don't stack more work
        # behind it; the caller leases another worker instead.
        stall_s = max(self.PIPELINE_STALL_S,
                      (pool.avg_ms or 0.0) * depth * 2 / 1000.0)
        rr = pool.rr
        for _ in range(len(rr)):
            lw = rr[0]
            rr.rotate(-1)  # the inspected lease is now at the back
            if pool.leases.get(lw.worker_id) is not lw:
                rr.pop()   # removed elsewhere: heal the rotation lazily
                continue
            if lw.client is not None and lw.client.closed:
                pool.leases.pop(lw.worker_id, None)
                rr.pop()
                continue
            n = len(lw.inflight)
            if n >= depth:
                continue
            # dict preserves insertion order and push timestamps are
            # monotonic, so the first inflight_since value IS the oldest
            if n and lw.inflight_since and \
                    now - next(iter(lw.inflight_since.values())) > stall_s:
                continue
            return lw
        return None

    @staticmethod
    def _strategy_is_hard(strategy) -> bool:
        """True when the strategy forbids running on an arbitrary node."""
        if not isinstance(strategy, dict):
            return False
        kind = strategy.get("kind")
        if kind == "node_label":
            return True
        if kind == "node_affinity":
            return not strategy.get("soft")
        return False

    def _remote_raylet_client(self, addr) -> Client:
        """One cached client per remote raylet (reference: the raylet
        client pool): a fresh conn per lease request would leak a socket
        + two threads each, and the remote raylet's reclaim/disconnect
        tracking keys off the conn — churning conns per request would
        false-signal client death on any one socket error."""
        addr = tuple(addr)
        with self.lock:
            cli = self._remote_raylets.get(addr)
            if cli is not None and not cli.closed:
                return cli
        cli = Client(addr, name="core->remote-raylet",
                     on_push=self._on_raylet_push)
        with self.lock:
            self._remote_raylets[addr] = cli
        return cli

    def _request_lease(self, pool: SchedPool, count: int = 1):
        """Acquire up to `count` leases for this pool in one vectorized
        round-trip: pick_nodes reserves the placements at the control
        plane, then each chosen raylet serves its whole share via a
        single request_leases RPC.  _pump pre-charged pending_requests by
        `count`; it is decremented by exactly `count` here on every path
        (partial grants simply leave the shortfall for the next _pump)."""
        from ray_tpu.util import tracing

        carrier = None
        if tracing.is_enabled():
            with self.lock:
                spec0 = pool.queue[0].spec if pool.queue else None
            carrier = spec0.trace_ctx if spec0 is not None else None
        outcome, err = "error", None
        try:
            # the lease phase span covers pick_nodes + request_leases;
            # their CLIENT rpc spans nest under it via the contextvar
            with tracing.phase_span("driver.lease", carrier, count=count):
                outcome = self._request_lease_inner(pool, count)
        except Exception as e:
            err = e
        finally:
            with self.lock:
                pool.pending_requests -= count
                had_queue = bool(pool.queue)
        if self._shutdown or not had_queue or outcome == "canceled":
            return
        if outcome == "ok":
            self._pump(pool)
        elif outcome == "reprobe":
            # no node satisfies the hard constraint right now: stay
            # pending and re-probe (falling back to the local raylet
            # would violate the strategy — reference keeps such tasks
            # queued as demand)
            def reprobe():
                time.sleep(0.5)
                self._pump(pool)

            self.pool_executor.submit(reprobe)
        else:
            logger.warning("lease request failed (%s); retrying", err)
            time.sleep(0.2)
            self._pump(pool)

    def _request_lease_inner(self, pool: SchedPool, count: int) -> str:
        resources = dict(pool.key[0])
        pg_id, bundle_index = pool.key[1], pool.key[2]
        strategy = None
        spec0 = None
        with self.lock:
            if pool.queue:
                spec0 = pool.queue[0].spec
        if spec0 is not None:
            strategy = spec0.scheduling_strategy
        if pg_id:
            strategy = {"kind": "placement_group", "pg_id": pg_id,
                        "bundle_index": bundle_index}
        demand = common.denormalize_resources(dict(resources))
        picked = self._control_call("pick_nodes", {
            "resources": demand,
            "strategy": strategy,
            "count": count,
        }, timeout=30.0)
        if not picked:
            if self._strategy_is_hard(strategy):
                return "reprobe"
            # soft/no strategy with nothing reserved: aim the whole batch
            # at the local raylet (mirrors the old single-lease fallback)
            picked = [None] * count
        # one request_leases RPC per granting raylet, carrying its share
        shares: Dict[Optional[Tuple], int] = {}
        for pk in picked:
            addr = tuple(pk["addr"]) if pk is not None else None
            shares[addr] = shares.get(addr, 0) + 1
        got_any = False
        canceled = False
        for addr, share in shares.items():
            raylet_addr = self.raylet_addr
            raylet_cli = self.raylet
            if addr is not None and addr != self.raylet_addr:
                raylet_addr = addr
                raylet_cli = self._remote_raylet_client(addr)
            if raylet_cli is None:
                raise RuntimeError("no raylet available for lease request")
            payload = {"resources": demand,
                       "client_id": self.worker_id,
                       "count": share,
                       # OOM-victim hint (reference retriable-FIFO policy):
                       # whether the work heading for this lease can be
                       # retried if the raylet kills the worker
                       "retriable": (spec0.max_retries > 0
                                     if spec0 is not None else True)}
            if pg_id:
                payload["bundle"] = (pg_id, bundle_index)
            # Idempotency token: if the connection drops after the raylet
            # granted the leases but before the reply lands, the blind
            # retry below replays the SAME request and the raylet's replay
            # cache answers with the original grant — a retry can never
            # double-place a lease.
            payload[IDEM_KEY] = idem_token()
            lease_deadline = time.monotonic() + 120.0
            bo = Backoff(_cfg().rpc_backoff_base_s,
                         _cfg().rpc_backoff_cap_s)
            while True:
                try:
                    r = raylet_cli.call(
                        "request_leases", payload,
                        timeout=max(1.0, lease_deadline - time.monotonic()))
                    break
                except (ConnectionLost, OSError) as lease_err:
                    if self._shutdown or time.monotonic() >= lease_deadline:
                        raise
                    logger.warning("request_leases connection lost (%s); "
                                   "replaying with idempotency token",
                                   lease_err)
                    bo.sleep(max_s=max(
                        0.0, lease_deadline - time.monotonic()))
                    if raylet_addr != self.raylet_addr:
                        raylet_cli = self._remote_raylet_client(raylet_addr)
                    elif self.raylet is not None \
                            and not self.raylet.closed:
                        raylet_cli = self.raylet
            if not (r and r.get("ok")):
                if r and r.get("canceled"):
                    canceled = True
                    continue
                raise RuntimeError(f"lease request failed: {r}")
            if r.get("mux") and self._mux_enabled \
                    and addr in (None, self.raylet_addr):
                # the local raylet sees multiple concurrent submitters:
                # route future eligible submissions through the relay
                self._mux_flip_on()
            node_id = r["node_id"]
            for g in r.get("grants", []):
                with self.lock:
                    unneeded = not pool.queue
                if unneeded:
                    # queue drained while the grant was pending: hand the
                    # rest of the vector back
                    try:
                        raylet_cli.notify("return_lease",
                                          {"worker_id": g["worker_id"]})
                    except Exception:
                        pass
                    continue
                lw = LeasedWorker(g["worker_id"], g["worker_addr"],
                                  g["lease_id"], node_id, raylet_addr, None)
                lw.client = Client(
                    lw.addr, name="core->leased",
                    on_disconnect=lambda pool=pool, lw=lw:
                        self._on_worker_lost(pool, lw),
                    on_push=lambda topic, payload, pool=pool, lw=lw:
                        self._on_lease_push(pool, lw, topic, payload))
                with self.lock:
                    pool.leases[lw.worker_id] = lw
                    pool.rr.append(lw)
                got_any = True
        if got_any:
            return "ok"
        return "canceled" if canceled else "ok"

    def _push_task(self, lw: LeasedWorker, rec: TaskRecord, pool: SchedPool):
        """Legacy single-task push (submit_batch <= 1 escape hatch):
        one call_cb round-trip per task, reply handled per task."""
        def on_reply(reply, exc):
            if exc is not None:
                self._on_task_failure(pool, lw, rec, exc)
                return
            self._on_task_reply(pool, lw, rec, reply)

        lw.client.call_cb("push_task", rec.spec, on_reply)

    def _trace_flush_cm(self, chunk: List[TaskRecord], transport: str):
        """Sampled-trace bookkeeping for one shipped batch: emit a retro
        driver.stage_wait span per sampled rec (staged -> picked up by
        the combining flusher), then return a driver.flush_batch span
        contextmanager parented to the first sampled trace in the chunk,
        annotated with batch size and payload bytes.  nullcontext when
        tracing is off or nothing in the chunk is sampled."""
        from ray_tpu.util import tracing

        if not tracing.is_enabled():
            return contextlib.nullcontext()
        carrier = None
        now_ns = time.time_ns()
        for rec in chunk:
            if rec.staged_ns is None:
                continue
            tracing.record_span(
                "driver.stage_wait", "INTERNAL", rec.staged_ns, now_ns,
                tracing._extract(rec.spec.trace_ctx), batch=len(chunk))
            rec.staged_ns = None   # a retried rec must not re-report
            if carrier is None:
                carrier = rec.spec.trace_ctx
        if carrier is None:
            return contextlib.nullcontext()
        payload_bytes = sum(len(rec.spec.args_blob or b"")
                            for rec in chunk)
        return tracing.phase_span(
            "driver.flush_batch", carrier, batch=len(chunk),
            payload_bytes=payload_bytes, transport=transport)

    def _push_batched(self, pool: SchedPool,
                      to_push: List[Tuple[LeasedWorker, TaskRecord]]):
        """Ship the picked (lease, task) pairs.  Batched mode groups by
        lease and frames up to submit_batch specs per one-way push_tasks
        notify — O(bytes) on the wire, no per-task reply slot; the worker
        acks via coalesced tasks_done pushes instead."""
        if self._submit_batch <= 1:
            for lw, rec in to_push:
                self._push_task(lw, rec, pool)
            return
        groups: Dict[str, Tuple[LeasedWorker, List[TaskRecord]]] = {}
        for lw, rec in to_push:   # dict keeps insertion order = FIFO
            groups.setdefault(lw.worker_id, (lw, []))[1].append(rec)
        for lw, recs in groups.values():
            for i in range(0, len(recs), self._submit_batch):
                chunk = recs[i:i + self._submit_batch]
                with self._stats_lock:
                    h = self._submit_hist
                    h[len(chunk)] = h.get(len(chunk), 0) + 1
                    self._flush_stats["tasks"] += len(chunk)
                try:
                    with self._trace_flush_cm(chunk, "lease"):
                        lw.client.notify("push_tasks",
                                         [rec.spec for rec in chunk])
                except (ConnectionLost, OSError) as e:
                    # synchronous failure only (conn already closed at
                    # enqueue); async write failures surface through the
                    # client's on_disconnect -> _on_worker_lost
                    for rec in chunk:
                        self._on_task_failure(pool, lw, rec, e)

    def _on_lease_push(self, pool: SchedPool, lw: LeasedWorker,
                       topic: str, payload):
        """Server-push from a leased worker (reader thread)."""
        if topic == "tasks_done":
            self._on_tasks_done(pool, lw, payload)

    def _on_task_reply(self, pool, lw: LeasedWorker, rec: TaskRecord, reply):
        self._on_tasks_done(pool, lw, [(rec.spec.task_id, reply)])

    def _on_tasks_done(self, pool: SchedPool, lw: LeasedWorker, items):
        """Handle a coalesced batch of task completions from one lease.
        ONE lock acquisition for the whole batch's bookkeeping: this path
        ping-pongs the core lock with the submitting thread — every extra
        acquire/release pair is contention at 100k-task submission
        bursts — and one _pump refills the freed pipeline slots for all
        completions at once."""
        finished: List[Tuple[TaskRecord, Dict[str, Any]]] = []
        with self.lock:
            for task_id, reply in items:
                lw.inflight.discard(task_id)
                lw.inflight_since.pop(task_id, None)
                ms = reply.get("exec_ms")
                if ms is not None:
                    pool.avg_ms = ms if pool.avg_ms is None else \
                        0.8 * pool.avg_ms + 0.2 * ms
                rec = self.task_records.get(task_id)
                if rec is None or rec.done:
                    continue   # late duplicate (e.g. post-retry ack)
                rec.done = True
                self.task_records.pop(task_id, None)
                finished.append((rec, reply))
            lw.idle_since = time.monotonic()
        for rec, reply in finished:
            self._released_streams.discard(rec.spec.task_id)
            if rec.canceled and reply.get("status") != "ok":
                # the worker raised out of the injected cancellation:
                # surface TaskCancelledError, not the interrupt artifact
                reply = {"status": "error",
                         "error": serialization.dumps_inline(
                             TaskCancelledError(
                                 f"task {rec.spec.function_name} "
                                 f"was cancelled"))}
            self._store_results(rec.spec, reply)
            if rec.spec.num_returns == STREAMING_RETURNS:
                self._finish_stream(rec.spec.task_id, reply)
        self._pump(pool)
        self._maybe_return_idle_leases(pool)

    # -- streaming generators (owner side) --------------------------------
    # reference: task_manager.h:355 HandleReportGeneratorItemReturns +
    # _raylet.pyx:281 ObjectRefGenerator

    def h_generator_item(self, conn, p, d):
        """A worker reports one yielded item of a streaming task.  The
        reply is the producer's backpressure ack: deferred while too many
        items sit unconsumed; {"stop": True} tells the producer to quit
        (stream closed/cancelled/unknown)."""
        tid, index = p["task_id"], p["index"]
        st = self.streams.get(tid)
        if st is None or st.closed:
            if (st is not None and st.closed) \
                    or tid in self._released_streams:
                # the consumer explicitly dropped the generator: stop
                d.resolve({"ok": False, "stop": True})
                return
            with self.lock:
                recovering = tid in self.task_records
            if not recovering:
                # not released, not recovering: a stray report from a
                # long-finished stream — nothing wants it
                d.resolve({"ok": False, "stop": True})
                return
            # lineage-recovery re-execution of a consumed stream — store
            # items someone is waiting on, ack the rest to completion
            oid = common.object_id_for_return(tid, index)
            with self.lock:
                e = self.objects.get(oid)
            if e is not None and not e.ready:
                self._store_one(e, p["result"])
            d.resolve({"ok": True})
            return
        ack = None
        with st.cv:
            if index < st.produced:
                # duplicate from a retry/recovery attempt: usually
                # already stored, but a lost-and-reconstructing item's
                # entry was reset (event cleared) — re-store those
                oid = common.object_id_for_return(tid, index)
                with self.lock:
                    e = self.objects.get(oid)
                if e is not None and not e.ready:
                    self._store_one(e, p["result"])
                ack = {"ok": True}
            else:
                oid = common.object_id_for_return(tid, index)
                with self.lock:
                    e = self.objects.get(oid) or self._new_entry(oid)
                    e.pins = max(e.pins, 1)
                    e.lineage = st.spec
                    self.local_ref_counts.setdefault(oid, 0)
                self._store_one(e, p["result"])
                st.produced = index + 1
                st.ready.append(index)
                st.cv.notify_all()
                bp = st.spec.generator_backpressure
                if bp and (st.produced - st.consumed) >= bp:
                    st.waiters.append(d)   # ack later, when consumed
                else:
                    ack = {"ok": True}
        if ack is not None:
            # the ack is a framed socket send (sock.sendall can block on
            # a slow worker): never do it while holding st.cv, or every
            # consumer in _next_stream_item stalls behind that socket
            d.resolve(ack)

    def _next_stream_item(self, tid: str, timeout: Optional[float]):
        """Blocking pop of the next stream index -> ObjectRef (None =
        exhausted)."""
        st = self.streams.get(tid)
        if st is None:
            return None
        deadline = None if timeout is None else time.monotonic() + timeout
        with st.cv:
            while True:
                if st.ready:
                    index = st.ready.popleft()
                    st.consumed += 1
                    # consumption opens backpressure windows
                    waiters, st.waiters = st.waiters, []
                    break
                if st.error is not None and st.done:
                    err = st.error
                    raise_stored(err)
                if st.done:
                    self.streams.pop(tid, None)
                    return None
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise GetTimeoutError(
                        f"streaming task {tid} produced no item in time")
                st.cv.wait(remaining if remaining is not None else 0.5)
        for w in waiters:
            try:
                w.resolve({"ok": True})
            except Exception:
                pass
        oid = common.object_id_for_return(tid, index)
        with self.lock:
            self.local_ref_counts[oid] = \
                self.local_ref_counts.get(oid, 0) + 1
        return ObjectRef(oid, self.addr, self.worker_id)

    def _finish_stream(self, tid: str, reply: Dict[str, Any]):
        st = self.streams.get(tid)
        if st is None:
            return
        with st.cv:
            st.done = True
            if reply.get("status") == "ok":
                st.total = reply.get("streaming_done", st.produced)
            else:
                try:
                    st.error = serialization.loads_inline(reply["error"])
                except Exception as e:
                    st.error = RayTpuError(f"stream failed: {e}")
            waiters, st.waiters = st.waiters, []
            st.cv.notify_all()
        for w in waiters:
            try:
                w.resolve({"ok": False, "stop": True})
            except Exception:
                pass

    def _fail_stream(self, tid: str, err: BaseException):
        self._finish_stream(tid, {
            "status": "error", "error": serialization.dumps_inline(err)})

    def _release_stream(self, tid: str):
        """Generator dropped by the user: stop the producer and release
        never-consumed items."""
        st = self.streams.pop(tid, None)
        if st is None:
            return
        # remember the drop so late/retried item reports are told to stop
        # (vs. lineage-recovery re-reports, which must be accepted);
        # cleared when the producer's final reply arrives
        self._released_streams.add(tid)
        with st.cv:
            st.closed = True
            pending = list(st.ready)
            st.ready.clear()
            waiters, st.waiters = st.waiters, []
            st.cv.notify_all()
        for w in waiters:
            try:
                w.resolve({"ok": False, "stop": True})
            except Exception:
                pass
        for index in pending:
            oid = common.object_id_for_return(tid, index)
            with self.lock:
                if self.local_ref_counts.get(oid, 0) <= 0:
                    self._unpin(oid)

    def _store_one(self, e: ObjectEntry, result):
        """Store one (kind, payload) wire result into an entry."""
        kind, payload = result
        if kind == "inline":
            meta, bufs = payload
            try:
                e.value = serialization.loads_oob(
                    meta, [memoryview(b) for b in bufs])
                e.has_value = True
            except BaseException as ex:
                e.error = ex
        else:  # shm
            e.shm_node = payload["node_id"]
            e.shm_addr = tuple(payload["addr"])
            e.nbytes = payload.get("nbytes", 0)
        e.event.set()

    def _store_results(self, spec: TaskSpec, reply: Dict[str, Any]):
        status = reply.get("status")
        results = reply.get("results", [])
        for i, oid in enumerate(spec.return_ids()):
            with self.lock:
                e = self.objects.get(oid)
                if e is None:
                    continue
            if status == "ok":
                self._store_one(e, results[i])
            else:
                err = serialization.loads_inline(reply["error"])
                e.error = err
                e.event.set()

    def _on_task_failure(self, pool, lw: LeasedWorker, rec: TaskRecord, exc):
        """Worker died or connection lost mid-task: retry or error out
        (reference: TaskManager retry bookkeeping, task_manager.h:208)."""
        with self.lock:
            # idempotency guard: a lost worker can report the same task
            # through two paths (pending-call ConnectionLost callback in
            # legacy mode AND _on_worker_lost's sweep) — only the first
            # claim for this (task, worker) assignment acts
            if rec.done or rec.pushed_to != lw.worker_id:
                return
            rec.pushed_to = None
            lw.inflight.discard(rec.spec.task_id)
            lw.inflight_since.pop(rec.spec.task_id, None)
            if lw.client is not None and lw.client.closed:
                pool.leases.pop(lw.worker_id, None)
        if rec.retries_left > 0 and not self._shutdown and not rec.canceled:
            rec.retries_left -= 1
            logger.warning("task %s failed on %s (%s); retrying (%d left)",
                           rec.spec.task_id[:12], lw.worker_id[:12], exc,
                           rec.retries_left)
            with self.lock:
                pool.queue.append(rec)
            self._pump(pool)
        else:
            with self.lock:
                self.task_records.pop(rec.spec.task_id, None)
            self._released_streams.discard(rec.spec.task_id)
            if rec.canceled:
                err: BaseException = TaskCancelledError(
                    f"task {rec.spec.function_name} was cancelled")
            else:
                err = WorkerCrashedError(
                    f"task {rec.spec.function_name} failed: worker died ({exc})")
            self.task_events.record_status(
                rec.spec.task_id, "FAILED", name=rec.spec.function_name,
                error=str(err))
            for oid in rec.spec.return_ids():
                with self.lock:
                    e = self.objects.get(oid)
                if e is not None:
                    e.error = err
                    e.event.set()
            if rec.spec.num_returns == STREAMING_RETURNS:
                self._fail_stream(rec.spec.task_id, err)

    def _on_worker_lost(self, pool: SchedPool, lw: LeasedWorker):
        with self.lock:
            pool.leases.pop(lw.worker_id, None)
            lost = list(lw.inflight)
            recs = [self.task_records.get(t) for t in lost]
        # batched pushes are one-way notifies with no per-task reply slot,
        # so a dead connection surfaces ONLY here: sweep every in-flight
        # task into the retry/error path.  In legacy (submit_batch<=1)
        # mode the pending call_cb futures also fire ConnectionLost —
        # _on_task_failure's pushed_to guard keeps the two claims from
        # double-handling a task.
        err = ConnectionLost(f"worker {lw.worker_id} connection lost")
        for rec in recs:
            if rec is not None and not rec.done:
                self._on_task_failure(pool, lw, rec, err)

    def _on_raylet_push(self, topic, payload):
        """Raylet -> core notifications (worker_proc forwards unhandled
        worker-level pushes here)."""
        if topic == "reclaim_idle_leases":
            # off the push thread: returning leases does RPCs
            self.pool_executor.submit(self.flush_idle_leases)
        elif topic == "submit_mux":
            if self._mux_enabled and payload.get("on"):
                self._mux_flip_on()
        elif topic == "mux_tasks_done":
            self._on_mux_tasks_done(payload)
        elif topic == "mux_task_failed":
            self._on_mux_task_failed(payload)

    # ------------------------------------------------------------------
    # multi-client submit multiplexer (driver side).  The raylet flips
    # mux on when it observes >=2 concurrent external submitters; from
    # then on eligible plain tasks ship as framed mux_push_tasks
    # notifies on the ONE existing driver->raylet connection and the
    # raylet schedules them itself — N drivers stop holding N separate
    # pick_nodes/request_leases/push conversations with the control
    # plane and each other's reclaim storms.
    # ------------------------------------------------------------------

    def _mux_flip_on(self):
        """First submit_mux signal: route future eligible submissions
        through the relay AND migrate eligible tasks already staged in
        classic pools.  On a saturated node the relay can hold every
        worker slot, so a task parked in a pool behind an unanswered
        lease request would otherwise starve until the relay queue
        drains; moving it keeps one burst from straddling both paths."""
        moved = False
        with self.lock:
            if self._mux_on:
                return
            self._mux_on = True
            for pool in self.pools.values():
                keep: deque = deque()
                while pool.queue:
                    rec = pool.queue.popleft()
                    if not rec.canceled and not rec.done \
                            and self._mux_eligible(rec.spec):
                        rec.mux = True
                        self._mux_staged.append(rec)
                        moved = True
                    else:
                        keep.append(rec)
                pool.queue.extend(keep)
        if moved:
            with self._flush_cv:
                self._mux_dirty = True
                self._flush_cv.notify()

    def _mux_eligible(self, spec: TaskSpec) -> bool:
        # only the plain-CPU fast path rides the relay: placement
        # groups, affinity strategies, custom resources and streaming
        # generators keep the classic per-driver lease conversation
        return (self.raylet is not None
                and spec.placement_group_id is None
                and spec.scheduling_strategy is None
                and spec.num_returns != STREAMING_RETURNS
                and spec.resources == self._DEFAULT_RESOURCES)

    def _flush_mux(self):
        """Flusher-thread drain of the mux staging queue (mirrors
        _push_batched, with the raylet as the single destination)."""
        with self.lock:
            staged = list(self._mux_staged)
            self._mux_staged.clear()
            for rec in staged:
                rec.pushed_to = "__mux__"
            raylet = self.raylet
        if not staged or raylet is None:
            return
        for i in range(0, len(staged), self._submit_batch):
            chunk = staged[i:i + self._submit_batch]
            with self._stats_lock:
                h = self._submit_hist
                h[len(chunk)] = h.get(len(chunk), 0) + 1
                self._flush_stats["tasks"] += len(chunk)
            try:
                with self._trace_flush_cm(chunk, "mux"):
                    raylet.notify("mux_push_tasks",
                                  {"client_id": self.worker_id,
                                   "specs": [rec.spec for rec in chunk]})
            except (ConnectionLost, OSError) as e:
                for rec in chunk:
                    self._mux_task_failed(rec, str(e))

    def _on_mux_tasks_done(self, items):
        """Coalesced completions relayed by the raylet (reader thread);
        the lease-free twin of _on_tasks_done."""
        finished: List[Tuple[TaskRecord, Dict[str, Any]]] = []
        with self.lock:
            for task_id, reply in items:
                rec = self.task_records.get(task_id)
                if rec is None or rec.done:
                    continue   # late duplicate (e.g. post-retry ack)
                rec.done = True
                self.task_records.pop(task_id, None)
                finished.append((rec, reply))
        for rec, reply in finished:
            if rec.canceled and reply.get("status") != "ok":
                reply = {"status": "error",
                         "error": serialization.dumps_inline(
                             TaskCancelledError(
                                 f"task {rec.spec.function_name} "
                                 f"was cancelled"))}
            self._store_results(rec.spec, reply)

    def _on_mux_task_failed(self, items):
        """The raylet reports relay tasks whose worker died: retry by
        restaging, else error out (same policy as _on_task_failure)."""
        retry = False
        failed: List[Tuple[TaskRecord, str]] = []
        with self.lock:
            for task_id, errstr in items:
                rec = self.task_records.get(task_id)
                # pushed_to guard: a restaged rec (pushed_to None) must
                # not be claimed twice by duplicate failure reports
                if rec is None or rec.done or not rec.mux \
                        or rec.pushed_to != "__mux__":
                    continue
                rec.pushed_to = None
                if rec.retries_left > 0 and not self._shutdown \
                        and not rec.canceled:
                    rec.retries_left -= 1
                    self._mux_staged.append(rec)
                    retry = True
                else:
                    self.task_records.pop(task_id, None)
                    failed.append((rec, errstr))
        if retry:
            with self._flush_cv:
                self._mux_dirty = True
                self._flush_cv.notify()
        for rec, errstr in failed:
            self._mux_error_out(rec, errstr)

    def _mux_task_failed(self, rec: TaskRecord, errstr: str):
        """Synchronous-send failure for ONE staged rec (raylet conn
        already closed at enqueue)."""
        self._on_mux_task_failed([(rec.spec.task_id, errstr)])

    def _mux_error_out(self, rec: TaskRecord, errstr: str):
        if rec.canceled:
            err: BaseException = TaskCancelledError(
                f"task {rec.spec.function_name} was cancelled")
        else:
            err = WorkerCrashedError(
                f"task {rec.spec.function_name} failed: worker died "
                f"({errstr})")
        self.task_events.record_status(
            rec.spec.task_id, "FAILED", name=rec.spec.function_name,
            error=str(err))
        for oid in rec.spec.return_ids():
            with self.lock:
                e = self.objects.get(oid)
            if e is not None and not e.ready:
                e.error = err
                e.event.set()

    def _on_raylet_lost(self):
        """The raylet connection died: every relay-routed task loses its
        transport AND its completion channel — error them all out (the
        classic path's lease conversations die through their own worker
        conns)."""
        if self._shutdown:
            return
        with self.lock:
            recs = [r for r in self.task_records.values()
                    if r.mux and not r.done]
            for r in recs:
                self.task_records.pop(r.spec.task_id, None)
            self._mux_staged.clear()
        for rec in recs:
            self._mux_error_out(rec, "raylet connection lost")

    def flush_idle_leases(self) -> None:
        """Return EVERY currently-idle lease now (on-demand reclaim: the
        raylet pushes this when other clients' lease requests are starved
        — the reference's ReleaseUnusedWorkers role).  Without it, idle
        leases sit for IDLE_LEASE_TTL_S while a queued request waits:
        each new scheduling key (new remote function) builds its own
        lease pool, so a sequence of one-shot workloads once degraded to
        one 30s reap-quantum per round."""
        with self.lock:
            pools = list(self.pools.values())
        for pool in pools:
            # 1s threshold: a just-idled lease may be mid-assignment in
            # the submit pipeline; anything idle a full second is truly
            # surplus (vs the 30s TTL reaper)
            self._maybe_return_idle_leases(pool, ttl_s=1.0,
                                           allow_cancel=False)

    def _maybe_return_idle_leases(self, pool: SchedPool,
                                  ttl_s: float = IDLE_LEASE_TTL_S,
                                  allow_cancel: bool = True):
        now = time.monotonic()
        to_return = []
        cancel = False
        with self.lock:
            if pool.queue:
                return
            if pool.pending_requests > 0:
                # the pool still wants workers: in the on-demand flush
                # (allow_cancel=False) leave it entirely alone — its
                # in-flight requests are someone's live work, and
                # canceling them here starves the flushing client itself
                if not allow_cancel:
                    return
                cancel = True
            for wid, lw in list(pool.leases.items()):
                if not lw.inflight and now - lw.idle_since > ttl_s:
                    pool.leases.pop(wid)
                    to_return.append(lw)
        if cancel and self.raylet is not None:
            try:
                self.raylet.notify("cancel_lease_requests",
                                   {"client_id": self.worker_id})
            except Exception:
                pass
        for lw in to_return:
            try:
                cli = Client(lw.raylet_addr, name="core-return")
                cli.notify("return_lease", {"worker_id": lw.worker_id})
                cli.close()
            except Exception:
                pass
            lw.client.close()

    # ------------------------------------------------------------------
    # actors (submitter side)
    # ------------------------------------------------------------------

    def create_actor(self, cls, args, kwargs, *, resources=None, name=None,
                     max_restarts=0, max_task_retries=0, max_concurrency=1,
                     pg=None, bundle_index=-1, detached=False,
                     runtime_env=None, namespace=None, strategy=None) -> str:
        aid = common.actor_id()
        common._ensure_picklable_by_value(cls)
        container = None
        if runtime_env:
            from . import runtime_env as rtenv

            runtime_env = rtenv.prepare(runtime_env, self.control)
            container = rtenv.container_spec(runtime_env)
        spec = {
            "class_blob": cloudpickle.dumps(cls),
            "args_blob": self.serialize_args(args, kwargs),
            "max_concurrency": max_concurrency,
            "runtime_env": runtime_env,
        }
        ac = ActorConn(aid)
        ac.max_task_retries = max_task_retries
        with self.lock:
            self.actors[aid] = ac
        self._control_call("create_actor", {
            "actor_id": aid,
            "container": container,
            "spec_blob": cloudpickle.dumps(spec),
            "name": name,
            "class_name": getattr(cls, "__name__", "Actor"),
            "resources": {common.CPU: 1} if resources is None else resources,
            "namespace": namespace or self.namespace,
            "max_restarts": max_restarts,
            "owner_id": self.worker_id,
            # only driver jobs register with control, so only they can
            # "claim" restored actors after a control restart; actors
            # created from workers send "" (exempt from orphan reaping)
            "job_id": self.job_id if self.mode == "driver" else "",
            "pg_id": pg,
            "bundle_index": bundle_index,
            "detached": detached,
            "strategy": strategy,
        }, timeout=120.0)
        self.pool_executor.submit(self._resolve_actor, aid)
        return aid

    def _actor_conn(self, actor_id: str) -> ActorConn:
        with self.lock:
            ac = self.actors.get(actor_id)
            if ac is None:
                ac = self.actors[actor_id] = ActorConn(actor_id)
                self.pool_executor.submit(self._resolve_actor, actor_id)
            return ac

    def _resolve_actor(self, actor_id: str, min_incarnation: int = 0):
        ac = self._actor_conn(actor_id)
        with ac.lock:
            if ac.resolving:
                return
            ac.resolving = True
        try:
            # no overall deadline: an actor queued behind busy resources
            # stays PENDING arbitrarily long and must not be failed for it
            # (callers bound their own waits via get(timeout)); only a
            # DEAD/missing actor is fatal
            while not self._shutdown:
                view = self._control_call(
                    "wait_actor_alive",
                    {"actor_id": actor_id, "timeout": 60.0,
                     "min_incarnation": min_incarnation},
                    timeout=70.0)
                if view is None or view["state"] == "DEAD":
                    err = (view or {}).get("error") or "actor not found"
                    self._fail_actor(ac, err)
                    return
                if view["state"] != "ALIVE":
                    time.sleep(0.05)
                    continue
                try:
                    client = Client(
                        tuple(view["worker_addr"]),
                        name=f"core->actor-{actor_id[:8]}",
                        on_disconnect=lambda: self._on_actor_conn_lost(actor_id),
                        on_push=lambda topic, payload, aid=actor_id:
                            self._on_actor_push(aid, topic, payload),
                        connect_timeout=5.0)
                except (ConnectionLost, OSError):
                    # stale view: this incarnation already died and the
                    # control plane hasn't processed the death yet — wait
                    # for a newer incarnation (or DEAD)
                    min_incarnation = view["incarnation"] + 1
                    continue
                with ac.lock:
                    ac.client = client
                    ac.addr = tuple(view["worker_addr"])
                    ac.incarnation = view["incarnation"]
                    ac.state = "ALIVE"
                    if self._submit_batch > 1:
                        # batched mode: leave the backlog staged and let
                        # the flusher ship it as framed envelopes
                        buffered = None
                        has_backlog = bool(ac.buffer)
                    else:
                        buffered = list(ac.buffer)
                        ac.buffer.clear()
                        has_backlog = False
                if buffered is None:
                    if has_backlog:
                        with self._flush_cv:
                            self._flush_dirty_actors.add(ac)
                            self._flush_cv.notify()
                else:
                    for spec in buffered:
                        self._send_actor_task(ac, spec)
                return
        finally:
            with ac.lock:
                ac.resolving = False

    def _fail_actor(self, ac: ActorConn, err: str):
        logger.debug("marking actor %s DEAD at driver: %s", ac.actor_id, err)
        with ac.lock:
            ac.state = "DEAD"
            ac.dead_error = err
            pending = list(ac.buffer) + list(ac.inflight.values())
            ac.buffer.clear()
            ac.inflight.clear()
        e = ActorDiedError(err)
        for spec in pending:
            if spec.num_returns == STREAMING_RETURNS:
                self._fail_stream(spec.task_id, e)
            for oid in spec.return_ids():
                with self.lock:
                    ent = self.objects.get(oid)
                if ent is not None:
                    ent.error = e
                    ent.event.set()

    def submit_actor_task(self, actor_id: str, method_name: str, args, kwargs,
                          num_returns: int = 1,
                          generator_backpressure: int = 0) -> List[ObjectRef]:
        if num_returns == "streaming":
            num_returns = STREAMING_RETURNS
        ac = self._actor_conn(actor_id)
        tid = common.task_id()
        spec = TaskSpec(
            task_id=tid,
            function_id="",
            function_name=method_name,
            args_blob=self.serialize_args(args, kwargs, task_id=tid),
            num_returns=num_returns,
            actor_id=actor_id,
            seq_no=0,   # assigned with the stage/send decision below
            owner_id=self.worker_id,
            owner_addr=self.addr,
            parent_task_id=EXECUTING_TASK_ID.get(),
            job_id=EXECUTING_JOB_ID.get() or self.job_id,
            generator_backpressure=generator_backpressure,
        )
        from ray_tpu.util import tracing

        if tracing.is_enabled():
            with tracing.submit_span("actor_task", method_name):
                spec.trace_ctx = tracing.inject_context()
            staged_ns = self._trace_stage_ns(spec.trace_ctx)
            if staged_ns is not None:
                # local-only attr: TaskSpec.__reduce__ pickles declared
                # fields, so the stage clock never rides the wire
                spec._staged_ns = staged_ns
        streaming = spec.num_returns == STREAMING_RETURNS
        task_id_for_stream = spec.task_id
        if streaming and spec.task_id not in self.streams:
            self.streams[spec.task_id] = StreamState(spec)
        refs = []
        with self.lock:
            for oid in spec.return_ids():
                e = self._new_entry(oid)
                e.pins = 1
                self.local_ref_counts[oid] = 1
                refs.append(ObjectRef(oid, self.addr, self.worker_id))
        if streaming:
            refs = [ObjectRefGenerator(self, spec.task_id)]
        self.task_events.record_submit(
            spec.task_id, method_name, "ACTOR_TASK", actor_id=actor_id)
        # A locally-DEAD conn may be stale: during control-plane failover
        # the conn can be marked dead (lost worker + transient control
        # unavailability) while the restored control has since restarted
        # the actor.  Re-check the authoritative record and revive the
        # conn if the actor is in fact coming back.  The verdict is
        # TTL-cached per conn: the probe is a synchronous control
        # round-trip (timeout=10.0) that must not tax every call to a
        # genuinely dead actor.
        if ac.state == "DEAD":
            with ac.lock:
                probe = ac.state == "DEAD" \
                    and time.monotonic() >= ac.dead_recheck_at
            if probe:
                try:
                    view = self._control_call(
                        "get_actor", {"actor_id": actor_id}, timeout=10.0)
                except Exception:
                    view = None
                with ac.lock:
                    if view and view["state"] in ("ALIVE", "RESTARTING",
                                                  "PENDING"):
                        if ac.state == "DEAD":
                            ac.state = "RECONNECTING"
                            ac.dead_error = None
                            ac.client = None
                    else:
                        ac.dead_recheck_at = \
                            time.monotonic() + DEAD_RECHECK_TTL_S
        # single critical section assigns the seq AND decides
        # stage/send — splitting the two let a concurrent submitter
        # interleave between seq assignment and enqueue, shipping seqs
        # out of FIFO order; it also closes the double-send race with
        # _resolve_actor's buffer flush
        batched = self._submit_batch > 1
        with ac.lock:
            if ac.state == "DEAD":
                dead = True
                need_resolve = False
                staged = False
            else:
                dead = False
                ac.seq += 1
                spec.seq_no = ac.seq
                if batched or ac.client is None:
                    ac.buffer.append(spec)
                    staged = True
                    need_resolve = ac.client is None and not ac.resolving
                    spec = None
                else:
                    staged = False
                    need_resolve = False
        if dead:
            e = ActorDiedError(ac.dead_error or "actor is dead")
            if streaming:
                self._fail_stream(task_id_for_stream, e)
                return refs
            for oid in [r.id for r in refs]:
                with self.lock:
                    ent = self.objects.get(oid)
                if ent is not None:
                    ent.error = e
                    ent.event.set()
            return refs
        if need_resolve:
            self.pool_executor.submit(self._resolve_actor, actor_id)
        if staged and batched:
            # hand the send to the combining flusher (a conn with no
            # client yet is skipped there; _resolve_actor re-marks it)
            with self._flush_cv:
                self._flush_dirty_actors.add(ac)
                self._flush_cv.notify()
        elif spec is not None:
            self._send_actor_task(ac, spec)
        return refs

    def _send_actor_task(self, ac: ActorConn, spec: TaskSpec):
        with ac.lock:
            client = ac.client
            if client is None:
                ac.buffer.append(spec)
                return
            ac.inflight[spec.task_id] = spec
        # actor sends bypass the combining flusher (one frame per call,
        # straight to the actor's worker) — record them in the same
        # batch histogram as size-1 rows so submit telemetry covers the
        # actor path too, not just push_tasks batches
        with self._stats_lock:
            self._submit_hist[1] = self._submit_hist.get(1, 0) + 1
            self._actor_sends += 1
        fut = client.call_async("actor_task", spec)

        def on_done(f):
            try:
                reply = f.result()
            except (ConnectionLost, RpcError) as e:
                # connection-level failure: handled by _on_actor_conn_lost,
                # which decides retry vs error using the control plane state
                return
            with ac.lock:
                ac.inflight.pop(spec.task_id, None)
            self._store_results(spec, reply)
            if spec.num_returns == STREAMING_RETURNS:
                self._finish_stream(spec.task_id, reply)

        fut.add_done_callback(on_done)

    def _flush_actor_conn(self, ac: ActorConn):
        """Flusher-thread drain of one actor conn's staging queue: move
        the whole backlog to inflight under the conn lock, ship it as
        framed push_tasks envelopes outside it.  seq/FIFO order is
        preserved end to end: submit stages in seq order, one flusher
        thread drains, and the client's combining writer is strict FIFO
        per conn."""
        with ac.lock:
            if ac.client is None or ac.state != "ALIVE" or not ac.buffer:
                # PENDING/RECONNECTING: _resolve_actor re-marks the conn
                # dirty once it lands; DEAD drains through _fail_actor
                return
            specs = list(ac.buffer)
            ac.buffer.clear()
            for spec in specs:
                ac.inflight[spec.task_id] = spec
            client = ac.client
        for i in range(0, len(specs), self._submit_batch):
            chunk = specs[i:i + self._submit_batch]
            with self._stats_lock:
                h = self._actor_hist
                h[len(chunk)] = h.get(len(chunk), 0) + 1
                self._actor_sends += len(chunk)
                self._flush_stats["tasks"] += len(chunk)
            try:
                with self._trace_actor_flush_cm(chunk):
                    client.notify("push_tasks", chunk)
            except (ConnectionLost, OSError):
                # conn died between stage and ship: everything already
                # sits in inflight, and the on_disconnect sweep
                # (_on_actor_conn_lost) claims it all — retry vs error
                # is decided there from the control-plane view
                return

    def _trace_actor_flush_cm(self, chunk: List[TaskSpec]):
        """Actor twin of _trace_flush_cm: the staging queue holds bare
        specs, so the stage clock rides a local-only spec attribute."""
        from ray_tpu.util import tracing

        if not tracing.is_enabled():
            return contextlib.nullcontext()
        carrier = None
        now_ns = time.time_ns()
        for spec in chunk:
            staged_ns = getattr(spec, "_staged_ns", None)
            if staged_ns is None:
                continue
            tracing.record_span(
                "driver.stage_wait", "INTERNAL", staged_ns, now_ns,
                tracing._extract(spec.trace_ctx), batch=len(chunk))
            spec._staged_ns = None
            if carrier is None:
                carrier = spec.trace_ctx
        if carrier is None:
            return contextlib.nullcontext()
        payload_bytes = sum(len(spec.args_blob or b"") for spec in chunk)
        return tracing.phase_span(
            "driver.flush_batch", carrier, batch=len(chunk),
            payload_bytes=payload_bytes, transport="actor")

    def _on_actor_push(self, actor_id: str, topic: str, payload):
        """Server-push from an actor's worker (reader thread): coalesced
        tasks_done acks for batched actor calls (the actor twin of
        _on_tasks_done; lease/pool bookkeeping does not apply)."""
        if topic != "tasks_done":
            return
        with self.lock:
            ac = self.actors.get(actor_id)
        if ac is None:
            return
        finished = []
        with ac.lock:
            for task_id, reply in payload:
                spec = ac.inflight.pop(task_id, None)
                if spec is None:
                    continue   # late duplicate after a conn-loss sweep
                finished.append((spec, reply))
        for spec, reply in finished:
            self._store_results(spec, reply)
            if spec.num_returns == STREAMING_RETURNS:
                self._finish_stream(spec.task_id, reply)

    def _on_actor_conn_lost(self, actor_id: str):
        ac = self._actor_conn(actor_id)
        with ac.lock:
            ac.client = None
            ac.state = "RECONNECTING"
            pending = list(ac.inflight.values())
            ac.inflight.clear()
            # a lost connection means this incarnation is gone: anything we
            # hear about the actor next must be a newer incarnation or DEAD
            next_inc = ac.incarnation + 1
        if self._shutdown:
            return

        def recover():
            view = None
            try:
                view = self._control_call(
                    "wait_actor_alive",
                    {"actor_id": actor_id, "timeout": 60.0,
                     "min_incarnation": next_inc},
                    timeout=70.0)
            except Exception:
                pass
            logger.debug("actor %s recover view: %s", actor_id, view)
            if view is not None and view["state"] == "ALIVE":
                if ac.max_task_retries != 0:
                    with ac.lock:
                        for spec in pending:
                            ac.buffer.appendleft(spec)
                else:
                    self._error_specs(pending, ActorDiedError(
                        "actor restarted; pending calls lost (max_task_retries=0)"))
                self._resolve_actor(actor_id, min_incarnation=next_inc)
            else:
                err = (view or {}).get("error") if view else "actor died"
                self._error_specs(pending, ActorDiedError(str(err)))
                with ac.lock:
                    ac.state = "DEAD"
                    ac.dead_error = str(err)
                    buffered = list(ac.buffer)
                    ac.buffer.clear()
                self._error_specs(buffered, ActorDiedError(str(err)))

        self.pool_executor.submit(recover)

    def _error_specs(self, specs, err):
        for spec in specs:
            if spec.num_returns == STREAMING_RETURNS:
                # a consumer may be blocked in ObjectRefGenerator.next()
                # waiting for the item the dead actor never reported:
                # finish the stream with the error so the wait raises now
                # instead of hanging on the reconnect quantum
                self._fail_stream(spec.task_id, err)
            for oid in spec.return_ids():
                with self.lock:
                    e = self.objects.get(oid)
                if e is not None and not e.ready:
                    e.error = err
                    e.event.set()

    def cancel(self, ref, force: bool = False,
               recursive: bool = True) -> bool:
        """Cancel the task producing `ref` (reference: ray.cancel,
        core_worker CancelTask + HandleRemoteCancelTask).  Queued tasks
        are dropped; a running task gets TaskCancelledError injected into
        its thread (force=True kills the worker process instead; not
        supported for actor tasks).  recursive=True also cancels the
        tasks the cancelled task submitted.  Cancelled tasks are never
        retried.  Returns False if the task already finished or isn't
        cancellable."""
        if isinstance(ref, ObjectRefGenerator):
            # cancelling a streaming task: the generator IS the handle
            return self._cancel_task_id(ref.task_id, force, recursive)
        tid = "tsk-" + ref.id[4:].rsplit("-", 1)[0] \
            if ref.id.startswith("obj-") else None
        if tid is None:
            return False
        return self._cancel_task_id(tid, force, recursive)

    def _cancel_task_id(self, tid: str, force: bool,
                        recursive: bool) -> bool:
        with self.lock:
            rec = self.task_records.get(tid)
            if rec is not None and rec.done:
                return False
            if rec is not None:
                rec.canceled = True
                rec.retries_left = 0
                if rec.mux:
                    pool = None
                    queued = rec in self._mux_staged
                    if queued:
                        self._mux_staged.remove(rec)
                        self.task_records.pop(tid, None)
                else:
                    pool = self.pools.get(rec.pool_key)
                    queued = pool is not None and rec in pool.queue
                    if queued:
                        pool.queue.remove(rec)
                        self.task_records.pop(tid, None)
        if rec is None:
            return self._cancel_actor_task(tid, force, recursive)
        if queued:
            err = TaskCancelledError(
                f"task {rec.spec.function_name} was cancelled before it "
                f"started")
            self.task_events.record_status(
                rec.spec.task_id, "FAILED", name=rec.spec.function_name,
                error=str(err))
            for oid in rec.spec.return_ids():
                with self.lock:
                    e = self.objects.get(oid)
                if e is not None and not e.ready:
                    e.error = err
                    e.event.set()
            if rec.spec.num_returns == STREAMING_RETURNS:
                self._fail_stream(tid, err)
            return True
        if rec.mux:
            # relay-routed: only the raylet knows which worker (if any)
            # runs it.  Delivery is best-effort with the same 15s
            # owner-side fallback as the direct path — the cancelled
            # reply arrives through mux_tasks_done when confirmed.
            def mux_fallback(rec=rec):
                if not rec.done:
                    logger.warning(
                        "mux cancel of %s not confirmed; resolving "
                        "owner-side", rec.spec.task_id[:12])
                    self._fail_canceled_entries(rec)

            raylet = self.raylet
            if raylet is None:
                self._fail_canceled_entries(rec)
                return True
            try:
                raylet.notify("mux_cancel",
                              {"task_id": tid, "client_id": self.worker_id,
                               "force": force, "recursive": recursive})
            except Exception:
                mux_fallback()
                return True
            t = threading.Timer(15.0, mux_fallback)
            t.daemon = True
            t.start()
            return True
        # pushed: tell the executing worker (it propagates to children
        # when recursive — they are owned by that worker, not us).
        # Delivery is CONFIRMED off-thread: if the worker never handles
        # the cancel (conn hiccup, handler fault), the owner resolves the
        # refs itself — a cancelled task never retries, so nobody else
        # ever would, and get() must not hang forever.
        with self.lock:
            lw = None
            if pool is not None and rec.pushed_to:
                lw = pool.leases.get(rec.pushed_to)
        client = lw.client if lw is not None else None
        if client is None:
            # no live lease to deliver to: resolve immediately
            self._fail_canceled_entries(rec)
            return True

        # async confirmation — never parks a shared pool thread: the ack
        # resolves via callback; a timer catches a worker that never
        # replies at all
        def fallback(rec=rec):
            if not rec.done:
                logger.warning(
                    "cancel of %s not confirmed by worker; resolving "
                    "owner-side", rec.spec.task_id[:12])
                self._fail_canceled_entries(rec)

        timer = threading.Timer(15.0, fallback)
        timer.daemon = True

        def on_ack(f):
            try:
                f.result()
                timer.cancel()
            except Exception:
                timer.cancel()
                fallback()

        try:
            fut = client.call_async(
                "cancel_task", {"task_id": rec.spec.task_id,
                                "force": force, "recursive": recursive})
        except Exception:
            fallback()
            return True
        timer.start()
        fut.add_done_callback(on_ack)
        return True

    def _fail_canceled_entries(self, rec: TaskRecord):
        err = TaskCancelledError(
            f"task {rec.spec.function_name} was cancelled")
        for oid in rec.spec.return_ids():
            with self.lock:
                e = self.objects.get(oid)
            if e is not None and not e.ready:
                e.error = err
                e.event.set()
        if rec.spec.num_returns == STREAMING_RETURNS:
            self._fail_stream(rec.spec.task_id, err)
            # and CLOSE it: an unconfirmed producer that later wakes must
            # be told to stop, not have its items stored and pinned
            self._release_stream(rec.spec.task_id)

    def _cancel_actor_task(self, tid: str, force: bool,
                           recursive: bool) -> bool:
        """Cancel an actor task: drop it if still buffered client-side,
        else ask the actor's worker (reference: core_worker.cc
        HandleCancelTask actor path; force-kill is not supported for
        actor tasks, matching ray.cancel semantics)."""
        with self.lock:
            conns = list(self.actors.values())
        for ac in conns:
            with ac.lock:
                buffered = next(
                    (s for s in ac.buffer if s.task_id == tid), None)
                if buffered is not None:
                    ac.buffer.remove(buffered)
                inflight = ac.inflight.get(tid)
                client = ac.client
            if buffered is not None:
                err = TaskCancelledError(
                    f"actor task {buffered.function_name} was cancelled "
                    f"before it was sent")
                self.task_events.record_status(
                    tid, "FAILED", name=buffered.function_name,
                    actor_id=ac.actor_id, error=str(err))
                for oid in buffered.return_ids():
                    with self.lock:
                        e = self.objects.get(oid)
                    if e is not None and not e.ready:
                        e.error = err
                        e.event.set()
                return True
            if inflight is not None:
                if force:
                    raise ValueError(
                        "force=True is not supported for actor tasks")
                if client is not None:
                    try:
                        client.notify("cancel_task",
                                      {"task_id": tid, "force": False,
                                       "recursive": recursive})
                    except Exception:
                        pass
                return True
        return False

    def cancel_children(self, parent_tid: str, force: bool = False):
        """Cancel every not-yet-finished task this process submitted on
        behalf of `parent_tid` (reference: ray.cancel(recursive=True) —
        each worker cancels the children it owns, recursing down)."""
        child_tids = []
        with self.lock:
            child_tids += [rec.spec.task_id
                           for rec in self.task_records.values()
                           if rec.spec.parent_task_id == parent_tid
                           and not rec.done]
            conns = list(self.actors.values())
        for ac in conns:
            with ac.lock:
                child_tids += [
                    s.task_id
                    for s in list(ac.buffer) + list(ac.inflight.values())
                    if s.parent_task_id == parent_tid]
        for tid in child_tids:
            try:
                self._cancel_task_id(tid, force, recursive=True)
            except ValueError:
                # actor child: force unsupported — plain cancel instead
                self._cancel_task_id(tid, False, recursive=True)

    def _task_is_live_locked(self, tid: str) -> bool:
        """Caller holds self.lock.  True while `tid` is still tracked:
        queued/running/retrying as a normal task, or buffered/in-flight
        on an actor connection.  ac.buffer/inflight are mutated under
        ac.lock (NOT self.lock) — taking ac.lock here would invert the
        lock order, so snapshot with list()/`in` (atomic under the GIL)
        instead of iterating the live deque."""
        if tid in self.task_records:
            return True
        for ac in list(self.actors.values()):
            if tid in ac.inflight:
                return True
            if any(getattr(s, "task_id", None) == tid
                   for s in list(ac.buffer)):
                return True
        return False

    def kill_actor(self, actor_id: str, no_restart: bool = True):
        self._control_call("kill_actor", {"actor_id": actor_id,
                                         "no_restart": no_restart}, timeout=30.0)

    # -- actor-handle borrow protocol --------------------------------------
    # reference: actor handles are reference-counted cluster-wide; the
    # actor is GC'd when no handle (owned or borrowed) remains.  Borrowed
    # handles register with the owner at deserialization; serialization
    # itself takes a time-bounded "transit" hold that bridges the gap
    # between pickling a handle and the receiver registering its borrow
    # (the window in which the old implementation killed the actor).

    # Baseline bound for holds not tied to a tracked task: a pickled
    # handle neither deserialized nor dropped within this window stops
    # protecting the actor.  Holds taken while serializing TASK ARGS are
    # bound to that task and auto-refresh while it is still queued /
    # running / retrying, so a call queued behind >60s of work keeps its
    # protection (the exact-tracking role of the reference's borrow acks).
    ACTOR_TRANSIT_S = 60.0

    def on_actor_handle_serialized(self, actor_id: str,
                                   owner_addr) -> Optional[str]:
        """Take one per-pickle transit hold; returns its nonce (embedded
        in the pickle so the borrower's add_ref retires exactly THIS
        hold, never another in-flight copy's)."""
        if owner_addr is None:
            # a weak handle (get_actor lookup): extends nothing, matching
            # the reference — named lookups don't own or pin lifetime
            return None
        nonce = uuid.uuid4().hex[:16]
        bound_task = TRANSIT_TASK_ID.get()
        if tuple(owner_addr) == self.addr:
            with self.lock:
                self._actor_transit.setdefault(actor_id, {})[nonce] = \
                    [time.monotonic() + self.ACTOR_TRANSIT_S, bound_task]
            return nonce
        try:
            # cross-core owner: no task binding (the owner cannot observe
            # this core's task liveness) — fixed window, nonce-retired
            self._owner_client(tuple(owner_addr)).notify(
                "actor_transit", {"actor_id": actor_id, "nonce": nonce})
        except Exception:
            pass
        return nonce

    def on_actor_handle_borrowed(self, actor_id: str, owner_addr,
                                 nonce: Optional[str] = None) -> bool:
        if owner_addr is None:
            return False
        owner_addr = tuple(owner_addr)
        if owner_addr == self.addr:
            # a handle round-tripped back to its owner: count it like any
            # other borrower (loopback entry, no RPC)
            self._register_actor_borrow(actor_id, self.worker_id, self.addr,
                                        nonce=nonce)
            with self.lock:
                self._borrowed_actors.setdefault(
                    actor_id, [0, owner_addr])[0] += 1
            return True
        with self.lock:
            rec = self._borrowed_actors.setdefault(actor_id, [0, owner_addr])
            rec[0] += 1
        # notify on EVERY deserialization, not just the first: the owner's
        # borrower set is idempotent, and the carried nonce retires
        # exactly this pickle's transit hold — a re-deserialized copy
        # retires nothing extra, so other in-flight pickles keep theirs
        try:
            self._owner_client(owner_addr).notify(
                "actor_add_ref", {"actor_id": actor_id,
                                  "borrower": self.worker_id,
                                  "borrower_addr": self.addr,
                                  "nonce": nonce})
        except Exception:
            pass
        return True

    def on_actor_handle_dropped(self, actor_id: str):
        # symmetric with on_actor_handle_borrowed: one del notification
        # per dropped handle (the owner counts adds per deserialization)
        with self.lock:
            rec = self._borrowed_actors.get(actor_id)
            if rec is None:
                return
            rec[0] -= 1
            if rec[0] <= 0:
                self._borrowed_actors.pop(actor_id, None)
            owner_addr = tuple(rec[1])
        if owner_addr == self.addr:
            self._deregister_actor_borrow(actor_id, self.worker_id)
            self._maybe_release_actor(actor_id)
            return
        try:
            # short timeout → the dead-owner negative cache applies: this
            # runs from __del__ via flush_pending_deletes/_delete_loop, and
            # dropping N handles of a killed actor must not stall put() and
            # gc in 30s connect-retry quanta (same fix as object del_ref)
            self._owner_client(owner_addr, connect_timeout=2.0).notify(
                "actor_del_ref", {"actor_id": actor_id,
                                  "borrower": self.worker_id})
        except Exception:
            pass

    def _register_actor_borrow(self, aid: str, borrower: str, addr,
                               nonce: Optional[str] = None):
        """Owner side: count one borrowed handle and retire THE pickle's
        in-transit hold (matched by nonce — retiring the oldest would let
        one twice-deserialized pickle strip another copy's protection)."""
        with self.lock:
            ent = self._actor_borrowers.setdefault(aid, {}) \
                .setdefault(borrower, [0, addr])
            ent[0] += 1
            ent[1] = addr or ent[1]
            holds = self._actor_transit.get(aid)
            if holds and nonce is not None:
                holds.pop(nonce, None)
                if not holds:
                    self._actor_transit.pop(aid, None)

    def _deregister_actor_borrow(self, aid: str, borrower: str,
                                 drop_all: bool = False):
        with self.lock:
            bs = self._actor_borrowers.get(aid)
            ent = bs.get(borrower) if bs else None
            if ent is not None:
                ent[0] -= 1
                if ent[0] <= 0 or drop_all:
                    bs.pop(borrower, None)
                if not bs:
                    self._actor_borrowers.pop(aid, None)

    def h_actor_add_ref(self, conn, p):
        self._register_actor_borrow(
            p["actor_id"], p["borrower"],
            tuple(p.get("borrower_addr") or ()) or None,
            nonce=p.get("nonce"))
        return True

    def h_actor_del_ref(self, conn, p):
        self._deregister_actor_borrow(p["actor_id"], p["borrower"],
                                      drop_all=bool(p.get("all")))
        self._maybe_release_actor(p["actor_id"])
        return True

    def h_actor_transit(self, conn, p):
        with self.lock:
            self._actor_transit.setdefault(p["actor_id"], {})[
                p.get("nonce") or uuid.uuid4().hex[:16]] = \
                [time.monotonic() + self.ACTOR_TRANSIT_S, None]
        return True

    ACTOR_BORROW_PROBE_S = 20.0

    def _probe_actor_borrowers(self, actor_id: str):
        """A release is pending but borrowers block it: verify they are
        still alive and still hold the handle — a crashed borrower never
        sends actor_del_ref and would block release forever."""
        with self.lock:
            self._actor_probe_scheduled.discard(actor_id)
            if actor_id not in self._actor_pending_release:
                return
            borrowers = dict(self._actor_borrowers.get(actor_id) or {})
        stale = []
        for bid, ent in borrowers.items():
            addr = ent[1] if isinstance(ent, list) else ent
            if bid == self.worker_id:
                continue  # loopback entries validated by local state
            alive = False
            if addr:
                try:
                    cli = Client(tuple(addr), name="core-borrow-probe",
                                 connect_timeout=5.0)
                    alive = bool(cli.call("actor_borrow_check",
                                          {"actor_id": actor_id},
                                          timeout=10.0))
                    cli.close()
                except Exception:
                    alive = False
            if not alive:
                stale.append(bid)
        if stale:
            with self.lock:
                bs = self._actor_borrowers.get(actor_id)
                for bid in stale:
                    if bs is not None:
                        bs.pop(bid, None)
                if bs is not None and not bs:
                    self._actor_borrowers.pop(actor_id, None)
        self._maybe_release_actor(actor_id)

    def h_actor_borrow_check(self, conn, p):
        with self.lock:
            return p["actor_id"] in self._borrowed_actors

    def _maybe_release_actor(self, actor_id: str):
        with self.lock:
            if actor_id not in self._actor_pending_release:
                return
            if self._actor_borrowers.get(actor_id):
                # a borrower still holds a handle; schedule a liveness
                # probe in case it crashed without deregistering
                if actor_id not in self._actor_probe_scheduled:
                    self._actor_probe_scheduled.add(actor_id)
                    t = threading.Timer(
                        self.ACTOR_BORROW_PROBE_S,
                        lambda: self.pool_executor.submit(
                            self._probe_actor_borrowers, actor_id))
                    t.daemon = True
                    t.start()
                return
            now = time.monotonic()
            holds = {}
            for nonce, (exp, tid) in \
                    self._actor_transit.get(actor_id, {}).items():
                if tid is not None and self._task_is_live_locked(tid):
                    # hold bound to a still-queued/running/retrying task:
                    # its pickled handle is still in the args — refresh
                    # (ADVICE r2: a call queued >60s must stay protected)
                    exp = now + self.ACTOR_TRANSIT_S
                if exp > now:
                    holds[nonce] = [exp, tid]
            if holds:
                self._actor_transit[actor_id] = holds
                delay = min(h[0] for h in holds.values()) - now
            else:
                self._actor_pending_release.discard(actor_id)
                self._actor_transit.pop(actor_id, None)
                delay = None
        if delay is not None:
            t = threading.Timer(delay + 0.05,
                                self._maybe_release_actor, (actor_id,))
            t.daemon = True
            t.start()
            return
        self._terminate_actor(actor_id)

    def release_actor(self, actor_id: str):
        """Every owner handle went out of scope: terminate — unless a
        borrowed handle (or an in-transit serialized copy) still exists,
        in which case the release defers until they clear."""
        with self.lock:
            self._actor_pending_release.add(actor_id)
        self._maybe_release_actor(actor_id)

    def _terminate_actor(self, actor_id: str):
        """Terminate gracefully.  The __ray_terminate__ marker rides the
        ordered actor queue, so calls already submitted finish first
        (reference: ActorHandle.__del__ -> __ray_terminate__ semantics); a
        hard kill_actor is the fallback when the actor has no live
        connection to drain.

        Runs off-thread: __del__ may fire inside GC while this thread
        holds an ActorConn lock the submit path needs."""

        def do():
            with self.lock:
                ac = self.actors.get(actor_id)
            try:
                if ac is not None and ac.state in ("ALIVE", "PENDING",
                                                   "RECONNECTING"):
                    self.submit_actor_task(actor_id, "__ray_terminate__",
                                           (), {})
                    return
            except Exception:
                pass
            try:
                self.control.call_async(
                    "kill_actor", {"actor_id": actor_id,
                                   "no_restart": True})
            except Exception:
                pass

        try:
            self.pool_executor.submit(do)
        except Exception:
            pass

    def get_actor_by_name(self, name: str, namespace: Optional[str] = None):
        view = self._control_call(
            "get_actor", {"name": name,
                          "namespace": namespace or self.namespace},
            timeout=30.0)
        return view

    # ------------------------------------------------------------------
    # control pushes
    # ------------------------------------------------------------------

    def add_push_handler(self, topic: str, fn) -> None:
        """Register a callback for a control pubsub topic this process is
        subscribed to (callers also need control.call("subscribe", ...))."""
        with self.lock:
            self._push_handlers.setdefault(topic, []).append(fn)

    def remove_push_handler(self, topic: str, fn) -> None:
        """Detach a callback registered with add_push_handler (no-op if
        it was never registered — teardown paths call this defensively)."""
        with self.lock:
            handlers = self._push_handlers.get(topic)
            if handlers and fn in handlers:
                handlers.remove(fn)

    def _sub_topics(self) -> List[str]:
        topics = ["actor", "node"]
        if self.log_to_driver:
            topics.append("worker_logs")
        return topics

    def _on_control_push(self, topic: str, payload):
        if topic == "pub:worker_logs":
            # worker stdout routed to this driver (reference:
            # log_monitor.py -> pubsub -> driver console)
            if self.log_to_driver and payload.get("job_id") == self.job_id:
                import sys as _sys

                wid = payload.get("worker_id", "?")
                for line in payload.get("lines", ()):
                    print(f"({wid}) {line}", file=_sys.stderr)
            return
        if topic == "pub:actor":
            actor = payload.get("actor", {})
            aid = actor.get("actor_id")
            with self.lock:
                ac = self.actors.get(aid)
            if ac is not None and payload["event"] == "dead":
                self._fail_actor(ac, actor.get("error") or "actor died")
        handlers = getattr(self, "_push_handlers", {}).get(topic, ())
        for fn in list(handlers):
            try:
                fn(payload)
            except Exception:
                logger.exception("push handler for %s failed", topic)

    # ------------------------------------------------------------------
    # execution-side helpers (used by worker_proc)
    # ------------------------------------------------------------------

    def store_task_results(self, spec: TaskSpec, values: List[Any]) -> Dict[str, Any]:
        """Serialize task return values into a push_task reply.  Large values
        go to the node shm store; small ones travel inline in the reply
        (reference: small returns into the PushTask reply -> owner memory
        store; large into plasma, core_worker.cc:1246)."""
        results = []
        for i, v in enumerate(values):
            oid = common.object_id_for_return(spec.task_id, i)
            meta, bufs = serialization.dumps_oob(v)
            raw = [b.raw() for b in bufs]
            total = len(meta) + sum(len(b) for b in raw)
            if total > INLINE_OBJECT_LIMIT and self.store is not None:
                self._store_create(oid, meta, raw)
                results.append(("shm", {"node_id": self.node_id,
                                        "addr": self.raylet_addr,
                                        "nbytes": total}))
            else:
                results.append(("inline", (meta, [b.raw().tobytes() for b in bufs])))
        return {"status": "ok", "results": results}

    def store_stream_item(self, spec: TaskSpec, index: int, value):
        """Producer-side: serialize one yielded item (shm for big values,
        inline otherwise) into the wire (kind, payload) form."""
        oid = common.object_id_for_return(spec.task_id, index)
        meta, bufs = serialization.dumps_oob(value)
        raw = [b.raw() for b in bufs]
        total = len(meta) + sum(len(b) for b in raw)
        if total > INLINE_OBJECT_LIMIT and self.store is not None:
            self._store_create(oid, meta, raw)
            return ("shm", {"node_id": self.node_id,
                            "addr": self.raylet_addr,
                            "nbytes": total})
        return ("inline", (meta, [b.raw().tobytes() for b in bufs]))

    def resolve_args(self, spec: TaskSpec):
        args, kwargs = serialization.loads_inline(spec.args_blob)
        args = [self.get(a) if isinstance(a, ObjectRef) else a for a in args]
        kwargs = {k: self.get(v) if isinstance(v, ObjectRef) else v
                  for k, v in kwargs.items()}
        return args, kwargs
