"""Object spilling and memory pressure handling for the node daemon.

Reference parity:
  - spill/restore/delete of primary in-memory copies under store pressure
    (src/ray/raylet/local_object_manager.h:110 ``SpillObjects``,
    :122 ``AsyncRestoreSpilledObject``) — here the spill target is a
    directory of packed-layout files next to the shm arena, and restore
    re-seals the bytes back into the arena on demand;
  - system memory watchdog (src/ray/common/memory_monitor.h:52) with a
    retriable-first worker-killing policy
    (src/ray/raylet/worker_killing_policy.h) — the raylet kills the most
    recently leased task worker; the owner's task manager observes the
    death and retries, so under sustained pressure the oldest work keeps
    making progress (the reference's retriable-FIFO policy).

Differences from the reference, by design: there are no dedicated IO
worker processes — spill IO is a raylet thread writing files (the store
is a mapped arena, not a store daemon, so there is no plasma client
round-trip to amortize); and LRU order is approximated by entry-table
order (insertion order) rather than the arena's exact access clock.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Dict, Optional, Tuple

logger = logging.getLogger(__name__)

from .config import cfg as _cfg

SPILL_HIGH_FRAC = _cfg().spill_high
SPILL_LOW_FRAC = _cfg().spill_low


class SpillManager:
    """Moves sealed objects from the shm store to disk files and back."""

    def __init__(self, store, spill_dir: str,
                 high: float = SPILL_HIGH_FRAC, low: float = SPILL_LOW_FRAC):
        self.store = store
        self.dir = spill_dir
        self.high = high
        self.low = low
        os.makedirs(spill_dir, exist_ok=True)
        self.lock = threading.Lock()
        self.spilled: Dict[str, str] = {}  # object_id -> file path
        self.n_spilled = 0
        self.n_restored = 0
        self.bytes_spilled = 0

    # -- pressure ----------------------------------------------------------

    def _usage(self) -> Tuple[int, int]:
        """(used, capacity) of the in-memory store; (0, 0) if unknown."""
        stats = getattr(self.store, "stats", None)
        if stats is None:
            return 0, 0
        try:
            s = stats()
            return int(s.get("used", 0)), int(s.get("capacity", 0))
        except Exception:
            return 0, 0

    def over_high_water(self) -> bool:
        used, cap = self._usage()
        return cap > 0 and used / cap > self.high

    # -- spill -------------------------------------------------------------

    def maybe_spill(self) -> int:
        """Spill until usage drops below the low-water mark; returns the
        number of objects moved to disk this pass.

        Only primary copies are spilled: non-primary objects (pulled
        remote copies, raw blobs) are already LRU-evictable and
        recoverable without disk IO, so the allocator reclaims them on
        demand."""
        used, cap = self._usage()
        if cap <= 0 or used / cap <= self.high:
            return 0
        target = int(cap * self.low)
        is_primary = getattr(self.store, "is_primary", None)
        n = 0
        for oid in self.store.list_objects():
            if used <= target:
                break
            if is_primary is not None and not is_primary(oid):
                continue
            size = self.store.size(oid) or 0
            if self._spill_one(oid):
                used -= size
                n += 1
        return n

    def _spill_one(self, oid: str) -> bool:
        with self.lock:
            on_disk = oid in self.spilled
        if not on_disk:
            data = self.store.read_bytes(oid)
            if data is None:
                return False
            path = os.path.join(self.dir, oid)
            tmp = path + ".tmp"
            try:
                with open(tmp, "wb") as f:
                    f.write(data)
                os.rename(tmp, path)
            except OSError as e:
                logger.warning("spill of %s failed: %s", oid, e)
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                return False
            with self.lock:
                self.spilled[oid] = path
                self.n_spilled += 1
                self.bytes_spilled += len(data)
        # Bytes are safe on disk: demote from primary (making the entry
        # evictable) and free the in-memory copy.  A pinned object survives
        # the free attempt — report failure so the caller doesn't count
        # memory that wasn't reclaimed; the disk copy is a prepaid spill
        # for a later pass.
        set_primary = getattr(self.store, "set_primary", None)
        if set_primary is not None:
            set_primary(oid, False)
        try_free = getattr(self.store, "try_free", None)
        if try_free is not None:
            return bool(try_free(oid))
        return bool(self.store.delete(oid))

    # -- restore -----------------------------------------------------------

    def restore(self, oid: str) -> bool:
        """Bring a spilled object back into the store (idempotent)."""
        if self.store.contains(oid):
            return True
        with self.lock:
            path = self.spilled.get(oid)
        if path is None:
            return False
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            return False
        self.store.write_bytes(oid, data)
        with self.lock:
            self.n_restored += 1
        return True

    def read_spilled(self, oid: str) -> Optional[bytes]:
        """Serve spilled bytes directly (remote fetch path) without
        displacing resident objects."""
        with self.lock:
            path = self.spilled.get(oid)
        if path is None:
            return None
        try:
            with open(path, "rb") as f:
                return f.read()
        except OSError:
            return None

    def contains(self, oid: str) -> bool:
        with self.lock:
            return oid in self.spilled

    def delete(self, oid: str) -> bool:
        with self.lock:
            path = self.spilled.pop(oid, None)
        if path is None:
            return False
        try:
            os.unlink(path)
        except OSError:
            pass
        return True

    def stats(self) -> dict:
        with self.lock:
            return {
                "num_spilled": self.n_spilled,
                "num_restored": self.n_restored,
                "bytes_spilled": self.bytes_spilled,
                "num_on_disk": len(self.spilled),
            }

    def destroy(self) -> None:
        import shutil

        with self.lock:
            self.spilled.clear()
        shutil.rmtree(self.dir, ignore_errors=True)


# ---------------------------------------------------------------------------


def _cgroup_usage() -> Optional[Tuple[int, int]]:
    """(current, max) from cgroup v2 if this process has a real limit."""
    try:
        with open("/sys/fs/cgroup/memory.max") as f:
            raw = f.read().strip()
        if raw == "max":
            return None
        limit = int(raw)
        with open("/sys/fs/cgroup/memory.current") as f:
            cur = int(f.read().strip())
        return cur, limit
    except (OSError, ValueError):
        return None


def _meminfo_usage() -> Optional[Tuple[int, int]]:
    try:
        fields = {}
        with open("/proc/meminfo") as f:
            for line in f:
                k, _, v = line.partition(":")
                fields[k] = int(v.split()[0]) * 1024
        total = fields["MemTotal"]
        avail = fields.get("MemAvailable", fields.get("MemFree", 0))
        return total - avail, total
    except (OSError, KeyError, ValueError, IndexError):
        return None


class MemoryMonitor:
    """Samples system/cgroup memory usage (reference: memory_monitor.h:52).

    The raylet polls :meth:`over_threshold` and applies its killing policy
    when usage crosses the threshold.  ``get_usage`` is injectable for
    tests (returns a 0..1 fraction).
    """

    def __init__(self, threshold: Optional[float] = None, get_usage=None):
        if threshold is None:
            threshold = float(os.environ.get(
                "RAY_TPU_MEMORY_USAGE_THRESHOLD", "0.95"))
        self.threshold = threshold
        self._get_usage = get_usage
        self.last_fraction = 0.0

    def usage_fraction(self) -> float:
        fake = os.environ.get("RAY_TPU_MEMORY_USAGE_FILE")
        if fake:
            # test hook: the file holds the fraction to report
            try:
                with open(fake) as f:
                    self.last_fraction = float(f.read().strip())
            except (OSError, ValueError):
                self.last_fraction = 0.0
            return self.last_fraction
        if self._get_usage is not None:
            f = float(self._get_usage())
        else:
            u = _cgroup_usage() or _meminfo_usage()
            if u is None:
                return 0.0
            used, total = u
            f = used / total if total else 0.0
        self.last_fraction = f
        return f

    def over_threshold(self) -> bool:
        return self.usage_fraction() > self.threshold


KILL_GRACE_S = 1.0  # between OOM kills, let memory settle


class OomKiller:
    """Retriable-FIFO worker-killing policy over a raylet's worker table
    (reference: worker_killing_policy_retriable_fifo.h): kill the most
    recently leased task worker so the earliest-submitted work finishes."""

    def __init__(self, raylet, monitor: MemoryMonitor):
        self.raylet = raylet
        self.monitor = monitor
        self.n_killed = 0
        self._last_kill = 0.0

    def step(self) -> bool:
        if not self.monitor.over_threshold():
            return False
        now = time.monotonic()
        if now - self._last_kill < KILL_GRACE_S:
            return False
        victim = None
        with self.raylet.lock:
            leased = [r for r in self.raylet.workers.values()
                      if r.state == "leased" and r.proc is not None]
            # retriable-FIFO: a max_retries=0 task dies permanently if
            # killed, so prefer retriable victims (most recent lease
            # first) and fall back to non-retriable only when none exist
            pool = ([r for r in leased if r.lease_retriable]
                    or leased)
            if pool:
                victim = max(pool, key=lambda r: r.leased_at)
        if victim is None:
            return False
        logger.warning(
            "memory usage %.1f%% above threshold %.1f%%: killing worker %s "
            "(most recent lease) to release memory",
            self.monitor.last_fraction * 100, self.monitor.threshold * 100,
            victim.worker_id[:12])
        if not self.raylet.kill_worker_for_oom(victim):
            return False
        self.n_killed += 1
        self._last_kill = now
        try:
            # structured cluster event (reference: the OOM killer's
            # ray.event emission) — dashboards/state API surface it
            self.raylet.control.notify("report_event", {
                "severity": "ERROR", "source": "raylet",
                "event_type": "worker_oom_killed",
                "entity_id": victim.worker_id,
                "message": (f"memory {self.monitor.last_fraction:.0%} > "
                            f"{self.monitor.threshold:.0%}: killed worker "
                            f"{victim.worker_id[:12]} (most recent lease)"),
            })
        except Exception:
            pass
        return True
