"""Framed RPC layer: the TPU-native equivalent of the reference's gRPC wrappers.

The reference wraps async gRPC in `GrpcServer`/`GrpcClient`/`ClientCallManager`
(reference: src/ray/rpc/grpc_server.h:85, grpc_client.h:93, client_call.h:189).
We provide the same capability — async request/reply with correlation ids,
server push (for pubsub), connection-death notification — over plain TCP
sockets with pickle framing.  This keeps the control plane dependency-free and
fast enough for the control path; the data plane (tensors) never moves through
this layer: device arrays travel via compiled XLA collectives (ICI) and large
host objects via the shared-memory store.

Wire format: 4-byte header (<I: payload length) + pickled
(msg_id, kind, method, payload[, meta]).  kind: 0=request 1=reply 2=error
3=push.  The optional 5th element is a small dict of frame metadata —
"tp" (W3C traceparent for cross-process span nesting), "ts" (publisher
wall-clock stamp for pubsub fan-out latency), "re" (reply served from
the idempotency replay cache) — attached only when non-empty so the
common frame stays byte-identical to the 4-tuple format.
"""

from __future__ import annotations

import logging
import os
import pickle
import random
import selectors
import socket
import struct
import threading
import time
import traceback
from collections import OrderedDict
from concurrent.futures import Future
from typing import Any, Callable, Dict, Optional, Tuple

from ray_tpu._private.rpc_stats import (LatencyHist, MethodStats, budget_ms,
                                        record_pubsub_delivery)

logger = logging.getLogger(__name__)

# lazily-bound tracing module (None = not yet imported, False = unavailable)
_tracing: Any = None


def _trace_mod():
    global _tracing
    if _tracing is None:
        try:
            from ray_tpu.util import tracing as t
            _tracing = t
        except Exception:  # pragma: no cover - partial-install guard
            _tracing = False
    return _tracing

_HEADER = struct.Struct("<I")
REQUEST, REPLY, ERROR, PUSH = 0, 1, 2, 3

# Big frames allowed (object transfer fallback path), but the data plane
# should use the shm store instead.
MAX_FRAME = 1 << 31


class RpcError(Exception):
    """Remote handler raised; carries the remote traceback string."""


def _invoke(cb, value, exc) -> None:
    try:
        cb(value, exc)
    except Exception:
        logger.exception("reply callback failed")


class ConnectionLost(Exception):
    """Peer went away before replying."""


# Reserved payload key for idempotent requests: a caller stamps a dict
# payload with a unique token and the Server records the first reply under
# it, replaying the recording for duplicates.  This is what makes blind
# retries after a reconnect safe — a re-sent request_lease whose original
# reply was lost to the partition cannot place a second lease.
IDEM_KEY = "_idem"


def idem_token() -> str:
    """Globally-unique idempotency token (96 random bits)."""
    return os.urandom(12).hex()


class Backoff:
    """Jittered exponential backoff for reconnect/retry loops.

    Attempt n sleeps uniform(d/2, d) with d = min(cap, base * 2**n): the
    mean still doubles per attempt but a fleet of raylets re-homing after
    a control restart decorrelates instead of stampeding in lockstep.
    """

    def __init__(self, base: float = 0.05, cap: float = 2.0,
                 rng: Optional[random.Random] = None):
        self.base = max(1e-4, float(base))
        self.cap = max(self.base, float(cap))
        self.attempt = 0
        self._rng = rng or random.Random()

    def next_delay(self) -> float:
        d = min(self.cap, self.base * (2 ** min(self.attempt, 32)))
        self.attempt += 1
        return self._rng.uniform(d / 2, d)

    def sleep(self, max_s: Optional[float] = None) -> float:
        d = self.next_delay()
        if max_s is not None:
            d = max(0.0, min(d, max_s))
        time.sleep(d)
        return d

    def reset(self) -> None:
        self.attempt = 0


def _dumps(obj: Any) -> bytes:
    return pickle.dumps(obj, protocol=5)


def _pack_frame(msg_id: int, kind: int, method: str, payload: Any,
                meta: Optional[Dict[str, Any]] = None) -> bytes:
    """Serialize one frame; the meta element rides only when non-empty."""
    if meta:
        return _dumps((msg_id, kind, method, payload, meta))
    return _dumps((msg_id, kind, method, payload))


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise ConnectionLost("socket closed")
        got += r
    return bytes(buf)


def send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(_HEADER.pack(len(payload)) + payload)


if hasattr(socket.socket, "sendmsg"):

    def send_vec(sock: socket.socket, bufs: list) -> None:
        """Scatter-gather send of a buffer list: one syscall per ~1MB of
        frames instead of one per frame, and no join() copy."""
        views = [memoryview(b) for b in bufs]
        i = 0
        while i < len(views):
            n = sock.sendmsg(views[i:])
            while i < len(views) and n >= len(views[i]):
                n -= len(views[i])
                i += 1
            if i < len(views) and n:
                views[i] = views[i][n:]
else:  # pragma: no cover - non-POSIX fallback

    def send_vec(sock: socket.socket, bufs: list) -> None:
        sock.sendall(b"".join(bufs))


def recv_frame(sock: socket.socket) -> bytes:
    (n,) = _HEADER.unpack(_recv_exact(sock, _HEADER.size))
    if n > MAX_FRAME:
        raise RpcError(f"frame too large: {n}")
    return _recv_exact(sock, n)


def free_port() -> int:
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class DaemonPool:
    """Minimal thread pool with daemon threads (so a wedged handler can
    never block interpreter exit, unlike concurrent.futures)."""

    def __init__(self, max_workers: int, name: str = "pool"):
        import queue as _q

        self._q: "_q.Queue" = _q.Queue()
        self._name = name
        self._threads = []
        for i in range(max_workers):
            t = threading.Thread(target=self._run, name=f"{name}-{i}", daemon=True)
            t.start()
            self._threads.append(t)

    def submit(self, fn, *args, **kwargs) -> Future:
        fut: Future = Future()
        self._q.put((fut, fn, args, kwargs))
        return fut

    def _run(self):
        while True:
            item = self._q.get()
            if item is None:
                return
            fut, fn, args, kwargs = item
            if not fut.set_running_or_notify_cancel():
                continue
            try:
                fut.set_result(fn(*args, **kwargs))
            except BaseException as e:
                fut.set_exception(e)

    def shutdown(self, wait: bool = False):
        for _ in self._threads:
            self._q.put(None)


# ---------------------------------------------------------------------------
# Client
# ---------------------------------------------------------------------------


class Client:
    """Thread-safe RPC client: concurrent in-flight calls over one socket.

    A single reader thread demultiplexes replies to per-call futures and
    dispatches server pushes to `on_push`.  Mirrors the role of the
    reference's ClientCallManager (client_call.h:189).
    """

    def __init__(
        self,
        addr: Tuple[str, int],
        on_push: Optional[Callable[[str, Any], None]] = None,
        on_disconnect: Optional[Callable[[], None]] = None,
        connect_timeout: float = 30.0,
        name: str = "",
    ):
        self.addr = tuple(addr)
        self.name = name
        self._on_push = on_push
        self._on_disconnect = on_disconnect
        self._lock = threading.Lock()
        self._next_id = 0
        self._inflight: Dict[int, Tuple[Callable, str]] = {}
        # per-method counters, guarded by _lock:
        # [calls, bytes_out, replies, errors, bytes_in, replays]
        self._cstats: Dict[str, list] = {}
        self._closed = False
        deadline = time.monotonic() + connect_timeout
        last_err: Optional[Exception] = None
        while True:
            try:
                # per-attempt socket timeout must not exceed the overall
                # budget: a host-down peer (SYN dropped) blocks the whole
                # attempt, and a caller asking for a 0.5s bound must not
                # wait 5s for it
                self._sock = socket.create_connection(
                    self.addr, timeout=min(5.0, connect_timeout))
                break
            except OSError as e:  # daemon may still be booting
                last_err = e
                if time.monotonic() > deadline:
                    raise ConnectionLost(
                        f"cannot connect to {self.addr}: {last_err}"
                    ) from e
                time.sleep(0.05)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock.settimeout(None)
        # Combining writer: callers enqueue framed payloads and a dedicated
        # thread drains the queue with one scatter-gather sendmsg per
        # batch.  Under bursts (pipelined task pushes) dozens of frames
        # ride one syscall; a lone sync call costs one ~15us handoff in
        # place of its ~40us sendall.  Order is strictly FIFO — actor-task
        # ordering depends on per-connection frame order.
        import collections

        self._outq: "collections.deque" = collections.deque()
        self._out_cv = threading.Condition()
        self._writer = threading.Thread(
            target=self._write_loop, name=f"rpc-client-writer-{name}", daemon=True
        )
        self._writer.start()
        self._reader = threading.Thread(
            target=self._read_loop, name=f"rpc-client-reader-{name}", daemon=True
        )
        self._reader.start()

    # -- public ------------------------------------------------------------

    def call(self, method: str, payload: Any = None, timeout: Optional[float] = None) -> Any:
        t = _trace_mod()
        if t and t.is_enabled() and t.frame_traceparent() is not None:
            # CLIENT span around the round trip; the traceparent rides
            # the frame meta (call_cb) so the server handler nests under
            with t.rpc_client_span(method, peer=f"{self.addr[0]}:"
                                                f"{self.addr[1]}"):
                return self.call_async(method, payload).result(
                    timeout=timeout)
        fut = self.call_async(method, payload)
        return fut.result(timeout=timeout)

    def call_async(self, method: str, payload: Any = None) -> Future:
        fut: Future = Future()

        def fill(value, exc):
            if exc is not None:
                fut.set_exception(exc)
            else:
                fut.set_result(value)

        self.call_cb(method, payload, fill)
        return fut

    def call_cb(self, method: str, payload: Any,
                cb: Callable[[Any, Optional[BaseException]], None]) -> None:
        """Request whose reply invokes cb(payload, exc) directly on the
        read thread — the task-push hot path uses this to skip a Future
        allocation + lock round + done-callback machinery per task.

        Exactly-once delivery: every completion path (reply, error frame,
        send failure, teardown) pops the slot from _inflight first, so a
        send failure racing reader teardown cannot invoke cb twice."""
        closed = False
        with self._lock:
            if self._closed:
                closed = True  # invoke outside the lock: cb may re-enter
            else:
                self._next_id += 1
                msg_id = self._next_id
                self._inflight[msg_id] = (cb, method)
        if closed:
            _invoke(cb, None, ConnectionLost(f"client to {self.addr} closed"))
            return
        meta = None
        t = _trace_mod()
        if t and t.is_enabled():
            # sampled contexts only: suppressed requests skip the meta
            # dict + traceparent formatting (and the server-side parse)
            tp = t.frame_traceparent()
            if tp:
                meta = {"tp": tp}
        try:
            data = _pack_frame(msg_id, REQUEST, method, payload, meta)
        except BaseException:
            with self._lock:
                self._inflight.pop(msg_id, None)
            raise
        with self._lock:
            st = self._cstats.get(method)
            if st is None:
                st = self._cstats[method] = [0, 0, 0, 0, 0, 0]
            st[0] += 1
            st[1] += len(data)
        try:
            self._enqueue(data)
        except ConnectionLost as e:
            with self._lock:
                slot = self._inflight.pop(msg_id, None)
            if slot is not None:  # teardown may have delivered it already
                _invoke(cb, None, e)

    def notify(self, method: str, payload: Any = None) -> None:
        """One-way message; no reply expected (msg_id 0)."""
        data = _dumps((0, REQUEST, method, payload))
        with self._lock:
            st = self._cstats.get(method)
            if st is None:
                st = self._cstats[method] = [0, 0, 0, 0, 0, 0]
            st[0] += 1
            st[1] += len(data)
        self._enqueue(data)

    def stats_raw(self) -> Dict[str, list]:
        """Per-method raw counters
        [calls, bytes_out, replies, errors, bytes_in, replays]."""
        with self._lock:
            return {m: list(s) for m, s in self._cstats.items()}

    def stats(self) -> Dict[str, Dict[str, int]]:
        """Client-side per-method stats (mirror of the server's view)."""
        return {m: {"calls": s[0], "bytes_out": s[1], "replies": s[2],
                    "errors": s[3], "bytes_in": s[4], "replays": s[5]}
                for m, s in self.stats_raw().items()}

    def _enqueue(self, data: bytes) -> None:
        # after close/teardown the writer is gone — surface the failure
        # like the old synchronous send did instead of queueing forever
        if self._closed:
            raise ConnectionLost(f"client to {self.addr} closed")
        with self._out_cv:
            self._outq.append(data)
            self._out_cv.notify()

    def _write_loop(self) -> None:
        # 2 iovecs per frame, UIO_MAXIOV=1024 → cap well below it
        MAX_BATCH, MAX_BYTES = 256, 1 << 20
        sent_error = False
        try:
            while True:
                with self._out_cv:
                    while not self._outq and not self._closed:
                        self._out_cv.wait()
                    # graceful close: drain everything already enqueued
                    # (one-shot clients notify() then close() immediately —
                    # dropping those frames loses lease returns / object
                    # frees); a dead socket aborts us via OSError instead
                    if self._closed and not self._outq:
                        return
                    batch, nbytes = [], 0
                    while self._outq and len(batch) < MAX_BATCH \
                            and nbytes < MAX_BYTES:
                        d = self._outq.popleft()
                        batch.append(d)
                        nbytes += len(d)
                bufs = []
                for d in batch:
                    bufs.append(_HEADER.pack(len(d)))
                    bufs.append(d)
                send_vec(self._sock, bufs)
        except OSError:
            sent_error = True
        finally:
            if sent_error:
                # the reader owns teardown; make it notice
                try:
                    self._sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
        with self._out_cv:
            self._out_cv.notify_all()
        if threading.current_thread() is not self._writer:
            # let queued frames flush before tearing the socket down;
            # one-way frames here can be resource releases (lease
            # returns, object frees, actor_del_ref) whose silent loss
            # leaks the resource on the peer — extend the drain while
            # frames remain and say so if we give up on a stalled peer
            self._writer.join(timeout=5.0)
            if self._writer.is_alive() and self._outq:
                self._writer.join(timeout=10.0)
                if self._writer.is_alive() and self._outq:
                    logging.getLogger(__name__).warning(
                        "client %s: dropping %d queued frame(s) at close "
                        "(peer stalled) — peer-side resources they "
                        "release may leak until reclaimed by liveness "
                        "checks", self.name, len(self._outq))
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()

    @property
    def closed(self) -> bool:
        return self._closed

    # -- internals ---------------------------------------------------------

    def _read_loop(self) -> None:
        # Buffered framing: one recv per kernel burst instead of two per
        # frame (header + payload) — syscalls dominate small-RPC cost on
        # sandboxed kernels, and reply bursts arrive coalesced anyway.
        buf = bytearray()
        want = -1  # payload length being assembled; -1 = reading header
        hsize = _HEADER.size
        try:
            while True:
                chunk = self._sock.recv(1 << 20)
                if not chunk:
                    raise ConnectionLost("socket closed")
                buf += chunk
                while True:
                    if want < 0:
                        if len(buf) < hsize:
                            break
                        (want,) = _HEADER.unpack(bytes(buf[:hsize]))
                        if want > MAX_FRAME:
                            raise RpcError(f"frame too large: {want}")
                        del buf[:hsize]
                    if len(buf) < want:
                        break
                    frame = bytes(buf[:want])
                    del buf[:want]
                    want = -1
                    self._handle_frame(frame)
        except (ConnectionLost, OSError, EOFError, pickle.UnpicklingError,
                RpcError):
            pass
        finally:
            with self._lock:
                self._closed = True
                inflight, self._inflight = self._inflight, {}
            with self._out_cv:
                self._out_cv.notify_all()  # release the writer thread
            lost = ConnectionLost(f"connection to {self.addr} lost")
            for cb, _method in inflight.values():
                _invoke(cb, None, lost)
            if self._on_disconnect is not None:
                try:
                    self._on_disconnect()
                except Exception:
                    logger.exception("disconnect handler failed")

    def _handle_frame(self, frame: bytes) -> None:
        rec = pickle.loads(frame)
        msg_id, kind, method, payload = rec[0], rec[1], rec[2], rec[3]
        meta = rec[4] if len(rec) > 4 else None
        if kind in (REPLY, ERROR):
            slot = self._inflight.pop(msg_id, None)
            if slot is None:
                return
            cb, m = slot
            with self._lock:
                st = self._cstats.get(m)
                if st is None:
                    st = self._cstats[m] = [0, 0, 0, 0, 0, 0]
                st[4] += len(frame)
                st[2 if kind == REPLY else 3] += 1
                if meta and meta.get("re"):
                    st[5] += 1
            if kind == REPLY:
                _invoke(cb, payload, None)
            else:
                _invoke(cb, None, RpcError(payload))
        elif kind == PUSH:
            if meta and "ts" in meta:
                topic = method[4:] if method.startswith("pub:") else method
                record_pubsub_delivery(topic, time.time() - meta["ts"])
            if self._on_push is not None:
                try:
                    self._on_push(method, payload)
                except Exception:
                    logger.exception("push handler failed for %s", method)


class ResilientClient:
    """Self-healing RPC client: a Client that survives connection loss.

    Three guarantees on top of the raw Client:

    * reconnect with jittered exponential backoff (Backoff), re-resolving
      the peer address via ``addr_source`` on every attempt so a failover
      to a promoted standby is followed automatically;
    * per-call deadlines: ``timeout`` bounds the WHOLE call — connect
      time, reconnect retries and the in-flight wait all draw from one
      budget;
    * idempotent replay: ``call(..., idempotent=True)`` stamps the payload
      with an IDEM_KEY token, so a blind retry after a reconnect is
      answered from the server's replay cache instead of re-executing.

    Non-idempotent calls never retry once the request may have been sent:
    they surface ConnectionLost exactly like a plain Client.
    """

    def __init__(self, addr: Tuple[str, int], *,
                 addr_source: Optional[Callable[[], Any]] = None,
                 on_push: Optional[Callable[[str, Any], None]] = None,
                 backoff_base_s: float = 0.05, backoff_cap_s: float = 2.0,
                 seed: Optional[int] = None, name: str = ""):
        self._addr = tuple(addr)
        self._addr_source = addr_source
        self._on_push = on_push
        self.name = name
        self._backoff_args = (backoff_base_s, backoff_cap_s)
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._cli: Optional[Client] = None
        self._closed = False
        # flap-cost accounting (see client_stats): per-method
        # [attempts, retries]; plus stats carried over from replaced
        # Client instances so reconnects don't zero the byte counters
        self._rstats: Dict[str, list] = {}
        self._reconnects = 0
        self._backoff_s = 0.0
        self._prev_cstats: Dict[str, list] = {}

    @property
    def addr(self) -> Tuple[str, int]:
        return self._addr

    @property
    def closed(self) -> bool:
        return self._closed

    def _current_addr(self) -> Tuple[str, int]:
        if self._addr_source is not None:
            try:
                a = self._addr_source()
                if a:
                    self._addr = tuple(a)
            except Exception:
                pass
        return self._addr

    def _ensure(self, deadline: float) -> Client:
        with self._lock:
            if self._closed:
                raise ConnectionLost(f"{self.name or 'client'} closed")
            cli = self._cli
        if cli is not None and not cli.closed:
            return cli
        bo = Backoff(*self._backoff_args, rng=self._rng)
        last: Optional[Exception] = None
        while True:
            budget = deadline - time.monotonic()
            if budget <= 0:
                raise ConnectionLost(
                    f"{self.name or 'client'}: could not (re)connect to "
                    f"{self._addr} before deadline: {last}")
            try:
                cli = Client(self._current_addr(), on_push=self._on_push,
                             connect_timeout=min(2.0, max(0.1, budget)),
                             name=f"{self.name}~resilient")
            except Exception as e:
                last = e
                bo.sleep(max_s=max(0.0, deadline - time.monotonic()))
                continue
            with self._lock:
                if self._closed:
                    cli.close()
                    raise ConnectionLost(f"{self.name or 'client'} closed")
                old, self._cli = self._cli, cli
            if old is not None and old is not cli:
                from ray_tpu._private.rpc_stats import merge_client_stats

                prev = old.stats_raw()
                old.close()
                with self._lock:
                    self._reconnects += 1
                    merge_client_stats(self._prev_cstats, prev)
            return cli

    def call(self, method: str, payload: Any = None,
             timeout: float = 30.0, idempotent: bool = False) -> Any:
        deadline = time.monotonic() + timeout
        if idempotent and isinstance(payload, dict) \
                and IDEM_KEY not in payload:
            payload = {**payload, IDEM_KEY: idem_token()}
        bo = Backoff(*self._backoff_args, rng=self._rng)
        while True:
            cli = self._ensure(deadline)
            budget = deadline - time.monotonic()
            if budget <= 0:
                raise ConnectionLost(
                    f"deadline exceeded calling {method!r}")
            with self._lock:
                rs = self._rstats.get(method)
                if rs is None:
                    rs = self._rstats[method] = [0, 0]
                rs[0] += 1
            try:
                return cli.call(method, payload, timeout=budget)
            except (ConnectionLost, OSError) as e:
                # the request may or may not have executed; only a
                # tokened (idempotent) call is safe to blind-retry
                if not idempotent or self._closed:
                    raise
                if time.monotonic() >= deadline:
                    raise ConnectionLost(
                        f"deadline exceeded retrying {method!r}: {e}")
                slept = bo.sleep(max_s=max(0.0,
                                           deadline - time.monotonic()))
                with self._lock:
                    rs[1] += 1
                    self._backoff_s += slept

    def notify(self, method: str, payload: Any = None,
               timeout: float = 5.0) -> None:
        cli = self._ensure(time.monotonic() + timeout)
        cli.notify(method, payload)

    def client_stats(self) -> Dict[str, Any]:
        """Partition-flap cost view: per-method wire counters (summed
        across every connection epoch) plus attempts/retries from the
        resilient retry loop, reconnect count and total backoff sleep."""
        from ray_tpu._private.rpc_stats import merge_client_stats

        with self._lock:
            cli = self._cli
            agg = {m: list(s) for m, s in self._prev_cstats.items()}
            rstats = {m: list(v) for m, v in self._rstats.items()}
            reconnects, backoff_s = self._reconnects, self._backoff_s
        if cli is not None:
            merge_client_stats(agg, cli.stats_raw())
        methods = {}
        for m in set(agg) | set(rstats):
            s = agg.get(m, [0] * 6)
            r = rstats.get(m, [0, 0])
            methods[m] = {"calls": s[0], "bytes_out": s[1],
                          "replies": s[2], "errors": s[3],
                          "bytes_in": s[4], "replays": s[5],
                          "attempts": r[0], "retries": r[1]}
        return {"methods": methods, "reconnects": reconnects,
                "backoff_s": round(backoff_s, 3)}

    def close(self) -> None:
        with self._lock:
            self._closed = True
            cli, self._cli = self._cli, None
        if cli is not None:
            cli.close()


# ---------------------------------------------------------------------------
# Server
# ---------------------------------------------------------------------------


class ServerConn:
    """Per-connection server-side handle; used to push messages (pubsub)."""

    def __init__(self, server: "Server", sock: socket.socket, peer: Tuple[str, int]):
        self.server = server
        self.sock = sock
        self.peer = peer
        self.send_lock = threading.Lock()
        self.meta: Dict[str, Any] = {}  # handlers stash identity here
        self.alive = True
        self._buf = bytearray()
        self._want = -1  # payload size being assembled, -1 = reading header

    def push(self, topic: str, payload: Any,
             meta: Optional[Dict[str, Any]] = None) -> bool:
        try:
            data = _pack_frame(0, PUSH, topic, payload, meta)
            with self.send_lock:
                send_frame(self.sock, data)
            return True
        except OSError:
            return False

    def send_raw(self, data: bytes) -> bool:
        """Send an already-serialized frame (fan-out paths pickle the
        frame once and send it to N subscribers)."""
        try:
            with self.send_lock:
                send_frame(self.sock, data)
            return True
        except OSError:
            return False

    def reply(self, msg_id: int, payload: Any,
              meta: Optional[Dict[str, Any]] = None) -> int:
        if msg_id == 0:
            return 0
        data = _pack_frame(msg_id, REPLY, "", payload, meta)
        with self.send_lock:
            send_frame(self.sock, data)
        return len(data)

    def reply_error(self, msg_id: int, err: str,
                    meta: Optional[Dict[str, Any]] = None) -> int:
        if msg_id == 0:
            return 0
        data = _pack_frame(msg_id, ERROR, "", err, meta)
        with self.send_lock:
            send_frame(self.sock, data)
        return len(data)


class Deferred:
    """Return from a handler to defer the reply; call resolve/reject later."""

    def __init__(self, conn: ServerConn, msg_id: int,
                 server: Optional["Server"] = None,
                 method: Optional[str] = None,
                 t0: Optional[float] = None):
        self._conn = conn
        self._msg_id = msg_id
        self._server = server
        self._method = method
        self._t0 = t0
        self._done = False

    def _finish(self, err: bool, nbytes: int) -> None:
        # deferred replies are the true request latency for long-polls:
        # record handle-time (and close the in-flight slot) at resolve
        if self._done or self._server is None or self._t0 is None:
            return
        self._done = True
        self._server._observe_done(
            self._method, time.perf_counter() - self._t0, err, nbytes)

    def resolve(self, payload: Any = None) -> None:
        nbytes = 0
        try:
            nbytes = self._conn.reply(self._msg_id, payload)
        except OSError:
            pass
        self._finish(False, nbytes)

    def reject(self, err: str) -> None:
        nbytes = 0
        try:
            nbytes = self._conn.reply_error(self._msg_id, err)
        except OSError:
            pass
        self._finish(True, nbytes)


class _ReplayEntry:
    """One recorded (or in-flight) idempotent execution (see IDEM_KEY)."""

    __slots__ = ("done", "value", "is_error", "waiters")

    def __init__(self):
        self.done = False
        self.value: Any = None
        self.is_error = False
        # (conn, msg_id) of duplicate callers parked until the first
        # execution resolves — a retry can race the original in flight
        self.waiters: list = []


class _RecordingDeferred(Deferred):
    """Deferred that records its outcome in the server's replay cache
    (releasing parked duplicate callers) before replying."""

    def __init__(self, server: "Server", token: str, conn: ServerConn,
                 msg_id: int, method: Optional[str] = None,
                 t0: Optional[float] = None):
        super().__init__(conn, msg_id, server=server, method=method, t0=t0)
        self._token = token

    def resolve(self, payload: Any = None) -> None:
        self._server._replay_finish(self._token, payload)
        super().resolve(payload)

    def reject(self, err: str) -> None:
        self._server._replay_fail(self._token, err)
        super().reject(err)


class Server:
    """Selector-based RPC server.

    Handlers: fn(conn: ServerConn, payload) -> result | Deferred-sentinel.
    A handler that needs to reply later returns `server.DEFER`; it then gets
    a `Deferred` via `conn.meta['_deferred']`... simpler: handlers may accept
    a third positional arg `deferred` by declaring `needs_deferred=True` at
    registration.  Runs its event loop in a dedicated thread.  Handler
    execution happens on the event-loop thread — handlers must not block; long
    work goes to executor threads owned by the embedding daemon.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0, name: str = "rpc"):
        self.name = name
        self._sel = selectors.DefaultSelector()
        self._listen = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listen.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listen.bind((host, port))
        self._listen.listen(512)
        self._listen.setblocking(False)
        self.addr: Tuple[str, int] = self._listen.getsockname()
        self._handlers: Dict[str, Tuple[Callable, bool]] = {}
        self._on_disconnect: Optional[Callable[[ServerConn], None]] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._conns: Dict[socket.socket, ServerConn] = {}
        # per-handler flight recorder (reference: event_stats.h asio
        # handler instrumentation): method -> MethodStats with count,
        # in-flight, bytes, queue-wait and handle-time histograms.
        # Handlers run ON the loop thread, so a slow one stalls every
        # connection — these numbers find it.  _stats_lock is a leaf
        # lock (nothing is called while holding it): the loop thread and
        # off-loop Deferred completions both write here.
        self._stats_lock = threading.Lock()
        self._mstats: Dict[str, MethodStats] = {}
        # event-loop health: scheduled-vs-actual tick delta (a stalled
        # loop shows up as lag even when no RPC is in flight) and
        # frames-per-drain batching depth
        self._loop_lag = LatencyHist()
        self._loop_tick_s = 0.02
        self._drain_stats = [0, 0, 0]  # [drains, frames, max_batch]
        # Idempotency replay cache: token -> _ReplayEntry.  Bounded LRU;
        # a duplicate of a still-running execution is parked, a duplicate
        # of a finished one gets the recorded reply without re-executing.
        self._replay: "OrderedDict[str, _ReplayEntry]" = OrderedDict()
        self._replay_cap = 4096
        self._replay_lock = threading.Lock()
        self.handle("rpc_stats", lambda c, p: self.stats())
        self.handle("loop_stats", lambda c, p: self.loop_stats())

    def handle(self, method: str, fn: Callable, deferred: bool = False) -> None:
        self._handlers[method] = (fn, deferred)

    def stats(self) -> Dict[str, Dict[str, Any]]:
        """Per-handler flight-recorder snapshot: every REGISTERED method
        gets a row (zeros until first call) so consumers see the full
        handler surface, not just the hot set."""
        with self._stats_lock:
            for m in self._handlers:
                if m not in self._mstats:
                    self._mstats[m] = MethodStats(budget_ms(m))
            return {m: st.snapshot() for m, st in self._mstats.items()}

    def loop_stats(self) -> Dict[str, Any]:
        """Event-loop health: tick lag + dispatch batching depth."""
        with self._stats_lock:
            drains, frames, max_batch = self._drain_stats
            return {
                "lag_ms": self._loop_lag.snapshot(),
                "tick_s": self._loop_tick_s,
                "drains": drains,
                "frames": frames,
                "max_drain_batch": max_batch,
                "connections": len(self._conns),
            }

    def _observe_done(self, method: Optional[str], dt: float, err: bool,
                      nbytes: int, st: Optional[MethodStats] = None) -> None:
        """Close one request's accounting (sync reply, error reply, or a
        Deferred resolving later from an executor thread)."""
        if st is None:
            if method is None:
                return
            with self._stats_lock:
                st = self._mstats.get(method)
            if st is None:
                return
        warn_over = None
        with self._stats_lock:
            st.inflight -= 1
            st.handle.observe(dt)
            st.bytes_out += nbytes
            if err:
                st.errors += 1
            b = st.budget_ms
            if b is not None and dt * 1e3 > b:
                st.budget_exceeded += 1
                now = time.monotonic()
                if now - st.last_warn > 30.0:
                    st.last_warn = now
                    warn_over = (b, st.budget_exceeded)
        if warn_over is not None:
            logger.warning(
                "%s: handler %r took %.1fms (budget %.1fms, %d "
                "over-budget so far) — it runs on the event loop and "
                "stalls every connection", self.name, method, dt * 1e3,
                warn_over[0], warn_over[1])

    def on_disconnect(self, fn: Callable[[ServerConn], None]) -> None:
        self._on_disconnect = fn

    def start(self, thread: bool = True) -> None:
        self._sel.register(self._listen, selectors.EVENT_READ, None)
        if thread:
            self._thread = threading.Thread(
                target=self._loop, name=f"rpc-server-{self.name}", daemon=True
            )
            self._thread.start()
        else:
            self._loop()

    def stop(self) -> None:
        self._stop.set()
        try:
            # poke the selector awake
            s = socket.create_connection(self.addr, timeout=1.0)
            s.close()
        except OSError:
            pass
        if self._thread is not None and self._thread is not threading.current_thread():
            self._thread.join(timeout=5.0)

    # -- loop --------------------------------------------------------------

    def _loop(self) -> None:
        # loop-lag probe: schedule a tick every _loop_tick_s; any handler
        # that wedges the loop shows up as (actual - scheduled) lateness
        tick = self._loop_tick_s
        next_tick = time.perf_counter() + tick
        while not self._stop.is_set():
            timeout = min(0.5, max(0.0, next_tick - time.perf_counter()))
            events = self._sel.select(timeout=timeout)
            for key, _ in events:
                if key.fileobj is self._listen:
                    self._accept()
                else:
                    self._read(key.fileobj)
            now = time.perf_counter()
            if now >= next_tick:
                with self._stats_lock:
                    self._loop_lag.observe(now - next_tick)
                next_tick = now + tick
        for sock in list(self._conns):
            self._drop(sock)
        self._sel.close()
        self._listen.close()

    def _accept(self) -> None:
        try:
            sock, peer = self._listen.accept()
        except OSError:
            return
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # Socket stays BLOCKING: the selector only fires _read when data is
        # available (recv returns what's there without blocking), and writes
        # (replies/pushes, possibly multi-MB, possibly from worker threads)
        # need sendall semantics — a non-blocking sendall can partial-write
        # and desync the frame stream.
        conn = ServerConn(self, sock, peer)
        self._conns[sock] = conn
        self._sel.register(sock, selectors.EVENT_READ, conn)

    def _read(self, sock: socket.socket) -> None:
        conn = self._conns.get(sock)
        if conn is None:
            return
        try:
            data = sock.recv(1 << 20)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            data = b""
        if not data:
            self._drop(sock)
            return
        conn._buf += data
        self._drain(conn, time.perf_counter())

    def _drain(self, conn: ServerConn, t_arr: Optional[float] = None) -> None:
        buf = conn._buf
        nframes = 0
        while True:
            if conn._want < 0:
                if len(buf) < _HEADER.size:
                    break
                (conn._want,) = _HEADER.unpack(bytes(buf[: _HEADER.size]))
                del buf[: _HEADER.size]
            if len(buf) < conn._want:
                break
            frame = bytes(buf[: conn._want])
            del buf[: conn._want]
            conn._want = -1
            nframes += 1
            # t_arr is the recv time for the whole burst: frame N's
            # queue-wait includes the handle time of frames 1..N-1 ahead
            # of it in this drain batch — that IS the dispatch queue
            self._dispatch(conn, frame, t_arr)
        if nframes:
            with self._stats_lock:
                ds = self._drain_stats
                ds[0] += 1
                ds[1] += nframes
                if nframes > ds[2]:
                    ds[2] = nframes

    def _dispatch(self, conn: ServerConn, frame: bytes,
                  t_arr: Optional[float] = None) -> None:
        try:
            rec = pickle.loads(frame)
            msg_id, kind, method, payload = rec[0], rec[1], rec[2], rec[3]
            meta = rec[4] if len(rec) > 4 else None
        except Exception:
            logger.exception("%s: bad frame from %s", self.name, conn.peer)
            return
        if kind != REQUEST:
            return
        entry = self._handlers.get(method)
        if entry is None:
            conn.reply_error(msg_id, f"no handler for {method!r}")
            return
        t0 = time.perf_counter()
        with self._stats_lock:
            st = self._mstats.get(method)
            if st is None:
                st = self._mstats[method] = MethodStats(budget_ms(method))
            st.count += 1
            st.bytes_in += len(frame)
            if t_arr is not None:
                st.qwait.observe(t0 - t_arr)
        fn, wants_deferred = entry
        token = payload.get(IDEM_KEY) if isinstance(payload, dict) else None
        if token is not None and msg_id != 0:
            if self._replay_begin(conn, msg_id, token):
                with self._stats_lock:
                    st.replays += 1
                return  # duplicate: answered from the cache or parked
        with self._stats_lock:
            st.inflight += 1
        span_cm = None
        if meta is not None and meta.get("tp"):
            t = _trace_mod()
            if t and t.is_enabled():
                span_cm = t.rpc_server_span(
                    method, {"traceparent": meta["tp"]}, server=self.name)
                span_cm.__enter__()
        d: Optional[Deferred] = None
        try:
            if wants_deferred:
                d = (Deferred(conn, msg_id, server=self, method=method,
                              t0=t0) if token is None
                     else _RecordingDeferred(self, token, conn, msg_id,
                                             method=method, t0=t0))
                fn(conn, payload, d)
            else:
                result = fn(conn, payload)
                if token is not None:
                    self._replay_finish(token, result)
                nbytes = conn.reply(msg_id, result)
                self._observe_done(method, time.perf_counter() - t0,
                                   False, nbytes, st=st)
        except Exception as e:
            tb = traceback.format_exc()
            logger.debug("%s: handler %s raised: %s", self.name, method, e)
            err = f"{type(e).__name__}: {e}\n{tb}"
            if token is not None:
                self._replay_fail(token, err)
            if d is not None:
                # the deferred may never resolve after a raise — close
                # its accounting here (unless it already resolved before
                # raising) and make a late resolve a no-op
                if not d._done:
                    d._done = True
                    self._observe_done(method, time.perf_counter() - t0,
                                       True, 0, st=st)
            else:
                self._observe_done(method, time.perf_counter() - t0,
                                   True, 0, st=st)
            try:
                conn.reply_error(msg_id, err)
            except OSError:
                self._drop(conn.sock)
        finally:
            if span_cm is not None:
                span_cm.__exit__(None, None, None)

    # -- idempotency replay (see IDEM_KEY) ----------------------------------

    def _replay_begin(self, conn: ServerConn, msg_id: int,
                      token: str) -> bool:
        """Returns True if this request was handled from the cache (the
        caller must NOT execute the handler)."""
        with self._replay_lock:
            entry = self._replay.get(token)
            if entry is None:
                entry = _ReplayEntry()
                self._replay[token] = entry
                while len(self._replay) > self._replay_cap:
                    old_tok, old = next(iter(self._replay.items()))
                    if not old.done:
                        break  # never evict an in-flight execution
                    self._replay.pop(old_tok)
                return False
            self._replay.move_to_end(token)
            if not entry.done:
                entry.waiters.append((conn, msg_id))
                return True
            value, is_error = entry.value, entry.is_error
        try:
            if is_error:
                conn.reply_error(msg_id, value, meta={"re": 1})
            else:
                conn.reply(msg_id, value, meta={"re": 1})
        except OSError:
            pass
        return True

    def _replay_finish(self, token: str, value: Any) -> None:
        with self._replay_lock:
            entry = self._replay.get(token)
            if entry is None:
                return
            entry.done = True
            entry.value = value
            entry.is_error = False
            waiters, entry.waiters = entry.waiters, []
        for conn, msg_id in waiters:
            try:
                conn.reply(msg_id, value, meta={"re": 1})
            except OSError:
                pass

    def _replay_fail(self, token: str, err: str) -> None:
        """A failed execution is NOT cached — the error may be transient
        and a retry should re-execute; parked duplicates still get it."""
        with self._replay_lock:
            entry = self._replay.pop(token, None)
            waiters = entry.waiters if entry is not None else []
        for conn, msg_id in waiters:
            try:
                conn.reply_error(msg_id, err, meta={"re": 1})
            except OSError:
                pass

    def _drop(self, sock: socket.socket) -> None:
        conn = self._conns.pop(sock, None)
        try:
            self._sel.unregister(sock)
        except (KeyError, ValueError):
            pass
        try:
            sock.close()
        except OSError:
            pass
        if conn is not None:
            conn.alive = False
            if self._on_disconnect is not None:
                try:
                    self._on_disconnect(conn)
                except Exception:
                    logger.exception("%s: disconnect callback failed", self.name)
