"""Control plane server — the cluster-singleton GCS equivalent.

The reference's GcsServer composes per-concern managers (node, resource,
job, actor, placement group, worker, KV, pubsub, health
— reference: src/ray/gcs/gcs_server/gcs_server.h:128-179).  This module is the
TPU-native analog: one process owning

  * node table + resource view (fed by raylet heartbeats, the ray_syncer
    equivalent),
  * internal KV store (function table, collective rendezvous, named objects),
  * pubsub (long-push channels over server->client push frames),
  * actor manager with restart-on-failure (GcsActorManager::RestartActor,
    reference: gcs_actor_manager.cc:1361),
  * placement group manager with 2-phase PREPARE/COMMIT bundle reservation
    (reference: gcs_placement_group_manager.h:230,
    placement_group_resource_manager.h:54-61),
  * health checks via heartbeat timeout
    (reference: gcs_health_check_manager.h).

Scheduling policy: hybrid pack-then-spread over the resource view (reference:
hybrid_scheduling_policy.h:61) extended with TPU topology labels — nodes carry
`tpu_slice`/`tpu_worker_id` labels so gang placement can keep bundles on one
ICI-connected slice.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import pickle
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional, Set, Tuple

from . import common
from . import protocol
from .common import add, fits, normalize_resources, subtract
from .protocol import Client, DaemonPool, Deferred, Server, ServerConn

logger = logging.getLogger(__name__)

# typed flag table (reference: ray_config_def.h); RAY_TPU_* env or
# _system_config overrides.  The generous death timeout absorbs raylet
# heartbeat stalls during worker-spawn (jax import) storms.
from .config import cfg as _cfg

HEARTBEAT_INTERVAL_S = _cfg().heartbeat_interval_s
NODE_DEATH_TIMEOUT_S = _cfg().node_death_timeout_s
DRAIN_GRACE_S = _cfg().drain_grace_s

ALIVE, RESTARTING, DEAD, PENDING = "ALIVE", "RESTARTING", "DEAD", "PENDING"


class NodeRecord:
    def __init__(self, nid: str, addr, resources, labels):
        self.node_id = nid
        self.addr = tuple(addr)
        self.total = dict(resources)
        self.available = dict(resources)
        self.labels = dict(labels or {})
        self.last_heartbeat = time.monotonic()
        self.state = ALIVE
        #: bumped on every (re-)registration; h_disconnect ignores drops
        #: of connections from superseded registrations
        self.reg_epoch = 0
        #: monotonic time of the last TCP drop observed while ALIVE.
        #: A transient disconnect is NOT death — only the heartbeat
        #: timeout (or an explicit unregister_node) declares that.
        self.disconnected_at: Optional[float] = None
        #: last applied availability version (delta resource sync)
        self.avail_version = 0
        #: an optimistic reservation diverged this view from the
        #: raylet's truth; ask the raylet to resend it (delta sync
        #: would otherwise never correct a control-side guess)
        self.needs_resync = False
        #: advisory drain deadline (monotonic): a preemption/maintenance
        #: notice says this host is going away around then.  Draining is
        #: NOT death — the node keeps serving until it actually dies —
        #: but the scheduler avoids it and Train shrinks off it.
        self.draining_until: Optional[float] = None
        self.draining_reason: str = ""
        #: remediation quarantine deadline (monotonic): a sustained-
        #: straggler advisory got this node benched.  Quarantine is NOT
        #: death either — the node stays alive and its vaults readable —
        #: but the scheduler avoids it and Train rebalances off it until
        #: the deadline passes.
        self.quarantined_until: Optional[float] = None
        self.quarantine_reason: str = ""

    def view(self):
        return {
            "node_id": self.node_id,
            "addr": self.addr,
            "total": common.denormalize_resources(self.total),
            "available": common.denormalize_resources(self.available),
            "labels": self.labels,
            "state": self.state,
            # observability for partition tolerance: how many times this
            # node has (re-)registered, and whether its control link is
            # currently down (disconnected but NOT dead)
            "reg_epoch": self.reg_epoch,
            "disconnected": self.disconnected_at is not None,
            "draining": self.draining_until is not None,
            "draining_reason": self.draining_reason,
            "draining_remaining_s": (
                max(0.0, self.draining_until - time.monotonic())
                if self.draining_until is not None else None),
            "quarantined": self.quarantined_until is not None,
            "quarantine_reason": self.quarantine_reason,
            "quarantine_remaining_s": (
                max(0.0, self.quarantined_until - time.monotonic())
                if self.quarantined_until is not None else None),
        }


class ActorRecord:
    def __init__(self, aid: str, spec_blob: bytes, name, resources, max_restarts,
                 owner_id, pg_id=None, bundle_index=-1, detached=False,
                 namespace: str = "default", job_id: str = ""):
        # job_id: the owning *driver* job, when known ("" for actors
        # created from inside workers) — used to reap restored owned
        # actors whose driver never came back after a control restart
        self.job_id = job_id
        # non-PG scheduling strategy dict (node_affinity / node_label /
        # spread) honored at placement
        self.strategy: Optional[Dict] = None
        self.actor_id = aid
        self.spec_blob = spec_blob
        self.name = name
        self.namespace = namespace
        self.resources = resources
        self.max_restarts = max_restarts
        self.restarts = 0
        self.owner_id = owner_id
        self.pg_id = pg_id
        self.bundle_index = bundle_index
        self.detached = detached
        self.state = PENDING
        self.node_id: Optional[str] = None
        self.worker_addr: Optional[Tuple[str, int]] = None
        self.incarnation = 0
        self.error: Optional[str] = None
        self.class_name = ""
        #: validated container spec ({'image': ...}) — the raylet wraps
        #: this actor's dedicated worker in the container runtime
        self.container: Optional[Dict] = None
        self.last_pending_warn = -1e9  # monotonic ts of last pending warning

    def view(self):
        return {
            "actor_id": self.actor_id,
            "name": self.name,
            "namespace": self.namespace,
            "state": self.state,
            "node_id": self.node_id,
            "worker_addr": self.worker_addr,
            "incarnation": self.incarnation,
            "restarts": self.restarts,
            "max_restarts": self.max_restarts,
            "error": self.error,
            "class_name": self.class_name,
            "pg_id": self.pg_id,
            "resources": common.denormalize_resources(self.resources),
        }


class PlacementGroupRecord:
    def __init__(self, pgid: str, bundles: List[Dict[str, int]], strategy: str, name: str):
        self.pg_id = pgid
        self.bundles = bundles
        self.strategy = strategy
        self.name = name
        self.state = PENDING
        # bundle index -> node_id
        self.assignments: Dict[int, str] = {}

    def view(self):
        return {
            "pg_id": self.pg_id,
            "strategy": self.strategy,
            "name": self.name,
            "state": self.state,
            "bundles": [common.denormalize_resources(b) for b in self.bundles],
            "assignments": dict(self.assignments),
        }


def _named_key(namespace: str, name: str) -> str:
    return f"{namespace or 'default'}:{name}"


class _NullDeferred:
    """Stands in for a client Deferred when the control plane reschedules
    restored work at boot — nobody is waiting on the reply."""

    def resolve(self, *_):
        pass

    def reject(self, *_):
        pass


class ControlServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 persist_path: Optional[str] = None,
                 addr_file: Optional[str] = None):
        self.server = Server(host, port, name="control")
        self._addr_file = addr_file
        if addr_file:
            # the cluster's control-plane rendezvous: raylets and drivers
            # re-read this on reconnect, which is how they re-home to a
            # promoted standby at a different address (reference analog:
            # the Redis bootstrap address raylets resolve the GCS from)
            common.write_addr_file(addr_file, self.server.addr)
        self.lock = threading.RLock()
        self.kv: Dict[str, Dict[str, bytes]] = {}  # namespace -> key -> value
        self.nodes: Dict[str, NodeRecord] = {}
        self.actors: Dict[str, ActorRecord] = {}
        self.named_actors: Dict[str, str] = {}
        self.pgs: Dict[str, PlacementGroupRecord] = {}
        self.functions: Dict[str, bytes] = {}
        self.jobs: Dict[str, Dict[str, Any]] = {}
        self.subs: Dict[str, Set[ServerConn]] = {}  # topic -> conns
        self.node_clients: Dict[str, Client] = {}  # node_id -> raylet client
        self.pool = DaemonPool(max_workers=16, name="control")
        self._stop = threading.Event()
        self.start_time = time.time()
        # task-event manager (reference: GcsTaskManager,
        # src/ray/gcs/gcs_server/gcs_task_manager.h): bounded per-task
        # merged lifecycle records + profile spans for the timeline
        self.task_records: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        self.profile_events: List[Dict[str, Any]] = []
        self.task_events_dropped = 0
        self.max_task_records = _cfg().max_task_events
        # ingestion is queue + dedicated merge thread (own lock — event
        # merging must never contend with the scheduler's global lock)
        self._event_queue: deque = deque()
        self._event_queue_cap = 4096  # batches; overflow drops oldest
        self._event_signal = threading.Event()
        self._events_lock = threading.Lock()
        self._drain_lock = threading.Lock()  # one drainer at a time
        self._event_thread = threading.Thread(
            target=self._event_merge_loop, name="control-task-events",
            daemon=True)
        # destroyed-actor cache bound (reference:
        # maximum_gcs_destroyed_actor_cached_count)
        self._dead_actor_order: deque = deque()
        self._max_dead_actors = _cfg().max_dead_actors
        # structured cluster events (reference: src/ray/util/event.h):
        # bounded, seq-ordered; fed by publish() + h_report_event
        self.events: deque = deque(maxlen=_cfg().max_cluster_events)
        self._event_seq = 0
        # pending-actor scheduler queue (reference: GcsActorScheduler)
        self.pending_actors: List[ActorRecord] = []
        self._sched_event = threading.Event()
        # flight-recorder counters (control_stats).  _obs_lock is a LEAF
        # lock: publish() runs with self.lock held on some paths, so
        # nothing may be called while holding it.  KV counters are
        # loop-thread-only plain dicts.
        self._obs_lock = threading.Lock()  # lock-ok: leaf, no calls inside
        # ns -> [ops, bytes_in, bytes_out]
        self._kv_stats: Dict[str, list] = {}
        # topic -> [publishes, deliveries, drops, bytes_out,
        #           fanout_s_sum, fanout_s_max]
        self._pubsub_stats: Dict[str, list] = {}
        # coalesced task-event relay accounting (see h_report_task_events)
        self._relay_batches = 0
        self._relay_dropped = 0
        # distributed-trace span collector (see h_report_spans): batched
        # report_spans notifies land in a bounded queue; a dedicated
        # merge thread folds them per-trace and mirrors each trace as a
        # JSON blob into the _tracing KV namespace (so kv_get serves
        # trace reads), with LRU-cap + idle-TTL eviction
        self._span_queue: deque = deque()  # batches; overflow drops oldest
        self._span_queue_cap = 1024
        self._span_signal = threading.Event()
        self._traces_lock = threading.Lock()
        # trace_id -> list of span dicts
        self.trace_spans: Dict[str, List[Dict[str, Any]]] = {}  # guarded-by: _traces_lock
        # trace_id -> last-merge monotonic ts, LRU-ordered for eviction
        self._trace_index: "OrderedDict[str, float]" = OrderedDict()  # guarded-by: _traces_lock
        self._spans_received = 0       # guarded-by: _traces_lock
        self._span_batches = 0         # guarded-by: _traces_lock
        self._spans_dropped = 0        # guarded-by: _traces_lock
        self._trace_span_overflow = 0  # guarded-by: _traces_lock
        self._traces_evicted = 0       # guarded-by: _traces_lock
        self._trace_store_cap = _cfg().trace_store_cap
        self._trace_store_ttl_s = _cfg().trace_store_ttl_s
        self._trace_spans_per_trace = _cfg().trace_spans_per_trace
        self._span_thread = threading.Thread(
            target=self._span_merge_loop, name="control-trace-spans",
            daemon=True)
        # native C++ selection/planning engine (reference's scheduling core
        # is C++: cluster_resource_scheduler.h, hybrid_scheduling_policy.h);
        # Python keeps authoritative optimistic accounting and mirrors
        # availability into the native engine at every mutation
        self.nsched = None
        if _cfg().native_sched:
            try:
                from ray_tpu.native.sched import try_create
                self.nsched = try_create(spread_threshold=0.5, topk=1)
            except Exception:
                self.nsched = None

        s = self.server
        s.handle("ping", lambda c, p: "pong")
        s.handle("kv_put", self.h_kv_put)
        s.handle("kv_get", self.h_kv_get)
        s.handle("kv_del", self.h_kv_del)
        s.handle("kv_keys", self.h_kv_keys)
        s.handle("kv_exists", self.h_kv_exists)
        s.handle("register_node", self.h_register_node)
        s.handle("unregister_node", self.h_unregister_node)
        s.handle("heartbeat", self.h_heartbeat)
        s.handle("report_draining", self.h_report_draining)
        s.handle("report_quarantine", self.h_report_quarantine)
        s.handle("get_nodes", self.h_get_nodes)
        s.handle("pick_node", self.h_pick_node)
        s.handle("pick_nodes", self.h_pick_nodes)
        s.handle("register_function", self.h_register_function)
        s.handle("get_function", self.h_get_function)
        s.handle("register_job", self.h_register_job)
        s.handle("create_actor", self.h_create_actor, deferred=True)
        s.handle("get_actor", self.h_get_actor)
        s.handle("get_actor_spec", lambda c, p: (
            self.actors[p["actor_id"]].spec_blob
            if p["actor_id"] in self.actors else None))
        s.handle("wait_actor_alive", self.h_wait_actor_alive, deferred=True)
        s.handle("list_actors", self.h_list_actors)
        s.handle("actor_ready", self.h_actor_ready)
        s.handle("actor_failed", self.h_actor_failed)
        s.handle("kill_actor", self.h_kill_actor, deferred=True)
        s.handle("subscribe", self.h_subscribe)
        s.handle("publish", self.h_publish)
        s.handle("create_pg", self.h_create_pg, deferred=True)
        s.handle("remove_pg", self.h_remove_pg, deferred=True)
        s.handle("get_pg", self.h_get_pg)
        s.handle("list_pgs", lambda c, p: [pg.view() for pg in self.pgs.values()])
        s.handle("cluster_resources", self.h_cluster_resources)
        s.handle("state_dump", self.h_state_dump)
        s.handle("report_task_events", self.h_report_task_events)
        s.handle("report_spans", self.h_report_spans)
        s.handle("list_events", self.h_list_events)
        s.handle("report_event", self.h_report_event)
        s.handle("list_task_events", self.h_list_task_events, deferred=True)
        s.handle("list_profile_events", self.h_list_profile_events,
                 deferred=True)
        s.handle("control_stats", self.h_control_stats)
        s.on_disconnect(self.h_disconnect)

        self.health_thread = threading.Thread(
            target=self._health_loop, name="control-health", daemon=True
        )

        # restored owned actors awaiting their driver's re-registration:
        # actor_id -> reap deadline (monotonic)
        self._restored_unclaimed: Dict[str, float] = {}

        # restored ALIVE actors awaiting re-adoption by the raylet that
        # still hosts their live worker (warm-standby failover / in-place
        # restart): actor_id -> reschedule deadline.  A re-registering
        # raylet reports its live actor workers; matches are adopted in
        # place (same incarnation, state preserved); the rest are
        # rescheduled when the deadline passes.
        self._adoptable: Dict[str, float] = {}

        # durable metadata store (reference: redis_store_client.h role —
        # GCS fault tolerance).  Off unless a path is configured.
        from . import persist

        self.pstore = persist.open_store(
            persist_path or os.environ.get("RAY_TPU_CONTROL_PERSIST"))
        if self.pstore is not None:
            self._load_persisted()

    # -- persistence -------------------------------------------------------

    def _persist_actor(self, rec: ActorRecord):
        if rec.state == DEAD:
            # bound the destroyed-actor cache (reference: the GCS keeps
            # maximum_gcs_destroyed_actor_cached_count records): an
            # actor-churning workload (one Tune trial = one actor) would
            # otherwise grow self.actors — and every state_dump reply —
            # forever
            self._note_dead_actor(rec)
        if self.pstore is None:
            return
        # snapshot + write under the table lock so disk ordering can't
        # invert a pair of racing state transitions; DEAD records are
        # pruned (the reference GCS garbage-collects destroyed actors)
        with self.lock:
            if rec.state == DEAD:
                self.pstore.rec_del("actor", rec.actor_id)
                return
            self.pstore.rec_put("actor", rec.actor_id, {
                "spec_blob": rec.spec_blob, "name": rec.name,
                "resources": rec.resources,
                "max_restarts": rec.max_restarts,
                "owner_id": rec.owner_id, "pg_id": rec.pg_id,
                "bundle_index": rec.bundle_index, "detached": rec.detached,
                "job_id": rec.job_id, "strategy": rec.strategy,
                "state": rec.state, "restarts": rec.restarts,
                "incarnation": rec.incarnation, "error": rec.error,
                "class_name": rec.class_name,
                "namespace": rec.namespace,
                "container": rec.container,
            })

    def _persist_pg(self, rec: PlacementGroupRecord):
        if self.pstore is None:
            return
        with self.lock:
            if rec.state == DEAD:
                self.pstore.rec_del("pg", rec.pg_id)
                return
            self.pstore.rec_put("pg", rec.pg_id, {
                "bundles": rec.bundles, "strategy": rec.strategy,
                "name": rec.name, "state": rec.state,
            })

    def _load_persisted(self):
        """Reload durable tables after a control-plane restart
        (reference: GcsInitData reload, gcs_init_data.h).

        Non-PG actors whose workers may still be alive get an ADOPTION
        window first: reconnecting raylets report live actor workers
        (register_node live_actors) and matches resume in place — same
        incarnation, state preserved (the warm-standby promise).  Only
        unclaimed records are rescheduled fresh after the window
        (incarnation bumped; restart budget NOT charged — the failure
        was ours, not the actor's).  PG-placed actors skip adoption and
        reschedule with their group: live placement groups re-run
        2-phase reservation once nodes return."""
        self.kv = self.pstore.load_kv()
        self.functions = self.pstore.load_table("function")
        self.jobs = self.pstore.load_table("job")
        n_actors = n_pgs = 0
        grace = _cfg().restore_owner_grace_s
        for aid, d in self.pstore.load_table("actor").items():
            rec = ActorRecord(aid, d["spec_blob"], d["name"], d["resources"],
                              d["max_restarts"], d["owner_id"], d["pg_id"],
                              d["bundle_index"], d["detached"],
                              namespace=d.get("namespace", "default"),
                              job_id=d.get("job_id", ""))
            rec.class_name = d.get("class_name", "")
            rec.strategy = d.get("strategy")
            rec.container = d.get("container")
            rec.restarts = d.get("restarts", 0)
            rec.incarnation = d.get("incarnation", 0)
            self.actors[aid] = rec
            if d["state"] == DEAD:
                rec.state = DEAD
                rec.error = d.get("error")
                continue
            rec.state = RESTARTING
            rec.incarnation += 1
            if rec.name:
                self.named_actors[_named_key(rec.namespace, rec.name)] = aid
            if rec.pg_id is None:
                self._adoptable[aid] = \
                    time.monotonic() + _cfg().actor_adopt_grace_s
            else:
                self.pending_actors.append(rec)
            # non-detached actors die with their owner in the reference;
            # reschedule optimistically but reap unless the owning driver
            # job re-registers within the grace window (h_register_job
            # claims them; _health_loop reaps the rest)
            if not rec.detached and rec.job_id:
                self._restored_unclaimed[aid] = time.monotonic() + grace
            n_actors += 1
        for pgid, d in self.pstore.load_table("pg").items():
            rec = PlacementGroupRecord(pgid, d["bundles"], d["strategy"],
                                       d["name"])
            self.pgs[pgid] = rec
            if d["state"] == DEAD:
                rec.state = DEAD
                continue
            rec.state = PENDING
            self.pool.submit(self._schedule_pg, rec, _NullDeferred(),
                             600.0, False)
            n_pgs += 1
        if n_actors or n_pgs or self.kv or self.functions:
            logger.info(
                "restored persisted state: %d kv namespaces, %d functions, "
                "%d jobs, %d actors to reschedule, %d PGs to re-reserve",
                len(self.kv), len(self.functions), len(self.jobs),
                n_actors, n_pgs)
        self._sched_event.set()

    # -- lifecycle ---------------------------------------------------------

    def start(self, block: bool = False):
        self.health_thread.start()
        self._event_thread.start()
        self._span_thread.start()
        self._actor_sched_thread = threading.Thread(
            target=self._actor_sched_loop, name="control-actor-sched",
            daemon=True)
        self._actor_sched_thread.start()
        self.server.start(thread=not block)

    def stop(self):
        self._stop.set()
        self._event_signal.set()
        self._span_signal.set()
        if self._event_thread.is_alive():
            self._event_thread.join(timeout=2.0)
        if self._span_thread.is_alive():
            self._span_thread.join(timeout=2.0)
        self.server.stop()
        self.pool.shutdown(wait=False)
        if self.pstore is not None:
            self.pstore.close()

    @property
    def addr(self):
        return self.server.addr

    # -- kv ----------------------------------------------------------------

    def _kv_account(self, ns: str, bytes_in: int = 0, bytes_out: int = 0):
        """Per-namespace op/byte counters: the `_metrics` / `serve` /
        `remediation` namespaces are the control plane's chattiest
        tenants and these numbers name them (all KV handlers run on the
        RPC loop thread, as does the stats reader, so a plain dict
        suffices)."""
        st = self._kv_stats.get(ns)
        if st is None:
            st = self._kv_stats[ns] = [0, 0, 0]
        st[0] += 1
        st[1] += bytes_in
        st[2] += bytes_out

    def h_kv_put(self, conn, p):
        ns, k, v, overwrite = p["ns"], p["key"], p["val"], p.get("overwrite", True)
        self._kv_account(ns, bytes_in=len(v) if isinstance(v, (bytes, bytearray)) else 0)
        with self.lock:
            space = self.kv.setdefault(ns, {})
            if not overwrite and k in space:
                return False
            space[k] = v
            # persisted inside the lock: disk order must match memory order
            if self.pstore is not None:
                self.pstore.kv_put(ns, k, v)
        return True

    def h_kv_get(self, conn, p):
        with self.lock:
            v = self.kv.get(p["ns"], {}).get(p["key"])
        self._kv_account(p["ns"], bytes_out=len(v)
                         if isinstance(v, (bytes, bytearray)) else 0)
        return v

    def h_kv_del(self, conn, p):
        self._kv_account(p["ns"])
        with self.lock:
            found = self.kv.get(p["ns"], {}).pop(p["key"], None) is not None
            if found and self.pstore is not None:
                self.pstore.kv_del(p["ns"], p["key"])
        return found

    def h_kv_keys(self, conn, p):
        prefix = p.get("prefix", "")
        self._kv_account(p["ns"])
        with self.lock:
            return [k for k in self.kv.get(p["ns"], {}) if k.startswith(prefix)]

    def h_kv_exists(self, conn, p):
        self._kv_account(p["ns"])
        with self.lock:
            return p["key"] in self.kv.get(p["ns"], {})

    # -- nodes -------------------------------------------------------------

    def h_register_node(self, conn, p):
        """Cold registration OR re-registration of a live node.

        Re-registration — the control still holds a non-DEAD record for
        this node_id (the raylet reconnected after a transient partition)
        — is *resumed*: the record is refreshed in place, ALIVE actors
        whose node_id matches are re-adopted idempotently (same worker,
        same incarnation — nothing gets killed), and the reply carries
        ``resumed=True`` plus ``assigned_bundles`` (the PG bundles this
        control still places here) so the raylet preserves its PG state
        and reconciles instead of tearing down.  Cold registration gets a
        fresh record; only actors parked in the post-restart adoption
        window can be claimed.
        """
        nid = p["node_id"]
        adopted, rejected, lost = [], [], []
        with self.lock:
            prev = self.nodes.get(nid)
            resumed = prev is not None and prev.state != DEAD
            if resumed:
                rec = prev
                rec.addr = tuple(p["addr"])
                rec.total = normalize_resources(p["resources"])
                rec.labels = dict(p.get("labels") or {})
                rec.last_heartbeat = time.monotonic()
                rec.disconnected_at = None
                # keep the availability view — the raylet's books
                # survived with it; the next heartbeat resyncs truth
                rec.needs_resync = True
            else:
                rec = NodeRecord(nid, p["addr"],
                                 normalize_resources(p["resources"]),
                                 p.get("labels"))
                self.nodes[nid] = rec
            rec.reg_epoch += 1
            if self.nsched is not None:
                self.nsched.upsert_node(rec.node_id, rec.total)
                if resumed:
                    self.nsched.set_available(rec.node_id, rec.available)
            # a re-homing raylet reports actor workers that are still
            # alive on it.  Adoptable: (a) records waiting in the
            # post-restart adoption window, (b) on a resumed node, ALIVE
            # records this control already places here — re-adopted
            # idempotently.  Anything else (already rescheduled
            # elsewhere, reaped, unknown) is rejected and the raylet
            # kills that worker.
            reported = set()
            for la in p.get("live_actors") or []:
                reported.add(la["actor_id"])
                a = self.actors.get(la["actor_id"])
                if (a is not None and a.state == RESTARTING
                        and la["actor_id"] in self._adoptable):
                    a.state = ALIVE
                    a.node_id = rec.node_id
                    a.worker_addr = tuple(la["worker_addr"]) \
                        if la.get("worker_addr") else None
                    a.incarnation = la.get("incarnation", a.incarnation)
                    self._adoptable.pop(la["actor_id"], None)
                    adopted.append(a)
                elif (a is not None and a.state == ALIVE
                        and a.node_id == nid
                        and la.get("incarnation", a.incarnation)
                            == a.incarnation):
                    if la.get("worker_addr"):
                        a.worker_addr = tuple(la["worker_addr"])
                    adopted.append(a)
                else:
                    rejected.append(la["actor_id"])
            if resumed:
                # the inverse direction: actors this control believes
                # are ALIVE here but the raylet no longer hosts died
                # while we were partitioned — fail them now
                lost = [a.actor_id for a in self.actors.values()
                        if a.node_id == nid and a.state == ALIVE
                        and a.actor_id not in reported]
            # PG bundles this control still assigns to the node; the
            # raylet releases anything beyond this set (a remove_pg
            # whose release RPC was lost to the partition)
            assigned = [[pgid, idx]
                        for pgid, pg in self.pgs.items()
                        if pg.state != DEAD
                        for idx, bnid in pg.assignments.items()
                        if bnid == nid]
            conn.meta["node_id"] = rec.node_id
            conn.meta["reg_epoch"] = rec.reg_epoch
        logger.info("node %s %s at %s: %s", rec.node_id[:12],
                    "re-registered (resumed)" if resumed else "registered",
                    rec.addr, p["resources"])
        self.publish("node", {"event": "added", "node": rec.view()})
        for a in adopted:
            self._persist_actor(a)
            self.publish("actor", {"event": "update", "actor": a.view()})
            logger.info("adopted live actor %s on %s (incarnation %d)",
                        a.actor_id[:12], rec.node_id[:12], a.incarnation)
        for aid in lost:
            logger.warning("actor %s lost across re-registration of %s",
                           aid[:12], nid[:12])
            self._on_actor_failure(
                aid, "actor worker lost across raylet re-registration")
        return {"ok": True, "cluster_start_time": self.start_time,
                "resumed": resumed, "assigned_bundles": assigned,
                "rejected_actors": rejected}

    def h_heartbeat(self, conn, p):
        with self.lock:
            rec = self.nodes.get(p["node_id"])
            if rec is None or rec.state == DEAD:
                # a falsely-declared-dead raylet is still running: tell it
                # to wipe its actor workers and re-register (the reference
                # raylet exits and is restarted by its process manager)
                return {"ok": False, "reregister": True}
            rec.last_heartbeat = time.monotonic()
            rec.disconnected_at = None
            if "available" in p:
                # versioned delta sync (reference: ray_syncer.h:44-70):
                # only snapshots newer than the last applied version
                # land — a reordered/raced update can never roll the
                # view backwards
                v = p.get("avail_version", 0)
                if v == 0 or v > rec.avail_version:
                    if v:   # unversioned updates keep the high-water mark
                        rec.avail_version = v
                    rec.available = normalize_resources(p["available"])
                    rec.needs_resync = False
                    if self.nsched is not None:
                        self.nsched.set_available(rec.node_id,
                                                  rec.available)
            # resync: an optimistic pick_node reservation diverged this
            # view from the raylet's truth — delta sync skips unchanged
            # views, so explicitly request the ground truth back
            return {"ok": True, "resync": rec.needs_resync}

    def h_report_draining(self, conn, p):
        """A preemption/maintenance notice for a node: mark the record
        draining and broadcast a ``node_draining`` advisory with its
        deadline over pubsub, so consumers (Train's elastic supervisor,
        schedulers) act BEFORE the heartbeat timeout declares death.
        ``cancel=True`` clears a notice that didn't materialize."""
        nid = p["node_id"]
        cancel = bool(p.get("cancel"))
        with self.lock:
            rec = self.nodes.get(nid)
            if rec is None or rec.state == DEAD:
                return {"ok": False, "error": f"unknown or dead node {nid}"}
            if cancel:
                rec.draining_until = None
                rec.draining_reason = ""
                grace = None
            else:
                grace = float(p.get("grace_s") or DRAIN_GRACE_S)
                rec.draining_until = time.monotonic() + grace
                rec.draining_reason = str(p.get("reason") or "preemption")
            view = rec.view()
            reason = rec.draining_reason
        event = "drain_canceled" if cancel else "draining"
        if cancel:
            logger.info("node %s drain canceled", nid[:12])
        else:
            logger.warning("node %s draining in %.1fs (%s)", nid[:12],
                           grace, reason)
        self.record_event(
            severity="INFO" if cancel else "WARNING", source="node",
            event_type=event, entity_id=nid,
            message=(f"node {nid[:12]} drain canceled" if cancel else
                     f"node {nid[:12]} draining in {grace:.1f}s ({reason})"))
        self.publish("node", {"event": event, "node": view,
                              "grace_s": grace, "reason": reason})
        return {"ok": True}

    def h_report_quarantine(self, conn, p):
        """Remediation benched a node (sustained-straggler quarantine):
        mark the record so the scheduler avoids it, and broadcast a
        ``node_quarantined`` advisory over pubsub so Train executors
        rebalance off it.  ``cancel=True`` clears the bench early; the
        health loop clears it automatically once the grace passes."""
        nid = p["node_id"]
        cancel = bool(p.get("cancel"))
        with self.lock:
            rec = self.nodes.get(nid)
            if rec is None or rec.state == DEAD:
                return {"ok": False, "error": f"unknown or dead node {nid}"}
            if cancel:
                rec.quarantined_until = None
                rec.quarantine_reason = ""
                grace = None
            else:
                grace = float(p.get("grace_s") or 600.0)
                rec.quarantined_until = time.monotonic() + grace
                rec.quarantine_reason = str(
                    p.get("reason") or "sustained straggler")
            view = rec.view()
            reason = rec.quarantine_reason
        event = "quarantine_cleared" if cancel else "quarantined"
        if cancel:
            logger.info("node %s quarantine cleared", nid[:12])
        else:
            logger.warning("node %s quarantined for %.1fs (%s)", nid[:12],
                           grace, reason)
        self.record_event(
            severity="INFO" if cancel else "WARNING", source="remediation",
            event_type=event, entity_id=nid,
            message=(f"node {nid[:12]} quarantine cleared" if cancel else
                     f"node {nid[:12]} quarantined for {grace:.1f}s "
                     f"({reason})"))
        self.publish("node", {"event": event, "node": view,
                              "grace_s": grace, "reason": reason})
        return {"ok": True}

    def h_get_nodes(self, conn, p):
        with self.lock:
            return [n.view() for n in self.nodes.values()]

    def _alive_nodes(self) -> List[NodeRecord]:
        return [n for n in self.nodes.values() if n.state == ALIVE]

    @staticmethod
    def _match_one(labels: Dict[str, str], key: str, op: str,
                   values) -> bool:
        present = key in labels
        if op == "exists":
            return present
        if op == "does_not_exist":
            return not present
        if op == "in":
            return present and str(labels[key]) in values
        if op == "not_in":
            return present and str(labels[key]) not in values
        return False

    def _pick_node_locked(self, demand: Dict[str, int], strategy=None) -> Optional[NodeRecord]:
        """Hybrid policy: pack onto the busiest node that fits (reference
        defaults to pack-then-spread, hybrid_scheduling_policy.h:61); honors
        node-affinity / pg strategies."""
        nodes = self._alive_nodes()
        if strategy is not None:
            kind = strategy.get("kind")
            if kind == "node_affinity":
                n = self.nodes.get(strategy["node_id"])
                if n is not None and n.state == ALIVE and (strategy.get("soft") or fits(n.available, demand)):
                    return n
                if not strategy.get("soft"):
                    return None
            elif kind == "placement_group":
                pg = self.pgs.get(strategy["pg_id"])
                if pg is None or pg.state != ALIVE:
                    return None
                idx = strategy.get("bundle_index", -1)
                if idx >= 0:
                    indices = [idx]
                else:
                    # any-bundle (-1): rotate across assignment nodes so
                    # repeated leases don't pin to one node's bundle while
                    # the group's other bundles idle (per-bundle occupancy
                    # lives node-side; round-robin is the control's lever)
                    indices = list(pg.assignments)
                    pg.rr_cursor = getattr(pg, "rr_cursor", 0) + 1
                    k = pg.rr_cursor % max(1, len(indices))
                    indices = indices[k:] + indices[:k]
                for i in indices:
                    nid = pg.assignments.get(i)
                    n = self.nodes.get(nid)
                    if n is not None and n.state == ALIVE:
                        return n
                return None
            elif kind == "node_label":
                # label matching (reference: NodeLabelSchedulingStrategy,
                # scheduling_strategies.py:135 + label scheduling policy)
                hard = strategy.get("hard") or []
                soft = strategy.get("soft") or []
                def match_all(n, exprs):
                    return all(self._match_one(n.labels or {}, k, op, vals)
                               for (k, op, vals) in exprs)

                cands = [n for n in nodes
                         if fits(n.available, demand)
                         and match_all(n, hard)]
                if not cands:
                    return None
                preferred = [n for n in cands if match_all(n, soft)]
                pool = preferred or cands

                def util(n: NodeRecord) -> float:
                    tot = sum(n.total.values()) or 1
                    return 1.0 - sum(n.available.values()) / tot
                return max(pool, key=util)  # pack among matching nodes
            elif kind == "spread":
                n = self._native_pick(demand, spread=True)
                if n is not None:
                    return n
                cands = [n for n in nodes if fits(n.available, demand)]
                cands = self._prefer_not_draining(cands)
                if not cands:
                    return None
                # least-loaded first
                return min(cands, key=lambda n: sum(v / max(t, 1) for v, t in
                                                    ((n.total.get(k, 0) - n.available.get(k, 0), n.total.get(k, 1))
                                                     for k in n.total)))
        n = self._native_pick(demand, spread=False)
        if n is not None:
            return n
        cands = [n for n in nodes if fits(n.available, demand)]
        cands = self._prefer_not_draining(cands)
        if not cands:
            return None
        # pack: most-utilized node that still fits
        def util(n: NodeRecord) -> float:
            tot = sum(n.total.values()) or 1
            return 1.0 - sum(n.available.values()) / tot
        return max(cands, key=util)

    @staticmethod
    def _prefer_not_draining(cands: List[NodeRecord]) -> List[NodeRecord]:
        """New work avoids draining AND quarantined nodes while any
        untainted node fits — but a tainted node remains a last resort
        (its work is still better placed than not placed; a quarantined
        host is slow, not dead)."""
        fresh = [n for n in cands if n.draining_until is None
                 and n.quarantined_until is None]
        if fresh:
            return fresh
        # among tainted, a merely-quarantined node beats one that is
        # about to disappear
        not_draining = [n for n in cands if n.draining_until is None]
        return not_draining or cands

    def _native_pick(self, demand: Dict[str, int],
                     spread: bool) -> Optional[NodeRecord]:
        """Delegate selection to the native engine; validated against the
        Python books so mirror drift can never hand out a bad node."""
        if self.nsched is None:
            return None
        try:
            from ray_tpu.native.sched import PACK, SPREAD
            nid = self.nsched.pick(demand, SPREAD if spread else PACK)
        except Exception:
            return None
        if nid is None:
            return None
        n = self.nodes.get(nid)
        if n is not None and (n.draining_until is not None
                              or n.quarantined_until is not None):
            # the native mirror doesn't track drains/quarantines; fall
            # back to the Python path, which prefers untainted nodes
            return None
        if n is not None and n.state == ALIVE and fits(n.available, demand):
            return n
        return None

    def h_pick_node(self, conn, p):
        demand = normalize_resources(p.get("resources"))
        with self.lock:
            n = self._pick_node_locked(demand, p.get("strategy"))
            if n is None:
                return None
            # optimistic reservation so concurrent picks spread; the
            # raylet's ground truth comes back via the resync flag on
            # its next heartbeat (delta sync skips unchanged views)
            subtract(n.available, demand)
            n.needs_resync = True
            if self.nsched is not None:
                self.nsched.set_available(n.node_id, n.available)
            return {"node_id": n.node_id, "addr": n.addr}

    def _native_pick_n_locked(self, demand: Dict[str, int],
                              count: int) -> List[Dict[str, str]]:
        """Vectorized native selection: one ctypes call picks AND reserves
        up to `count` placements.  Each returned name is validated against
        the Python books (mirror drift can never hand out a bad node);
        accepted picks copy the native reservation into the Python books
        directly (the native side already subtracted, so set_available
        would double-count); rejected picks are released back and the
        remainder falls through to the Python loop."""
        try:
            from ray_tpu.native.sched import PACK
            out = self.nsched.pick_n(demand, count, PACK)
        except Exception:
            return []
        picks: List[Dict[str, str]] = []
        stop = False
        for nid in out:
            n = self.nodes.get(nid)
            ok = (not stop and n is not None and n.state == ALIVE
                  and n.draining_until is None
                  and n.quarantined_until is None
                  and fits(n.available, demand))
            if ok:
                subtract(n.available, demand)
                n.needs_resync = True
                picks.append({"node_id": n.node_id, "addr": n.addr})
            else:
                try:
                    self.nsched.release(nid, demand)
                except Exception:
                    pass
                stop = True
        return picks

    def h_pick_nodes(self, conn, p):
        """Batched pick_node: reserve up to `count` placements of one
        demand in a single RPC (the owner's vectorized lease ramp-up).
        Returns a possibly-short (or empty) list of {node_id, addr};
        names may repeat when one node fits several leases."""
        demand = normalize_resources(p.get("resources"))
        count = max(1, int(p.get("count", 1)))
        strategy = p.get("strategy")
        picks: List[Dict[str, str]] = []
        with self.lock:
            if strategy is None and self.nsched is not None:
                picks.extend(self._native_pick_n_locked(demand, count))
            while len(picks) < count:
                n = self._pick_node_locked(demand, strategy)
                if n is None:
                    break
                subtract(n.available, demand)
                n.needs_resync = True
                if self.nsched is not None:
                    self.nsched.set_available(n.node_id, n.available)
                picks.append({"node_id": n.node_id, "addr": n.addr})
        return picks

    def h_cluster_resources(self, conn, p):
        with self.lock:
            total: Dict[str, int] = {}
            avail: Dict[str, int] = {}
            for n in self._alive_nodes():
                add(total, n.total)
                add(avail, n.available)
            return {
                "total": common.denormalize_resources(total),
                "available": common.denormalize_resources(avail),
            }

    # -- functions / jobs --------------------------------------------------

    def h_register_function(self, conn, p):
        with self.lock:
            self.functions[p["function_id"]] = p["blob"]
        if self.pstore is not None:
            self.pstore.rec_put("function", p["function_id"], p["blob"])
        return True

    def h_get_function(self, conn, p):
        with self.lock:
            return self.functions.get(p["function_id"])

    def h_register_job(self, conn, p):
        with self.lock:
            self.jobs[p["job_id"]] = {"start_time": time.time(), **p}
            # the owning driver came back after a control restart: its
            # restored actors are claimed and escape the orphan reaper
            for aid in [a for a, _ in self._restored_unclaimed.items()
                        if self.actors.get(a) is not None
                        and self.actors[a].job_id == p["job_id"]]:
                self._restored_unclaimed.pop(aid, None)
        conn.meta["job_id"] = p["job_id"]
        if self.pstore is not None:
            self.pstore.rec_put("job", p["job_id"], self.jobs[p["job_id"]])
        self.record_event(severity="INFO", source="job",
                          event_type="started",
                          message=f"job {p['job_id'][:20]} registered",
                          entity_id=p["job_id"])
        return True

    # -- pubsub ------------------------------------------------------------

    def h_subscribe(self, conn, p):
        with self.lock:
            for t in p["topics"]:
                self.subs.setdefault(t, set()).add(conn)
        return True

    def h_publish(self, conn, p):
        self.publish(p["topic"], p["payload"])
        return True

    def publish(self, topic: str, payload: Any):
        try:
            # event recording must never break pubsub delivery: user
            # payloads on these topics may have any shape
            self._maybe_record_event(topic, payload)
        except Exception:
            logger.debug("event recording failed for topic %s", topic,
                         exc_info=True)
        with self.lock:
            conns = list(self.subs.get(topic, ()))
        # one pickle for the whole fan-out (500 subscribers = 1 dumps, not
        # 500); the meta wall-clock stamp lets every subscriber measure
        # publish->deliver latency (rpc_stats.record_pubsub_delivery)
        t0 = time.perf_counter()
        data = protocol._pack_frame(0, protocol.PUSH, f"pub:{topic}",
                                    payload, {"ts": time.time()})
        dead = [c for c in conns if not c.send_raw(data)]
        fanout_s = time.perf_counter() - t0
        with self._obs_lock:
            st = self._pubsub_stats.get(topic)
            if st is None:
                st = self._pubsub_stats[topic] = [0, 0, 0, 0, 0.0, 0.0]
            st[0] += 1
            st[1] += len(conns) - len(dead)
            st[2] += len(dead)
            st[3] += len(data) * (len(conns) - len(dead))
            st[4] += fanout_s
            if fanout_s > st[5]:
                st[5] = fanout_s
        if dead:
            with self.lock:
                for c in dead:
                    for s in self.subs.values():
                        s.discard(c)

    # -- structured cluster events -----------------------------------------
    # reference: src/ray/util/event.h + dashboard/modules/event — durable,
    # queryable records of lifecycle transitions (node died, actor failed,
    # job finished), distinct from free-text logs.  publish() is the
    # chokepoint every such transition already flows through.

    _EVENT_SEVERITY = {  # (topic, event) -> severity; default INFO
        ("node", "removed"): "WARNING",
        ("actor", "dead"): "WARNING",
        ("actor", "restarting"): "WARNING",
        ("pg", "removed"): "INFO",
        ("error", None): "ERROR",
    }

    def _maybe_record_event(self, topic: str, payload: Any):
        if topic not in ("node", "actor", "pg", "job", "error"):
            return
        p = payload if isinstance(payload, dict) else {"data": payload}
        ev = p.get("event", topic)
        entity = (p.get("node", {}).get("node_id", "")
                  if "node" in p else
                  p.get("actor", {}).get("actor_id", "")
                  if "actor" in p else
                  p.get("pg", {}).get("pg_id", p.get("pg_id", ""))
                  if topic == "pg" else
                  p.get("job_id", p.get("submission_id", "")))
        sev = self._EVENT_SEVERITY.get((topic, ev)) \
            or self._EVENT_SEVERITY.get((topic, None)) or "INFO"
        # actor death with an error message is an ERROR, not a shutdown
        if topic == "actor" and ev == "dead" \
                and p.get("actor", {}).get("error"):
            sev = "ERROR"
        msg = f"{topic} {entity[:20]} {ev}"
        err = (p.get("actor", {}) or {}).get("error") or p.get("error")
        if err:
            msg += f": {str(err)[:300]}"
        self.record_event(severity=sev, source=topic, event_type=ev,
                          message=msg, entity_id=entity)

    def _note_dead_actor(self, rec: ActorRecord):
        with self.lock:
            self._dead_actor_order.append(rec.actor_id)
            while len(self._dead_actor_order) > self._max_dead_actors:
                aid = self._dead_actor_order.popleft()
                old = self.actors.get(aid)
                if old is not None and old.state == DEAD:
                    del self.actors[aid]
                    if old.name:
                        key = _named_key(old.namespace, old.name)
                        if self.named_actors.get(key) == aid:
                            del self.named_actors[key]

    def record_event(self, *, severity: str, source: str, event_type: str,
                     message: str, entity_id: str = "",
                     custom: Optional[Dict[str, Any]] = None):
        """Append one structured event (bounded buffer, monotonic seq)."""
        with self.lock:
            self._event_seq += 1
            self.events.append({
                "seq": self._event_seq,
                "ts": time.time(),
                "severity": severity,
                "source": source,
                "event_type": event_type,
                "entity_id": entity_id,
                "message": message,
                **({"custom": custom} if custom else {}),
            })

    def h_report_event(self, conn, p):
        """External emitters (raylets, libraries) push structured events
        (reference: the event agent's ReportEvents RPC)."""
        self.record_event(
            severity=str(p.get("severity", "INFO")).upper(),
            source=str(p.get("source", "user")),
            event_type=str(p.get("event_type", "custom")),
            message=str(p.get("message", ""))[:2000],
            entity_id=str(p.get("entity_id", "")),
            custom=p.get("custom"))
        return True

    def h_list_events(self, conn, p):
        """Filterable, seq-ordered slice of the event buffer.

        With a cursor (after_seq > 0) the OLDEST `limit` matches after
        the cursor return, so pollers that fall behind page forward
        without silently skipping the middle; cursorless calls (the
        dashboard) get the newest `limit`."""
        sev = p.get("severity")
        sev = sev.upper() if sev else None   # stored normalized upper
        src = p.get("source")
        ent = p.get("entity_id")
        after = int(p.get("after_seq") or 0)
        limit = max(0, int(p.get("limit", 1000)))
        if limit == 0:
            return []
        with self.lock:
            out = [e for e in self.events
                   if e["seq"] > after
                   and (sev is None or e["severity"] == sev)
                   and (src is None or e["source"] == src)
                   and (ent is None or e["entity_id"] == ent)]
        return out[:limit] if after else out[-limit:]

    # -- raylet client cache ----------------------------------------------

    def _node_client(self, nid: str) -> Optional[Client]:
        with self.lock:
            rec = self.nodes.get(nid)
            if rec is None or rec.state != ALIVE:
                return None
            cli = self.node_clients.get(nid)
            if cli is not None and not cli.closed:
                return cli
            addr = rec.addr
        try:
            cli = Client(addr, name=f"control->raylet-{nid[:8]}")
        except Exception:
            return None
        with self.lock:
            self.node_clients[nid] = cli
        return cli

    # -- actors ------------------------------------------------------------

    def h_create_actor(self, conn, p, d: Deferred):
        rec = ActorRecord(
            p["actor_id"], p["spec_blob"], p.get("name"),
            normalize_resources(p.get("resources")), p.get("max_restarts", 0),
            p.get("owner_id", ""), p.get("pg_id"), p.get("bundle_index", -1),
            p.get("detached", False),
            namespace=p.get("namespace") or "default",
            job_id=p.get("job_id", ""),
        )
        rec.class_name = p.get("class_name", "")
        rec.strategy = p.get("strategy")
        rec.container = p.get("container")
        with self.lock:
            # idempotent on actor_id: clients retry blindly after a
            # control-plane reconnect, and the first attempt may have
            # registered (and persisted) the record before the reply
            # was lost
            existing = self.actors.get(rec.actor_id)
            if existing is not None:
                d.resolve(existing.view())
                return
            if rec.name:
                key = _named_key(rec.namespace, rec.name)
                if self.named_actors.get(key, rec.actor_id) \
                        != rec.actor_id:
                    d.reject(f"actor name {rec.name!r} already taken "
                             f"in namespace {rec.namespace!r}")
                    return
                self.named_actors[key] = rec.actor_id
            self.actors[rec.actor_id] = rec
        # creation is async (reference: RegisterActor replies before the
        # actor is scheduled; the caller learns placement via
        # wait_actor_alive / pubsub) — an unschedulable actor stays
        # PENDING as autoscaler demand instead of failing fast
        self._persist_actor(rec)
        d.resolve(rec.view())
        self._schedule_actor(rec, None)

    def _schedule_actor(self, rec: ActorRecord, d=None):
        """Queue for the scheduler loop (reference:
        GcsActorScheduler::Schedule, gcs_actor_scheduler.h:146)."""
        with self.lock:
            if rec not in self.pending_actors:
                self.pending_actors.append(rec)
        self._sched_event.set()

    def _actor_sched_loop(self):
        """Single placement loop over pending actors: retries forever as
        resources free up (the reference keeps unschedulable actors
        pending and reports them as resource demand)."""
        while not self._stop.is_set():
            self._sched_event.wait(0.2)
            self._sched_event.clear()
            with self.lock:
                pending = list(self.pending_actors)
            for rec in pending:
                placed_or_dropped = self._try_place_actor(rec)
                if placed_or_dropped:
                    with self.lock:
                        if rec in self.pending_actors:
                            self.pending_actors.remove(rec)

    def _try_place_actor(self, rec: ActorRecord) -> bool:
        """One placement attempt; True if the actor left the queue
        (started on a node, or died)."""
        strategy = rec.strategy
        if rec.pg_id:
            strategy = {"kind": "placement_group", "pg_id": rec.pg_id,
                        "bundle_index": rec.bundle_index}
        with self.lock:
            if rec.state == DEAD:
                return True
            if rec.state == ALIVE:
                # an orphaned worker's actor_ready adopted the placement
                # while this record sat in the queue — nothing to place
                return True
            node = self._pick_node_locked(rec.resources, strategy)
            if node is None:
                now = time.monotonic()
                if now - rec.last_pending_warn > 30.0:
                    rec.last_pending_warn = now
                    logger.warning(
                        "actor %s (%s) pending: no node with free %s",
                        rec.actor_id[:12], rec.class_name,
                        common.denormalize_resources(rec.resources))
                return False
        cli = self._node_client(node.node_id)
        if cli is None:
            return False
        try:
            r = cli.call("start_actor_worker", {
                "actor_id": rec.actor_id,
                "resources": common.denormalize_resources(rec.resources),
                "pg_id": rec.pg_id,
                "bundle_index": rec.bundle_index,
                "incarnation": rec.incarnation,
                "container": rec.container,
            }, timeout=60.0)
            if r and r.get("ok"):
                with self.lock:
                    killed = rec.state == DEAD
                    adopted_elsewhere = (
                        rec.state == ALIVE
                        and (rec.worker_addr or ()) != tuple(r["worker_addr"]))
                    if not killed and not adopted_elsewhere:
                        rec.node_id = node.node_id
                        rec.worker_addr = tuple(r["worker_addr"])
                        # stays PENDING until worker reports ready
                if killed or adopted_elsewhere:
                    # kill_actor raced with placement, or an orphaned
                    # worker already adopted this actor: reap the spare we
                    # just started (addressed by worker_addr so a same-node
                    # adopted worker is never the one killed)
                    logger.info(
                        "reaping spare worker of actor %s (%s)",
                        rec.actor_id[:12],
                        "killed during placement" if killed
                        else "adopted elsewhere")
                    self._kill_actor_worker(
                        node.node_id, rec.actor_id,
                        worker_addr=tuple(r["worker_addr"]))
                return True
            if r and r.get("permanent"):
                # the raylet says retrying can't help (e.g. container
                # runtime missing) — fail the actor loudly now instead
                # of re-queueing it forever
                self._on_actor_failure(
                    rec.actor_id, r.get("error", "worker spawn failed"))
                return True
        except Exception as e:
            logger.warning("actor %s placement on %s failed: %s",
                           rec.actor_id[:12], node.node_id[:12], e)
        return False

    def _kill_actor_worker(self, node_id: str, actor_id: str,
                           worker_addr=None):
        cli = self._node_client(node_id)
        if cli is not None:
            try:
                cli.call("kill_actor_worker",
                         {"actor_id": actor_id, "worker_addr": worker_addr},
                         timeout=10.0)
            except Exception:
                pass

    def h_actor_ready(self, conn, p):
        """Worker finished running the creation task.

        Placement is reconciled here, not assumed from the RPC reply: if
        the start_actor_worker call failed mid-flight but the raylet did
        start the worker, the orphan's report *adopts* the placement; a
        stale incarnation or a duplicate placement gets its worker reaped
        (reference: GcsActorManager reconciles via the actor table for the
        same reason — replies can be lost while the work happened)."""
        aid = p["actor_id"]
        rep_node = p.get("node_id")
        rep_inc = p.get("incarnation", 0)
        kill_on = None  # node to reap a stale/duplicate/killed worker from
        with self.lock:
            rec = self.actors.get(aid)
            if rec is None:
                return False
            if rec.state == DEAD:
                # killed while the creation task ran — never resurrect;
                # make sure the node reaps the worker and frees resources
                kill_on = rep_node or rec.node_id
                view = None
            elif rep_inc < rec.incarnation:
                # report from a previous incarnation's worker: stale
                kill_on = rep_node
                view = None
            elif (rec.state == ALIVE
                  and tuple(p.get("worker_addr") or ()) != (rec.worker_addr or ())):
                # double placement (lost-reply retry): keep the first
                # worker, reap the spare
                kill_on = rep_node
                view = None
            elif p.get("error"):
                rec.state = DEAD
                rec.error = p["error"]
                view = rec.view()
            else:
                rec.state = ALIVE
                rec.worker_addr = tuple(p["worker_addr"])
                rec.incarnation = rep_inc
                if rep_node:
                    rec.node_id = rep_node
                # adopted placements leave the pending queue
                if rec in self.pending_actors:
                    self.pending_actors.remove(rec)
                view = rec.view()
        if view is None:
            if kill_on:
                self._kill_actor_worker(kill_on, aid,
                                        worker_addr=p.get("worker_addr"))
            return True
        self._persist_actor(rec)
        self.publish("actor", {"event": "alive" if not p.get("error") else "dead",
                               "actor": view})
        return True

    def h_actor_failed(self, conn, p):
        """Worker/raylet reports actor process death -> maybe restart
        (reference: GcsActorManager::RestartActor gcs_actor_manager.cc:1361)."""
        self._on_actor_failure(p["actor_id"], p.get("error", "actor process died"))
        return True

    def _on_actor_failure(self, aid: str, error: str):
        with self.lock:
            rec = self.actors.get(aid)
            if rec is None or rec.state == DEAD:
                return
            if rec.max_restarts != 0 and (
                rec.max_restarts < 0 or rec.restarts < rec.max_restarts
            ):
                rec.restarts += 1
                rec.incarnation += 1
                rec.state = RESTARTING
                rec.worker_addr = None
                view = rec.view()
                restart = True
            else:
                rec.state = DEAD
                rec.error = error
                view = rec.view()
                restart = False
        self._persist_actor(self.actors[aid])
        self.publish("actor", {"event": "restarting" if restart else "dead", "actor": view})
        if restart:
            self.pool.submit(self._schedule_actor, self.actors[aid], None)

    def h_get_actor(self, conn, p):
        with self.lock:
            aid = p.get("actor_id")
            if aid is None and p.get("name"):
                aid = self.named_actors.get(
                    _named_key(p.get("namespace") or "default", p["name"]))
            rec = self.actors.get(aid) if aid else None
            return None if rec is None else rec.view()

    def h_wait_actor_alive(self, conn, p, d: Deferred):
        aid, timeout = p["actor_id"], p.get("timeout", 60.0)
        # callers that saw an incarnation die pass min_incarnation so a
        # stale ALIVE view (death notification still in flight) is not
        # returned as if it were the restarted actor
        min_inc = p.get("min_incarnation", 0)

        def waiter():
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline and not self._stop.is_set():
                with self.lock:
                    rec = self.actors.get(aid)
                    if rec is None:
                        d.resolve(None)
                        return
                    if rec.state == DEAD or (
                            rec.state == ALIVE and rec.incarnation >= min_inc):
                        d.resolve(rec.view())
                        return
                time.sleep(0.05)
            with self.lock:
                rec = self.actors.get(aid)
                d.resolve(rec.view() if rec else None)

        self.pool.submit(waiter)

    def h_list_actors(self, conn, p):
        with self.lock:
            return [a.view() for a in self.actors.values()]

    def h_kill_actor(self, conn, p, d: Deferred):
        aid, no_restart = p["actor_id"], p.get("no_restart", True)

        def do():
            with self.lock:
                rec = self.actors.get(aid)
                nid = rec.node_id if rec is not None else None
            if rec is None:
                d.resolve(False)
                return
            if no_restart:
                self._destroy_actor(aid, "killed via kill_actor")
            elif nid:
                # restartable kill: just reap the worker; the failure
                # path reschedules per max_restarts
                self._kill_actor_worker(nid, aid)
            d.resolve(True)

        self.pool.submit(do)

    # -- placement groups --------------------------------------------------

    def h_create_pg(self, conn, p, d: Deferred):
        bundles = [normalize_resources(b) for b in p["bundles"]]
        rec = PlacementGroupRecord(p["pg_id"], bundles, p.get("strategy", "PACK"),
                                   p.get("name", ""))
        with self.lock:
            existing = self.pgs.get(rec.pg_id)
            if existing is not None:
                # blind client retry after reconnect: never double-reserve
                d.resolve(existing.view())
                return
            self.pgs[rec.pg_id] = rec
        self._persist_pg(rec)
        self.pool.submit(self._schedule_pg, rec, d)

    def _schedule_pg(self, rec: PlacementGroupRecord, d: Deferred,
                     deadline_s: float = 60.0,
                     fail_on_timeout: bool = True):
        """2-phase bundle reservation: PREPARE on every chosen node, then
        COMMIT; release everything on any failure (reference:
        placement_group_resource_manager.h:54-61)."""
        deadline = time.monotonic() + deadline_s
        while not self._stop.is_set():
            plan_result = self._plan_pg(rec)
            if plan_result is not None:
                prepared: List[Tuple[str, int]] = []
                ok = True
                for idx, nid in plan_result.items():
                    cli = self._node_client(nid)
                    try:
                        r = cli.call("prepare_bundle", {
                            "pg_id": rec.pg_id, "bundle_index": idx,
                            "resources": common.denormalize_resources(rec.bundles[idx]),
                        }, timeout=15.0) if cli else None
                        if not (r and r.get("ok")):
                            ok = False
                            break
                        prepared.append((nid, idx))
                    except Exception:
                        ok = False
                        break
                if ok:
                    for nid, idx in prepared:
                        cli = self._node_client(nid)
                        if cli:
                            try:
                                cli.call("commit_bundle",
                                         {"pg_id": rec.pg_id, "bundle_index": idx},
                                         timeout=15.0)
                            except Exception:
                                pass
                    with self.lock:
                        rec.assignments = dict(plan_result)
                        rec.state = ALIVE
                    self._persist_pg(rec)
                    self.publish("pg", {"event": "alive", "pg": rec.view()})
                    d.resolve(rec.view())
                    return
                for nid, idx in prepared:
                    cli = self._node_client(nid)
                    if cli:
                        try:
                            cli.call("release_bundle",
                                     {"pg_id": rec.pg_id, "bundle_index": idx},
                                     timeout=15.0)
                        except Exception:
                            pass
            if time.monotonic() > deadline:
                if not fail_on_timeout:
                    # boot-restored PG: stay PENDING — nodes may still be
                    # rejoining after the control restart, and killing a
                    # previously-healthy group would strand its actors
                    d.resolve(rec.view())
                    return
                with self.lock:
                    rec.state = DEAD
                self._persist_pg(rec)
                d.resolve(rec.view())
                return
            time.sleep(0.2)

    def _plan_pg(self, rec: PlacementGroupRecord) -> Optional[Dict[int, str]]:
        with self.lock:
            nodes = self._alive_nodes()
            # native bundle planner (reference: bundle_scheduling_policy.h)
            # handles the pure-resource case; the Python path below keeps
            # TPU-slice-affinity ordering which the native engine lacks
            if (self.nsched is not None
                    and not any(n.labels.get("tpu_slice") for n in nodes)):
                plan = self._native_plan_pg(rec)
                if plan is not None:
                    return plan
            # simulate availability
            sim = {n.node_id: dict(n.available) for n in nodes}
            # TPU slice affinity: prefer nodes sharing a tpu_slice label
            order = sorted(nodes, key=lambda n: n.labels.get("tpu_slice", ""))
            out: Dict[int, str] = {}
            if rec.strategy == "STRICT_PACK":
                for n in order:
                    s = dict(sim[n.node_id])
                    if all(fits(s, b) and (subtract(s, b) or True)
                           for b in rec.bundles):
                        return {i: n.node_id for i in range(len(rec.bundles))}
                return None
            if rec.strategy == "STRICT_SPREAD":
                used: Set[str] = set()
                for i, b in enumerate(rec.bundles):
                    got = next((n.node_id for n in order
                                if n.node_id not in used
                                and fits(sim[n.node_id], b)), None)
                    if got is None:
                        return None
                    subtract(sim[got], b)
                    used.add(got)
                    out[i] = got
                return out
            # PACK / SPREAD: soft preferences
            prefer_spread = rec.strategy == "SPREAD"
            last = None
            for i, b in enumerate(rec.bundles):
                cands = [n for n in order if fits(sim[n.node_id], b)]
                if not cands:
                    return None
                if prefer_spread:
                    fresh = [n for n in cands if n.node_id != last]
                    n = (fresh or cands)[0]
                else:
                    n = cands[0] if last is None else next(
                        (c for c in cands if c.node_id == last), cands[0])
                subtract(sim[n.node_id], b)
                out[i] = n.node_id
                last = n.node_id
            return out

    def _native_plan_pg(self, rec) -> Optional[Dict[int, str]]:
        """Plan via the C++ engine; None falls back to the Python planner
        (including the infeasible case, which Python re-confirms)."""
        try:
            from ray_tpu.native.sched import (PACK, SPREAD, STRICT_PACK,
                                              STRICT_SPREAD)
            strat = {"PACK": PACK, "SPREAD": SPREAD,
                     "STRICT_PACK": STRICT_PACK,
                     "STRICT_SPREAD": STRICT_SPREAD}.get(rec.strategy)
            if strat is None:
                return None
            names = self.nsched.plan_bundles(rec.bundles, strat)
        except Exception:
            return None
        if names is None:
            return None
        # validate against the authoritative books before trusting
        sim = {n.node_id: dict(n.available) for n in self._alive_nodes()}
        for b, nid in zip(rec.bundles, names):
            if nid not in sim or not fits(sim[nid], b):
                return None
            subtract(sim[nid], b)
        return {i: nid for i, nid in enumerate(names)}

    def h_remove_pg(self, conn, p, d: Deferred):
        pgid = p["pg_id"]

        def do():
            with self.lock:
                rec = self.pgs.get(pgid)
                if rec is None:
                    d.resolve(False)
                    return
                rec.state = DEAD
                assignments = dict(rec.assignments)
            self._persist_pg(rec)
            for idx, nid in assignments.items():
                cli = self._node_client(nid)
                if cli:
                    try:
                        cli.call("release_bundle", {"pg_id": pgid, "bundle_index": idx},
                                 timeout=15.0)
                    except Exception:
                        pass
            self.publish("pg", {"event": "removed", "pg_id": pgid})
            d.resolve(True)

        self.pool.submit(do)

    def h_get_pg(self, conn, p):
        with self.lock:
            rec = self.pgs.get(p["pg_id"]) or (
                self.pgs.get(self._pg_by_name(p["name"])) if p.get("name") else None)
            return None if rec is None else rec.view()

    def _pg_by_name(self, name):
        for pg in self.pgs.values():
            if pg.name == name:
                return pg.pg_id
        return None

    # -- health / failure detection ---------------------------------------

    def _health_loop(self):
        while not self._stop.is_set():
            time.sleep(HEARTBEAT_INTERVAL_S)
            now = time.monotonic()
            dead_nodes: List[NodeRecord] = []
            drain_expired: List[NodeRecord] = []
            quarantine_expired: List[NodeRecord] = []
            with self.lock:
                for rec in self.nodes.values():
                    if rec.state == ALIVE and now - rec.last_heartbeat > NODE_DEATH_TIMEOUT_S:
                        rec.state = DEAD
                        dead_nodes.append(rec)
                    elif (rec.state == ALIVE and rec.draining_until is not None
                            and now > rec.draining_until + NODE_DEATH_TIMEOUT_S):
                        # the predicted preemption never happened: the node
                        # outlived its deadline by a full death interval —
                        # clear the advisory so it takes work again
                        rec.draining_until = None
                        rec.draining_reason = ""
                        drain_expired.append(rec)
                    if (rec.state == ALIVE
                            and rec.quarantined_until is not None
                            and now > rec.quarantined_until):
                        # quarantine served: the bench duration IS the
                        # penalty — the node rejoins the schedulable pool
                        rec.quarantined_until = None
                        rec.quarantine_reason = ""
                        quarantine_expired.append(rec)
            for rec in quarantine_expired:
                logger.info("node %s quarantine expired; schedulable again",
                            rec.node_id[:12])
                self.publish("node", {"event": "quarantine_cleared",
                                      "node": rec.view(), "grace_s": None,
                                      "reason": "expired"})
            for rec in drain_expired:
                logger.info("node %s drain notice expired without death; "
                            "cleared", rec.node_id[:12])
                self.publish("node", {"event": "drain_canceled",
                                      "node": rec.view(), "grace_s": None,
                                      "reason": "expired"})
            for rec in dead_nodes:
                logger.warning("node %s declared dead (heartbeat timeout)", rec.node_id[:12])
                self.publish("node", {"event": "removed", "node": rec.view()})
                self._on_node_death(rec.node_id)
            self._reap_unclaimed_restored(now)
            self._reschedule_unadopted(now)
            self._check_fenced()

    def _check_fenced(self):
        """Split-brain fencing: the addr-file is the single source of
        truth for who the controller is.  If a standby promoted while
        this (slow-but-alive) process was stalled, the file no longer
        names our address — step down immediately rather than serve a
        second, diverging control plane against the same persisted
        store."""
        if not self._addr_file:
            return
        cur = common.read_addr_file(self._addr_file)
        if cur is not None and tuple(cur) != tuple(self.server.addr):
            logger.critical(
                "fenced: addr-file %s now names %s (a standby promoted "
                "over us); stepping down", self._addr_file, cur)
            # immediate exit, no graceful stop: a fenced primary must
            # not serve one more request, and a graceful stop races the
            # blocking serve loop in main() returning 0 first (the WAL
            # is crash-safe; the successor already owns the store)
            os._exit(3)

    def _reschedule_unadopted(self, now: float):
        """Adoption window expired with no raylet claiming the live
        worker: fall back to a fresh reschedule (the round-4 restart
        semantics)."""
        fell_through = []
        with self.lock:
            expired = [aid for aid, dl in self._adoptable.items()
                       if now > dl]
            for aid in expired:
                self._adoptable.pop(aid, None)
                rec = self.actors.get(aid)
                if rec is not None and rec.state == RESTARTING \
                        and rec not in self.pending_actors:
                    self.pending_actors.append(rec)
                    fell_through.append(aid)
        if fell_through:
            logger.warning("adoption window expired for %d restored "
                           "actor(s); rescheduling fresh", len(fell_through))
            self._sched_event.set()

    def _reap_unclaimed_restored(self, now: float):
        """Destroy restored non-detached actors whose owning driver job
        never re-registered after a control restart (the reference only
        recreates detached actors — owned actors die with their owner;
        gcs_actor_manager.cc ownership rules)."""
        with self.lock:
            expired = [aid for aid, dl in self._restored_unclaimed.items()
                       if now > dl]
            for aid in expired:
                self._restored_unclaimed.pop(aid, None)
        for aid in expired:
            logger.warning(
                "reaping restored actor %s: owner job never re-registered",
                aid[:12])
            self._destroy_actor(
                aid, "owner driver did not return after control restart")

    def _destroy_actor(self, aid: str, error: str):
        """Force-kill an actor: mark DEAD, drop its name, reap its
        worker, publish (shared by kill_actor and the orphan reaper)."""
        with self.lock:
            rec = self.actors.get(aid)
            if rec is None or rec.state == DEAD:
                return
            rec.max_restarts = 0
            rec.state = DEAD
            rec.error = error
            if rec.name:
                self.named_actors.pop(
                    _named_key(rec.namespace, rec.name), None)
            if rec in self.pending_actors:
                self.pending_actors.remove(rec)
            self._adoptable.pop(aid, None)
            nid = rec.node_id
            view = rec.view()
        self._persist_actor(rec)
        if nid:
            self._kill_actor_worker(nid, aid)
        self.publish("actor", {"event": "dead", "actor": view})

    def _on_node_death(self, nid: str):
        with self.lock:
            if self.nsched is not None:
                self.nsched.set_alive(nid, False)
            cli = self.node_clients.pop(nid, None)
            affected = [a for a in self.actors.values()
                        if a.node_id == nid and a.state in (ALIVE, PENDING, RESTARTING)]
        if cli:
            cli.close()
        for rec in affected:
            self._on_actor_failure(rec.actor_id, f"node {nid} died")

    def h_disconnect(self, conn: ServerConn):
        with self.lock:
            for s in self.subs.values():
                s.discard(conn)
        nid = conn.meta.get("node_id")
        if not nid:
            return
        with self.lock:
            rec = self.nodes.get(nid)
            # Partition tolerance: a dropped TCP connection is NOT node
            # death.  The record stays ALIVE and its actors/bundles are
            # untouched; only the heartbeat timeout (_health_loop,
            # NODE_DEATH_TIMEOUT_S) or an explicit unregister_node
            # declares death.  Drops of superseded connections (the
            # raylet already re-registered over a fresh one) are ignored
            # so a slow FIN can't mark a healthy node disconnected.
            if rec is None or rec.state != ALIVE:
                return
            if conn.meta.get("reg_epoch") != rec.reg_epoch:
                return
            rec.disconnected_at = time.monotonic()
            view = rec.view()
        logger.warning(
            "node %s connection dropped; keeping it ALIVE pending "
            "heartbeat timeout (%.0fs)", nid[:12], NODE_DEATH_TIMEOUT_S)
        self.publish("node", {"event": "disconnected", "node": view})

    def h_unregister_node(self, conn, p):
        """Graceful node departure (raylet shutdown / scale-down): death
        is declared immediately.  The heartbeat-timeout grace exists for
        *transient* faults — a deliberate exit must not strand its actors
        for NODE_DEATH_TIMEOUT_S."""
        nid = p["node_id"]
        with self.lock:
            rec = self.nodes.get(nid)
            if rec is None or rec.state == DEAD:
                return {"ok": True}
            rec.state = DEAD
            view = rec.view()
        logger.info("node %s unregistered (graceful shutdown)", nid[:12])
        self.publish("node", {"event": "removed", "node": view})
        self._on_node_death(nid)
        return {"ok": True}

    # -- control-plane flight recorder ------------------------------------

    def h_control_stats(self, conn, p):
        """One-stop control-plane health view: per-handler RPC stats,
        event-loop lag, per-KV-namespace traffic, per-topic pubsub
        fan-out and task-event ingest accounting.  Served by `ray-tpu
        control-stats`, `GET /api/control/stats` and the dashboard's
        ray_tpu_control_* Prometheus series."""
        with self.lock:
            nodes_total = len(self.nodes)
            nodes_alive = sum(1 for n in self.nodes.values()
                              if n.state == "ALIVE")
            subs = {t: len(cs) for t, cs in self.subs.items() if cs}
        with self._obs_lock:
            pubsub = {
                t: {"publishes": st[0], "deliveries": st[1],
                    "dropped_subscribers": st[2], "bytes_out": st[3],
                    "fanout_ms_total": round(st[4] * 1e3, 3),
                    "fanout_ms_max": round(st[5] * 1e3, 3)}
                for t, st in self._pubsub_stats.items()}
            relay_batches = self._relay_batches
            relay_dropped = self._relay_dropped
        with self._events_lock:
            events = {
                "queue_depth": len(self._event_queue),
                "dropped": self.task_events_dropped,
                "task_records": len(self.task_records),
                "profile_events": len(self.profile_events),
                "relay_batches": relay_batches,
                "relay_dropped": relay_dropped,
            }
        with self._traces_lock:
            tracing = {
                "queue_depth": len(self._span_queue),
                "traces": len(self.trace_spans),
                "spans": self._spans_received,
                "span_batches": self._span_batches,
                "dropped": self._spans_dropped,
                "span_overflow": self._trace_span_overflow,
                "traces_evicted": self._traces_evicted,
            }
        return {
            "uptime_s": round(time.time() - self.start_time, 1),
            "handlers": self.server.stats(),
            "loop": self.server.loop_stats(),
            "kv": {ns: {"ops": st[0], "bytes_in": st[1],
                        "bytes_out": st[2]}
                   for ns, st in self._kv_stats.items()},
            "pubsub": pubsub,
            "subscriptions": subs,
            "events": events,
            "tracing": tracing,
            "nodes": {"alive": nodes_alive, "total": nodes_total},
        }

    # -- state dump (state API source of truth) ---------------------------

    def h_state_dump(self, conn, p):
        with self.lock:
            return {
                "nodes": [n.view() for n in self.nodes.values()],
                "actors": [a.view() for a in self.actors.values()],
                "pgs": [g.view() for g in self.pgs.values()],
                "jobs": dict(self.jobs),
                "start_time": self.start_time,
            }

    # -- task events (reference: GcsTaskManager) --------------------------

    def _defer(self, d: Deferred, fn):
        def run():
            try:
                d.resolve(fn())
            except Exception as e:
                logger.exception("deferred control handler failed")
                try:
                    d.reject(f"{type(e).__name__}: {e}")
                except Exception:
                    pass

        self.pool.submit(run)

    def h_report_task_events(self, conn, p):
        """Ingest is decoupled from the RPC loop: batches land in a
        queue and a dedicated thread merges them.  At high task rates
        the merge is the control plane's biggest CPU item — doing it on
        the event loop under the global lock stalled lease scheduling
        (measured ~40% of headline tasks/s).  The queue is bounded: if
        the merge thread falls behind the oldest batch is dropped with
        accounting (the reference's TaskEventBuffer does the same).

        Accepts either one worker batch ({"events", "dropped", "common"})
        or a raylet relay envelope ({"batches": [...], "dropped": n}) —
        one framed pipe write carrying every worker batch a node
        coalesced in its flush window."""
        q = self._event_queue
        batches = p.get("batches")
        if batches is not None:
            with self._obs_lock:
                self._relay_batches += 1
                self._relay_dropped += p.get("dropped", 0)
            if p.get("dropped"):
                with self._events_lock:
                    self.task_events_dropped += p["dropped"]
            q.extend(batches)
        else:
            q.append(p)
        while len(q) > self._event_queue_cap:
            try:
                old = q.popleft()
                with self._events_lock:
                    self.task_events_dropped += \
                        len(old.get("events", ())) + old.get("dropped", 0)
            except IndexError:
                break
        self._event_signal.set()
        return True

    def _event_merge_loop(self):
        while not self._stop.is_set():
            self._event_signal.wait(0.5)
            self._event_signal.clear()
            self._drain_event_queue()
        self._drain_event_queue()  # final drain: don't lose pre-stop batches

    def _drain_event_queue(self):
        # single drainer: the merge thread and deferred readers race here;
        # batches must merge in report order and a reader that got True
        # from report_task_events must then see those events
        with self._drain_lock:
            while self._event_queue:
                try:
                    self._merge_task_events(self._event_queue.popleft())
                except IndexError:
                    break
                except Exception:
                    logger.exception("task-event merge failed")

    def _merge_task_events(self, p):
        with self._events_lock:
            self.task_events_dropped += p.get("dropped", 0)
            common_fields = p.get("common") or {}
            for ev in p.get("events", []):
                if common_fields:
                    ev = {**common_fields, **ev}
                if ev.get("kind") == "profile":
                    self.profile_events.append(ev)
                    if len(self.profile_events) > self.max_task_records:
                        self.profile_events.pop(0)
                    continue
                tid = ev["task_id"]
                rec = self.task_records.get(tid)
                if rec is None:
                    rec = {"task_id": tid, "state_ts": {}}
                    self.task_records[tid] = rec
                    while len(self.task_records) > self.max_task_records:
                        self.task_records.popitem(last=False)
                        self.task_events_dropped += 1
                for k in ("name", "job_id", "actor_id", "node_id",
                          "worker_id", "error", "type"):
                    if ev.get(k):
                        rec[k] = ev[k]
                state = ev.get("state")
                if state:
                    # merge out-of-order batches: a terminal state must not
                    # be overwritten by a late RUNNING report
                    terminal = rec.get("state") in ("FINISHED", "FAILED")
                    if not terminal or state in ("FINISHED", "FAILED"):
                        rec["state"] = state
                    rec["state_ts"][state] = ev["ts"]

    def h_list_task_events(self, conn, p, d):
        # deferred: the drain + snapshot is O(backlog + records) and must
        # not run on the RPC event loop (protocol handlers must not block)
        def run():
            filters = p.get("filters") or {}
            limit = p.get("limit", 1000)
            out = []
            self._drain_event_queue()  # readers see everything reported
            with self._events_lock:
                for rec in reversed(self.task_records.values()):
                    if all(rec.get(k) == v for k, v in filters.items()):
                        out.append(dict(rec, state_ts=dict(rec["state_ts"])))
                        if len(out) >= limit:
                            break
                return {"records": out, "dropped": self.task_events_dropped,
                        "total": len(self.task_records),
                        # server clock anchor: event ts are cluster-host
                        # time; viewers (dashboard timeline) must render
                        # relative to THIS, not their own skewed clock
                        "now": time.time()}

        self._defer(d, run)

    def h_list_profile_events(self, conn, p, d):
        def run():
            limit = p.get("limit", 10000)
            self._drain_event_queue()
            with self._events_lock:
                return list(self.profile_events[-limit:])

        self._defer(d, run)

    # -- distributed-trace span collector ---------------------------------

    def h_report_spans(self, conn, p):
        """Span ingest mirrors task-event ingest: batches queue here and
        a dedicated thread merges them per-trace off the RPC loop, so a
        burst of sampled traces never stalls lease scheduling.  Accepts
        one process batch ({"spans", "dropped", "common"}) or a relay
        envelope ({"batches": [...], "dropped": n}); the queue is
        bounded with drop-oldest accounting."""
        q = self._span_queue
        batches = p.get("batches")
        if batches is not None:
            if p.get("dropped"):
                with self._traces_lock:
                    self._spans_dropped += p["dropped"]
            q.extend(batches)
        else:
            q.append(p)
        while len(q) > self._span_queue_cap:
            try:
                old = q.popleft()
                with self._traces_lock:
                    self._spans_dropped += \
                        len(old.get("spans", ())) + old.get("dropped", 0)
            except IndexError:
                break
        self._span_signal.set()
        return True

    def _span_merge_loop(self):
        while not self._stop.is_set():
            self._span_signal.wait(0.5)
            self._span_signal.clear()
            self._drain_span_queue()
        self._drain_span_queue()  # final drain: keep pre-stop batches

    def _drain_span_queue(self):
        while self._span_queue:
            try:
                self._merge_spans(self._span_queue.popleft())
            except IndexError:
                break
            except Exception:
                logger.exception("span merge failed")

    def _merge_spans(self, p):
        """Fold one batch into the per-trace store, evict (LRU cap +
        idle TTL), then mirror touched traces into the _tracing KV
        namespace as pre-encoded JSON blobs — the encode happens outside
        self.lock, so the global lock is held only for dict updates."""
        common_fields = p.get("common") or {}
        proc = common_fields.get("proc")
        now = time.monotonic()
        with self._traces_lock:
            self._span_batches += 1
            self._spans_dropped += p.get("dropped", 0)
            touched = set()
            for sp in p.get("spans", []):
                tid = sp.get("trace_id")
                if not tid:
                    continue
                if proc and "proc" not in sp:
                    sp["proc"] = proc
                lst = self.trace_spans.get(tid)
                if lst is None:
                    lst = self.trace_spans[tid] = []
                if len(lst) >= self._trace_spans_per_trace:
                    self._trace_span_overflow += 1
                    continue
                lst.append(sp)
                self._spans_received += 1
                self._trace_index[tid] = now
                self._trace_index.move_to_end(tid)
                touched.add(tid)
            evicted = []
            while len(self._trace_index) > self._trace_store_cap:
                old, _ = self._trace_index.popitem(last=False)
                self.trace_spans.pop(old, None)
                evicted.append(old)
                self._traces_evicted += 1
            while self._trace_index:
                old, ts = next(iter(self._trace_index.items()))
                if now - ts <= self._trace_store_ttl_s:
                    break
                self._trace_index.popitem(last=False)
                self.trace_spans.pop(old, None)
                evicted.append(old)
                self._traces_evicted += 1
            blobs = {tid: json.dumps(self.trace_spans[tid]).encode()
                     for tid in touched if tid in self.trace_spans}
        if not blobs and not evicted:
            return
        with self.lock:
            ns = self.kv.setdefault("_tracing", {})
            for tid, blob in blobs.items():
                ns[f"trace:{tid}"] = blob
            for tid in evicted:
                ns.pop(f"trace:{tid}", None)


def _standby_watch(peer: str, interval: float, misses_to_promote: int):
    """Block until the primary at `peer` is unreachable for
    `misses_to_promote` consecutive probes, then return (the caller
    promotes).  The warm-standby analog of the reference's GCS
    fault-tolerance supervisor: state is already on shared disk, so
    promotion is just 'load the store and start serving'."""
    from .protocol import Client

    host, port = peer.rsplit(":", 1)
    addr = (host, int(port))
    misses = 0
    logger.info("standby: watching primary at %s", peer)
    while True:
        try:
            cli = Client(addr, name="standby->primary", connect_timeout=2.0)
            try:
                cli.call("ping", timeout=2.0)
            finally:
                cli.close()
            misses = 0
        except Exception:
            misses += 1
            logger.warning("standby: primary probe failed (%d/%d)",
                           misses, misses_to_promote)
            if misses >= misses_to_promote:
                logger.warning("standby: promoting — primary declared dead")
                return
        time.sleep(interval)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--persist", default=None,
                    help="sqlite path for durable control-plane state "
                         "(GCS fault-tolerance equivalent)")
    ap.add_argument("--addr-file", default=None,
                    help="file to publish this control plane's address "
                         "in (the re-homing rendezvous for failover)")
    ap.add_argument("--standby-of", default=None, metavar="HOST:PORT",
                    help="run as a warm standby: watch the primary at "
                         "this address and take over (load the persisted "
                         "state, serve, rewrite --addr-file) when it "
                         "stops answering")
    ap.add_argument("--standby-interval", type=float, default=0.5)
    ap.add_argument("--standby-misses", type=int, default=3)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s control %(levelname)s %(message)s")
    if args.standby_of:
        if not args.persist:
            ap.error("--standby-of requires --persist (takeover state)")
        if not args.addr_file:
            ap.error("--standby-of requires --addr-file (re-homing)")
        _standby_watch(args.standby_of, args.standby_interval,
                       args.standby_misses)
    srv = ControlServer(args.host, args.port, persist_path=args.persist,
                        addr_file=args.addr_file)
    srv.start(block=True)


if __name__ == "__main__":
    main()
