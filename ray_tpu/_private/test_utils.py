"""Fault-injection utilities for chaos testing.

Reference parity: python/ray/_private/test_utils.py resource-killer
actors — ``NodeKillerBase`` (:1500), ``RayletKiller`` (:1536),
``WorkerKillerActor`` (:1597) — used by release/nightly chaos suites
(`setup_chaos.py --chaos KillRaylet|KillWorker`).  Same shape here:
killer actors run *inside* the cluster under test, pick victims from
cluster state, and record what they killed so tests can assert both
damage and recovery.
"""

from __future__ import annotations

import os
import random
import signal
import socket
import threading
import time
from typing import Any, Dict, List, Optional, Tuple


def resolve_chaos_seed(seed: Optional[int]) -> int:
    """Chaos-run reproducibility: RAY_TPU_CHAOS_SEED overrides any seed
    a test passed, so a failed chaos run can be replayed exactly; with no
    env and no explicit seed, one is drawn and (like every injector seed)
    printed at run() start so failures always name their seed."""
    env = os.environ.get("RAY_TPU_CHAOS_SEED")
    if env:
        try:
            return int(env)
        except ValueError:
            pass
    if seed is None:
        seed = random.Random().randrange(2 ** 31)
    return int(seed)


class KillerBase:
    """Periodically kills victims until stopped.  Subclasses implement
    ``_pick_victims`` and ``_kill_one``."""

    def __init__(self, kill_interval_s: float = 2.0,
                 max_to_kill: int = 3, seed: Optional[int] = None):
        self.kill_interval_s = kill_interval_s
        self.max_to_kill = max_to_kill
        self.killed: List[Dict[str, Any]] = []
        self.seed = resolve_chaos_seed(seed)
        self._rng = random.Random(self.seed)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- actor API ---------------------------------------------------------

    def run(self):
        """Start the kill loop (returns immediately; the loop runs on a
        thread so the actor stays responsive to stop()/get_total_killed)."""
        if self._thread is None:
            print(f"[chaos] {type(self).__name__} seed={self.seed} "
                  f"(rerun with RAY_TPU_CHAOS_SEED={self.seed})",
                  flush=True)
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()
        return True

    def stop_run(self):
        self._stop.set()
        return True

    def get_total_killed(self) -> List[Dict[str, Any]]:
        return list(self.killed)

    # -- internals ---------------------------------------------------------

    def _loop(self):
        while not self._stop.is_set() and len(self.killed) < self.max_to_kill:
            self._stop.wait(self.kill_interval_s)
            if self._stop.is_set():
                return
            victims = self._pick_victims()
            if not victims:
                continue
            victim = self._rng.choice(victims)
            try:
                if self._kill_one(victim):
                    self.killed.append(victim)
            except Exception:
                pass

    def _pick_victims(self) -> List[Dict[str, Any]]:
        raise NotImplementedError

    def _kill_one(self, victim: Dict[str, Any]) -> bool:
        raise NotImplementedError


class WorkerKiller(KillerBase):
    """SIGKILLs random leased task workers (reference:
    WorkerKillerActor).  Tasks with retries left are re-executed by
    their owners; the test asserts results stay correct."""

    def _pick_victims(self):
        from ray_tpu._private.api import current_core
        from ray_tpu.util.state.api import StateApiClient

        core = current_core()
        cli = StateApiClient("%s:%s" % core.control_addr)
        try:
            out = []
            for node_id, workers in cli.per_node("list_workers").items():
                for w in workers:
                    if w["state"] == "leased" and w.get("pid") \
                            and w["pid"] != os.getpid():
                        out.append({"kind": "worker", "pid": w["pid"],
                                    "worker_id": w["worker_id"],
                                    "node_id": node_id})
            return out
        finally:
            cli.close()

    def _kill_one(self, victim):
        os.kill(victim["pid"], signal.SIGKILL)
        return True


class RayletKiller(KillerBase):
    """Kills whole raylets (node failure; reference: RayletKiller).
    Only nodes without the protected label are eligible, so the node
    hosting this killer (and the driver's node) can be exempted."""

    def __init__(self, protect_node_ids: Optional[List[str]] = None,
                 **kwargs):
        super().__init__(**kwargs)
        self.protect = set(protect_node_ids or [])

    def _pick_victims(self):
        from ray_tpu._private.api import current_core

        core = current_core()
        nodes = core.control.call("get_nodes", timeout=10.0)
        out = []
        for n in nodes:
            if n["state"] != "ALIVE" or n["node_id"] in self.protect:
                continue
            out.append({"kind": "raylet", "node_id": n["node_id"],
                        "addr": tuple(n["addr"])})
        return out

    def _kill_one(self, victim):
        from ray_tpu._private.protocol import Client

        # ask the raylet for its own pid, then SIGKILL the process —
        # the control plane must detect the death via missed heartbeats
        try:
            cli = Client(victim["addr"], name="raylet-killer",
                         connect_timeout=2.0)
            info = cli.call("node_info", timeout=5.0)
            cli.close()
        except Exception:
            return False
        # node_info has no pid; kill via the session dir's worker table
        # is overkill — raylets are processes on this host in tests, so
        # resolve the listener's pid through /proc
        pid = _pid_listening_on(victim["addr"][1])
        if pid is None or pid == os.getpid():
            return False
        os.kill(pid, signal.SIGKILL)
        return True


def _pid_listening_on(port: int) -> Optional[int]:
    """Find the local pid listening on a TCP port (test-only; /proc)."""
    import re

    want = f":{port:04X}"
    inode = None
    try:
        with open("/proc/net/tcp") as f:
            for line in f:
                parts = line.split()
                if len(parts) > 9 and parts[3] == "0A" \
                        and parts[1].endswith(want.upper()):
                    inode = parts[9]
                    break
    except OSError:
        return None
    if inode is None:
        return None
    for pid in os.listdir("/proc"):
        if not pid.isdigit():
            continue
        fd_dir = f"/proc/{pid}/fd"
        try:
            for fd in os.listdir(fd_dir):
                try:
                    if os.readlink(f"{fd_dir}/{fd}") == f"socket:[{inode}]":
                        return int(pid)
                except OSError:
                    continue
        except OSError:
            continue
    return None


# ---------------------------------------------------------------------------
# Network fault injection (partitions, not process kills)
# ---------------------------------------------------------------------------


class SocketProxy:
    """TCP forwarding proxy for network fault injection.

    Sits between a client population and a real server: point the clients
    at ``proxy.addr`` and the proxy relays byte streams to ``target``.
    ``sever()`` drops every live link and refuses new ones — connects are
    accepted then immediately closed, so peers observe a reset rather
    than a hang — until ``resume()``; ``set_delay()`` adds per-chunk
    forwarding latency.  This is how tests partition raylet<->control and
    client<->control without touching the processes themselves.
    """

    def __init__(self, target: Tuple[str, int], host: str = "127.0.0.1"):
        self.target = tuple(target)
        self._listen = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listen.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listen.bind((host, 0))
        self._listen.listen(64)
        self.addr: Tuple[str, int] = self._listen.getsockname()
        self._severed = threading.Event()
        self._delay = 0.0
        self._lock = threading.Lock()
        self._links: set = set()
        self._closed = False
        self.drop_count = 0
        threading.Thread(target=self._accept_loop, name="socket-proxy",
                         daemon=True).start()

    def _accept_loop(self):
        while not self._closed:
            try:
                s, _ = self._listen.accept()
            except OSError:
                return
            if self._closed or self._severed.is_set():
                try:
                    s.close()
                except OSError:
                    pass
                continue
            try:
                up = socket.create_connection(self.target, timeout=5.0)
            except OSError:
                try:
                    s.close()
                except OSError:
                    pass
                continue
            for sock in (s, up):
                try:
                    sock.setsockopt(socket.IPPROTO_TCP,
                                    socket.TCP_NODELAY, 1)
                except OSError:
                    pass
            with self._lock:
                self._links.add(s)
                self._links.add(up)
            for src, dst in ((s, up), (up, s)):
                threading.Thread(target=self._pump, args=(src, dst),
                                 daemon=True).start()

    def _pump(self, src, dst):
        try:
            while True:
                data = src.recv(1 << 16)
                if not data:
                    break
                if self._delay:
                    time.sleep(self._delay)
                dst.sendall(data)
        except OSError:
            pass
        finally:
            with self._lock:
                self._links.discard(src)
                self._links.discard(dst)
            for sock in (src, dst):
                try:
                    sock.close()
                except OSError:
                    pass

    def sever(self):
        """Open the partition: kill live links, refuse new ones."""
        self._severed.set()
        self.drop_count += 1
        with self._lock:
            links = list(self._links)
            self._links.clear()
        for sock in links:
            try:
                sock.close()
            except OSError:
                pass

    def resume(self):
        """Heal the partition: new connections forward again."""
        self._severed.clear()

    @property
    def severed(self) -> bool:
        return self._severed.is_set()

    def set_delay(self, seconds: float):
        self._delay = max(0.0, float(seconds))

    def close(self):
        self._closed = True
        try:
            self._listen.close()
        except OSError:
            pass
        self.sever()
        self._severed.clear()


class ConnectionDropper:
    """Scoped connection drop over a SocketProxy: a context manager that
    severs on enter and resumes on exit, plus a timed ``drop()`` for
    fire-and-forget blips."""

    def __init__(self, proxy: SocketProxy):
        self.proxy = proxy

    def __enter__(self) -> "ConnectionDropper":
        self.proxy.sever()
        return self

    def __exit__(self, *exc) -> bool:
        self.proxy.resume()
        return False

    def drop(self, duration_s: float) -> threading.Timer:
        self.proxy.sever()
        t = threading.Timer(duration_s, self.proxy.resume)
        t.daemon = True
        t.start()
        return t


class PartitionInjector:
    """Flaps SocketProxy links on a seeded schedule — the network-fault
    sibling of the killers (sever, hold, resume, repeat), with the same
    run()/stop_run()/get_total_killed() surface so chaos tests drive
    both kinds of injector identically.  Honors RAY_TPU_CHAOS_SEED."""

    def __init__(self, proxies, interval_s: float = 1.0,
                 drop_duration_s: float = 0.5, max_drops: int = 3,
                 seed: Optional[int] = None, delay_s: float = 0.0):
        if isinstance(proxies, SocketProxy):
            proxies = [proxies]
        self.proxies = list(proxies)
        self.interval_s = interval_s
        self.drop_duration_s = drop_duration_s
        self.max_drops = max_drops
        self.delay_s = delay_s
        self.seed = resolve_chaos_seed(seed)
        self._rng = random.Random(self.seed)
        self.dropped: List[Dict[str, Any]] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def run(self):
        if self._thread is None:
            print(f"[chaos] PartitionInjector seed={self.seed} "
                  f"(rerun with RAY_TPU_CHAOS_SEED={self.seed})",
                  flush=True)
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()
        return True

    def stop_run(self):
        self._stop.set()
        for p in self.proxies:
            p.resume()  # never leave the cluster partitioned
        return True

    def get_total_killed(self) -> List[Dict[str, Any]]:
        return list(self.dropped)

    def _loop(self):
        while not self._stop.is_set() \
                and len(self.dropped) < self.max_drops:
            # jittered schedule, fully determined by the seed
            self._stop.wait(self._rng.uniform(0.5, 1.5) * self.interval_s)
            if self._stop.is_set():
                return
            victim = self._rng.choice(self.proxies)
            hold = self._rng.uniform(0.5, 1.5) * self.drop_duration_s
            if self.delay_s:
                victim.set_delay(self.delay_s)
            victim.sever()
            self._stop.wait(hold)
            victim.resume()
            victim.set_delay(0.0)
            self.dropped.append({"kind": "partition",
                                 "target": victim.target,
                                 "held_s": round(hold, 3)})


def get_and_run_killer(killer_cls, *, kill_interval_s: float = 2.0,
                       max_to_kill: int = 3, seed: Optional[int] = None,
                       **actor_kwargs):
    """Spawn the killer as a 0-CPU actor and start its loop (reference:
    setup_chaos.py get_and_run_resource_killer)."""
    import ray_tpu

    KillerActor = ray_tpu.remote(killer_cls)
    killer = KillerActor.options(num_cpus=0, max_concurrency=4).remote(
        kill_interval_s=kill_interval_s, max_to_kill=max_to_kill,
        seed=seed, **actor_kwargs)
    ray_tpu.get(killer.run.remote(), timeout=60)
    return killer
