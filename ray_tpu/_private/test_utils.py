"""Fault-injection utilities for chaos testing.

Reference parity: python/ray/_private/test_utils.py resource-killer
actors — ``NodeKillerBase`` (:1500), ``RayletKiller`` (:1536),
``WorkerKillerActor`` (:1597) — used by release/nightly chaos suites
(`setup_chaos.py --chaos KillRaylet|KillWorker`).  Same shape here:
killer actors run *inside* the cluster under test, pick victims from
cluster state, and record what they killed so tests can assert both
damage and recovery.
"""

from __future__ import annotations

import os
import random
import signal
import threading
import time
from typing import Any, Dict, List, Optional


class KillerBase:
    """Periodically kills victims until stopped.  Subclasses implement
    ``_pick_victims`` and ``_kill_one``."""

    def __init__(self, kill_interval_s: float = 2.0,
                 max_to_kill: int = 3, seed: Optional[int] = None):
        self.kill_interval_s = kill_interval_s
        self.max_to_kill = max_to_kill
        self.killed: List[Dict[str, Any]] = []
        self._rng = random.Random(seed)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- actor API ---------------------------------------------------------

    def run(self):
        """Start the kill loop (returns immediately; the loop runs on a
        thread so the actor stays responsive to stop()/get_total_killed)."""
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()
        return True

    def stop_run(self):
        self._stop.set()
        return True

    def get_total_killed(self) -> List[Dict[str, Any]]:
        return list(self.killed)

    # -- internals ---------------------------------------------------------

    def _loop(self):
        while not self._stop.is_set() and len(self.killed) < self.max_to_kill:
            self._stop.wait(self.kill_interval_s)
            if self._stop.is_set():
                return
            victims = self._pick_victims()
            if not victims:
                continue
            victim = self._rng.choice(victims)
            try:
                if self._kill_one(victim):
                    self.killed.append(victim)
            except Exception:
                pass

    def _pick_victims(self) -> List[Dict[str, Any]]:
        raise NotImplementedError

    def _kill_one(self, victim: Dict[str, Any]) -> bool:
        raise NotImplementedError


class WorkerKiller(KillerBase):
    """SIGKILLs random leased task workers (reference:
    WorkerKillerActor).  Tasks with retries left are re-executed by
    their owners; the test asserts results stay correct."""

    def _pick_victims(self):
        from ray_tpu._private.api import current_core
        from ray_tpu.util.state.api import StateApiClient

        core = current_core()
        cli = StateApiClient("%s:%s" % core.control_addr)
        try:
            out = []
            for node_id, workers in cli.per_node("list_workers").items():
                for w in workers:
                    if w["state"] == "leased" and w.get("pid") \
                            and w["pid"] != os.getpid():
                        out.append({"kind": "worker", "pid": w["pid"],
                                    "worker_id": w["worker_id"],
                                    "node_id": node_id})
            return out
        finally:
            cli.close()

    def _kill_one(self, victim):
        os.kill(victim["pid"], signal.SIGKILL)
        return True


class RayletKiller(KillerBase):
    """Kills whole raylets (node failure; reference: RayletKiller).
    Only nodes without the protected label are eligible, so the node
    hosting this killer (and the driver's node) can be exempted."""

    def __init__(self, protect_node_ids: Optional[List[str]] = None,
                 **kwargs):
        super().__init__(**kwargs)
        self.protect = set(protect_node_ids or [])

    def _pick_victims(self):
        from ray_tpu._private.api import current_core

        core = current_core()
        nodes = core.control.call("get_nodes", timeout=10.0)
        out = []
        for n in nodes:
            if n["state"] != "ALIVE" or n["node_id"] in self.protect:
                continue
            out.append({"kind": "raylet", "node_id": n["node_id"],
                        "addr": tuple(n["addr"])})
        return out

    def _kill_one(self, victim):
        from ray_tpu._private.protocol import Client

        # ask the raylet for its own pid, then SIGKILL the process —
        # the control plane must detect the death via missed heartbeats
        try:
            cli = Client(victim["addr"], name="raylet-killer",
                         connect_timeout=2.0)
            info = cli.call("node_info", timeout=5.0)
            cli.close()
        except Exception:
            return False
        # node_info has no pid; kill via the session dir's worker table
        # is overkill — raylets are processes on this host in tests, so
        # resolve the listener's pid through /proc
        pid = _pid_listening_on(victim["addr"][1])
        if pid is None or pid == os.getpid():
            return False
        os.kill(pid, signal.SIGKILL)
        return True


def _pid_listening_on(port: int) -> Optional[int]:
    """Find the local pid listening on a TCP port (test-only; /proc)."""
    import re

    want = f":{port:04X}"
    inode = None
    try:
        with open("/proc/net/tcp") as f:
            for line in f:
                parts = line.split()
                if len(parts) > 9 and parts[3] == "0A" \
                        and parts[1].endswith(want.upper()):
                    inode = parts[9]
                    break
    except OSError:
        return None
    if inode is None:
        return None
    for pid in os.listdir("/proc"):
        if not pid.isdigit():
            continue
        fd_dir = f"/proc/{pid}/fd"
        try:
            for fd in os.listdir(fd_dir):
                try:
                    if os.readlink(f"{fd_dir}/{fd}") == f"socket:[{inode}]":
                        return int(pid)
                except OSError:
                    continue
        except OSError:
            continue
    return None


def get_and_run_killer(killer_cls, *, kill_interval_s: float = 2.0,
                       max_to_kill: int = 3, seed: Optional[int] = None,
                       **actor_kwargs):
    """Spawn the killer as a 0-CPU actor and start its loop (reference:
    setup_chaos.py get_and_run_resource_killer)."""
    import ray_tpu

    KillerActor = ray_tpu.remote(killer_cls)
    killer = KillerActor.options(num_cpus=0, max_concurrency=4).remote(
        kill_interval_s=kill_interval_s, max_to_kill=max_to_kill,
        seed=seed, **actor_kwargs)
    ray_tpu.get(killer.run.remote(), timeout=60)
    return killer
